"""Per-program resource accounting: who is spending this box's time.

PR 6 made the box multi-tenant (per-program engines behind one endpoint)
but left the observability plane tenant-blind where it matters: the
metrics catalog says how busy the machine is, nothing says WHICH program
made it busy.  Admission control and fleet health scoring (ROADMAP: per-
tenant quotas, replicated fleet) both need per-tenant cost signals; this
module is that ledger.

Four accumulators per program (label = the registry program name; the
pre-registry single-program surface accounts under "default"):

  requests / values   what entered the compute lanes
  cpu_seconds         fused-pass wall time, split across the requests a
                      pass served by slot share (each request's share is
                      pass_wall * its_values / pass_values) — so the sum
                      over programs equals the total fused-pass wall time
                      by construction, which the conservation test pins
                      (tests/test_usage.py, within 5%)
  native_seconds      MEASURED time in the C++ pool attributed to this
                      program's passes, from the per-thread busy-ns
                      counters native/interpreter.cpp maintains (NOT a
                      Python-side wall-clock inference); conservation vs
                      pool busy-ns pinned within 10%
  queue_seconds       time requests waited ahead of their first dispatch
                      (serve-scheduler queue delay + direct-lane slot
                      waits) — the contention signal quotas act on

Surfaces: ``GET /debug/usage`` (this module's debug_payload), a `usage`
block per program in ``GET /programs`` listings (runtime/registry.py),
and ``misaka_usage_*`` counters on GET /metrics — program-labeled, with
the same cardinality guard discipline as the registry series (an
unauthenticated upload flood collapses to program="other").

The module also owns the per-request *program context* (a contextvar the
registry lease sets): ``current_program()`` is how the structured logs
(utils/jsonlog.py) stamp a `program` field next to `trace_id`, closing
the log <-> trace <-> tenant correlation loop in one grep.

Kill switch: ``MISAKA_USAGE=0`` turns every record call into a no-op
(the ABBA overhead gate in bench.py --usage-ab runs with it on AND off).
Stdlib-only, like metrics/tracespan/jsonlog — importable anywhere.
"""

from __future__ import annotations

import contextlib
import contextvars
import hmac
import json
import os
import threading
import time

from misaka_tpu.utils import metrics

DEFAULT_LABEL = "default"

# Accounts that always resolve verbatim, cardinality cap or not: the
# synthetic canary (runtime/canary.py) books its probe traffic here so
# no REAL tenant is ever billed for it — collapsing it into "other"
# under label pressure would silently re-mix probe cost into a bucket
# billing exports treat as tenant traffic.  Conservation still holds:
# _canary's seconds are in both the per-program sum and the pass-wall
# anchor, and exports exclude the account wholesale by name.
EXEMPT_LABELS = ("_canary",)

# One counter family per accumulator, program-labeled.  Children are
# resolved once per program (cached on the _Account) — the serve hot path
# must not pay a label-lookup dict walk per pass.
M_USAGE_REQS = metrics.counter(
    "misaka_usage_requests_total",
    "Compute requests accounted to a program by the usage ledger",
    ("program",),
)
M_USAGE_VALUES = metrics.counter(
    "misaka_usage_values_total",
    "Values accounted to a program by the usage ledger",
    ("program",),
)
M_USAGE_CPU = metrics.counter(
    "misaka_usage_cpu_seconds_total",
    "Fused-pass wall seconds attributed to a program (slot-share split; "
    "sums across programs to misaka_serve_pass_wall_seconds_total)",
    ("program",),
)
M_USAGE_NATIVE = metrics.counter(
    "misaka_usage_native_seconds_total",
    "Measured C++-pool busy seconds attributed to a program (from the "
    "native per-thread busy-ns counters)",
    ("program",),
)
M_USAGE_QUEUE = metrics.counter(
    "misaka_usage_queue_seconds_total",
    "Seconds requests of a program waited ahead of first dispatch "
    "(scheduler queue delay + direct-lane slot waits)",
    ("program",),
)
# The conservation anchor: total fused-pass wall time, accumulated at the
# pass sites themselves (NOT derived from the per-program splits — the
# tests compare the two to catch attribution that leaks or double-counts).
M_PASS_SECONDS = metrics.counter(
    "misaka_serve_pass_wall_seconds_total",
    "Total wall seconds of fused serve passes (all programs; the "
    "conservation anchor for misaka_usage_cpu_seconds_total)",
)


class _Account:
    """One program's accumulators + its resolved metric children."""

    __slots__ = ("label", "requests", "values", "cpu_seconds",
                 "native_seconds", "queue_seconds", "_lock",
                 "_m_reqs", "_m_values", "_m_cpu", "_m_native", "_m_queue")

    def __init__(self, label: str):
        self.label = label
        self.requests = 0
        self.values = 0
        self.cpu_seconds = 0.0
        self.native_seconds = 0.0
        self.queue_seconds = 0.0
        self._lock = threading.Lock()
        self._m_reqs = M_USAGE_REQS.labels(program=label)
        self._m_values = M_USAGE_VALUES.labels(program=label)
        self._m_cpu = M_USAGE_CPU.labels(program=label)
        self._m_native = M_USAGE_NATIVE.labels(program=label)
        self._m_queue = M_USAGE_QUEUE.labels(program=label)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "requests": self.requests,
                "values": self.values,
                "cpu_seconds": round(self.cpu_seconds, 6),
                "native_seconds": round(self.native_seconds, 6),
                "queue_seconds": round(self.queue_seconds, 6),
            }


_lock = threading.Lock()
_accounts: dict[str, _Account] = {}
_ENABLED = True


def configure(environ=os.environ) -> None:
    """(Re-)read MISAKA_USAGE (kill switch; default on).  Called at
    import; the bench A/B toggles it live."""
    global _ENABLED
    _ENABLED = environ.get("MISAKA_USAGE", "1") != "0"


configure()


def enabled() -> bool:
    return _ENABLED


def _label_budget() -> int:
    return metrics.tenant_label_budget()


def account(program: str | None) -> _Account:
    """The ledger for `program` (None -> "default"), creating it within
    the cardinality budget — past MISAKA_USAGE_LABEL_MAX distinct labels,
    new programs collapse into "other" (metrics.capped_label, the guard
    shared with the SLO windows and the registry's metric series)."""
    label = program or DEFAULT_LABEL
    acct = _accounts.get(label)
    if acct is not None:
        return acct
    with _lock:
        label = metrics.capped_label(
            _accounts, label, _label_budget(), exempt=EXEMPT_LABELS
        )
        acct = _accounts.get(label)
        if acct is None:
            acct = _accounts[label] = _Account(label)
    return acct


def add_request(program: str | None, values: int) -> None:
    if not _ENABLED:
        return
    a = account(program)
    with a._lock:
        a.requests += 1
        a.values += int(values)
    a._m_reqs.inc()
    a._m_values.inc(values)


def add_cpu(program: str | None, seconds: float) -> None:
    """One request's slot share of a fused pass's wall time."""
    if not _ENABLED or seconds <= 0:
        return
    a = account(program)
    with a._lock:
        a.cpu_seconds += seconds
    a._m_cpu.inc(seconds)


def add_native(program: str | None, seconds: float) -> None:
    """Measured C++-pool busy time (busy-ns counter delta) for one of
    this program's engine calls."""
    if not _ENABLED or seconds <= 0:
        return
    a = account(program)
    with a._lock:
        a.native_seconds += seconds
    a._m_native.inc(seconds)


def add_queue(program: str | None, seconds: float) -> None:
    if not _ENABLED or seconds <= 0:
        return
    a = account(program)
    with a._lock:
        a.queue_seconds += seconds
    a._m_queue.inc(seconds)


def note_pass(seconds: float) -> None:
    """Record one fused pass's total wall time into the conservation
    anchor (called at the pass site, independent of the per-program
    splits — so the conservation tests compare two real code paths)."""
    if not _ENABLED or seconds <= 0:
        return
    M_PASS_SECONDS.inc(seconds)


def pass_seconds_total() -> float:
    return M_PASS_SECONDS.value


def snapshot() -> dict[str, dict]:
    """{program: accumulators} for every program the ledger has seen."""
    with _lock:
        accounts = list(_accounts.values())
    return {a.label: a.snapshot() for a in accounts}


def program_snapshot(program: str) -> dict | None:
    """One program's accumulators, or None when it never served (the
    /programs listing must not mint ledger entries for idle programs)."""
    a = _accounts.get(program)
    return a.snapshot() if a is not None else None


def reset() -> None:
    """Tests: wipe the ledger (metric counters keep their process-
    cumulative Prometheus semantics and are delta'd by readers)."""
    with _lock:
        _accounts.clear()


def debug_payload() -> dict:
    """The GET /debug/usage body."""
    programs = snapshot()
    payload = {
        "enabled": _ENABLED,
        "programs": programs,
        "pass_seconds_total": round(pass_seconds_total(), 6),
        "cpu_seconds_total": round(
            sum(p["cpu_seconds"] for p in programs.values()), 6
        ),
    }
    if _spool is not None:
        # the durable ledger's restart-spanning view (base + live)
        payload["cumulative"] = cumulative_snapshot()
    try:
        # the live native pool's measured busy/idle split (None when no
        # pool is serving); lazy import — this module stays stdlib-only
        # for every process that never runs a native engine
        from misaka_tpu.core import native_serve

        pool = native_serve.pool_counters()
        if pool is not None:
            payload["native_pool"] = pool
    except Exception:  # pragma: no cover — the ledger must always answer
        pass
    return payload


# --- the durable ledger (billing-grade persistence + signed exports) --------
#
# With MISAKA_TSDB_DIR set, a flusher thread appends CUMULATIVE per-
# program counter frames (live accumulators + the base reloaded from the
# previous process's spool) to fsync'd segments under <dir>/usage — the
# same utils/spool.py discipline as TSDB retention.  Cumulative-by-
# construction means restart-safe monotonicity: a kill -9 loses at most
# the accrual since the last fsync'd frame, never regresses an exported
# number (GET /usage/export flushes before answering, so anything a
# billing scrape saw is on disk).  The conservation anchor
# (misaka_serve_pass_wall_seconds_total) rides the same frames, so
# cumulative cpu-vs-wall stays checkable across restarts.
#
# Export: JSONL, one line per (frame interval, program) delta plus a
# trailing cumulative totals line, each signed with HMAC-SHA256 over the
# canonical (sorted-keys) JSON minus the "sig" field, keyed by
# MISAKA_USAGE_SECRET (falling back to the plane secret — one fleet, one
# key). The synthetic canary's account is excluded from export lines by
# name (it is not tenant traffic) but stays inside the conservation
# totals, which cover ALL programs.

M_USAGE_SPOOL_DROPPED = metrics.counter(
    "misaka_usage_spool_dropped_total",
    "Usage ledger spool segments evicted by the MISAKA_USAGE_DISK_MB "
    "budget (billing periods older than the retained window are lost)",
)

FIELDS = ("requests", "values", "cpu_seconds", "native_seconds",
          "queue_seconds")


class UsageExportError(RuntimeError):
    """Unusable or tampered usage export content."""


_spool = None  # utils/spool.SegmentSpool once armed
_spool_lock = threading.Lock()
_base: dict[str, dict] = {}
_pass_base = 0.0
# live-counter values AT ARM TIME: cumulative = base + (live - offset),
# so accrual from before the spool armed (other servers in the same test
# process) is never double-counted against the reloaded base
_live_offset: dict[str, dict] = {}
_pass_offset = 0.0
_last_flushed: tuple | None = None
_flush_stop: threading.Event | None = None


def cumulative_snapshot() -> dict:
    """Base (reloaded from the previous process) + live accrual since
    the spool armed: the monotone counters the billing export
    publishes."""
    live = snapshot()
    programs: dict[str, dict] = {}
    for label in set(_base) | set(live):
        b = _base.get(label) or {}
        v = live.get(label) or {}
        o = _live_offset.get(label) or {}
        programs[label] = {
            f: round(
                float(b.get(f, 0)) + max(
                    0.0, float(v.get(f, 0)) - float(o.get(f, 0))
                ), 6,
            )
            for f in FIELDS
        }
    return {
        "programs": programs,
        "pass_wall_seconds": round(
            _pass_base + max(0.0, pass_seconds_total() - _pass_offset), 6
        ),
    }


def spool_dir(environ=os.environ) -> str | None:
    root = environ.get("MISAKA_TSDB_DIR")
    if not root or environ.get("MISAKA_USAGE_SPOOL", "1") == "0":
        return None
    return os.path.join(root, "usage")


def ensure_spool(environ=os.environ):
    """Arm the durable ledger (idempotent; None when MISAKA_TSDB_DIR is
    unset — today's in-memory behavior).  Reloads the newest retained
    frame as the cumulative base, writes a boot frame, and starts the
    periodic flusher."""
    global _spool, _pass_base, _pass_offset, _flush_stop
    d = spool_dir(environ)
    if d is None or not _ENABLED:
        return None
    with _spool_lock:
        if _spool is not None:
            return _spool
        from misaka_tpu.utils import spool as spool_mod
        from misaka_tpu.utils.tsdb import env_float

        sp = spool_mod.SegmentSpool(
            d, prefix="usage",
            budget_bytes=int(env_float(
                environ, "MISAKA_USAGE_DISK_MB", 16.0) * (1 << 20)),
            segment_bytes=int(env_float(
                environ, "MISAKA_USAGE_SEG_KB", 256.0) * 1024),
            on_evict=M_USAGE_SPOOL_DROPPED.inc,
            on_error=lambda: spool_mod.M_SPOOL_ERRORS.labels(
                plane="usage").inc(),
        )
        last: list = [None]

        def _keep_last(frame):
            if frame.get("k") == "usage":
                last[0] = frame

        sp.reload(_keep_last)
        if last[0] is not None:
            _base.clear()
            for label, row in (last[0].get("programs") or {}).items():
                _base[str(label)] = {
                    f: float(row.get(f, 0)) for f in FIELDS
                }
            _pass_base = float(last[0].get("pass_wall", 0.0))
        _live_offset.clear()
        _live_offset.update(snapshot())
        _pass_offset = pass_seconds_total()
        _spool = sp
        _flush_stop = threading.Event()
        interval = max(0.05, env_float(
            environ, "MISAKA_USAGE_FLUSH_S", 15.0))
        threading.Thread(
            target=_flush_loop, args=(_flush_stop, interval),
            daemon=True, name="misaka-usage-spool",
        ).start()
    flush_now(force=True)  # the boot frame: periods have a baseline
    return _spool


def _flush_loop(stop: threading.Event, interval: float) -> None:
    while not stop.wait(interval):
        try:
            flush_now()
        except Exception:  # pragma: no cover — billing flush must never
            pass           # take the process down


def flush_now(force: bool = False) -> bool:
    """Append one cumulative frame + fsync.  Identical consecutive
    frames are elided (an idle box must not grow its ledger), unless
    ``force`` (boot; the export path, which must include up-to-call
    accrual)."""
    global _last_flushed
    with _spool_lock:
        if _spool is None:
            return False
        snap = cumulative_snapshot()
        fingerprint = (
            snap["pass_wall_seconds"],
            tuple(sorted(
                (p, row["requests"], row["cpu_seconds"])
                for p, row in snap["programs"].items()
            )),
        )
        if not force and fingerprint == _last_flushed:
            return False
        _last_flushed = fingerprint
        _spool.append({
            "k": "usage",
            "t": round(time.time(), 3),
            "pass_wall": snap["pass_wall_seconds"],
            "programs": snap["programs"],
        })
        _spool.flush()
        return True


def shutdown_spool() -> None:
    """Tests: stop the flusher and drop the armed spool + bases."""
    global _spool, _pass_base, _pass_offset, _last_flushed, _flush_stop
    with _spool_lock:
        if _flush_stop is not None:
            _flush_stop.set()
            _flush_stop = None
        if _spool is not None:
            _spool.close()
            _spool = None
        _base.clear()
        _live_offset.clear()
        _pass_base = 0.0
        _pass_offset = 0.0
        _last_flushed = None


# --- signed JSONL export ----------------------------------------------------

def export_secret(environ=os.environ) -> bytes | None:
    """The HMAC key for export lines: MISAKA_USAGE_SECRET, else the
    plane secret (MISAKA_PLANE_SECRET / _FILE) — one fleet, one key.
    None -> exports go out unsigned (lines carry no "sig")."""
    s = environ.get("MISAKA_USAGE_SECRET") or environ.get(
        "MISAKA_PLANE_SECRET")
    if s:
        return s.encode()
    p = environ.get("MISAKA_PLANE_SECRET_FILE")
    if p:
        try:
            with open(p, "rb") as f:
                return f.read().strip() or None
        except OSError:
            return None
    return None


def _canonical(obj: dict) -> bytes:
    return json.dumps(
        {k: v for k, v in obj.items() if k != "sig"},
        sort_keys=True, separators=(",", ":"),
    ).encode()


def sign_line(obj: dict, secret: bytes) -> dict:
    obj["sig"] = hmac.new(secret, _canonical(obj), "sha256").hexdigest()
    return obj


def verify_line(obj: dict, secret: bytes) -> bool:
    sig = obj.get("sig")
    if not isinstance(sig, str):
        return False
    want = hmac.new(secret, _canonical(obj), "sha256").hexdigest()
    return hmac.compare_digest(sig, want)


def export_lines(since: float = 0.0, environ=os.environ) -> list[dict]:
    """The GET /usage/export body: per-(interval, program) delta lines
    between consecutive retained frames with end > ``since``, then one
    cumulative totals line.  Signed when a secret is configured.  With
    no spool armed, degrades to the single process-lifetime period."""
    frames: list[dict] = []
    with _spool_lock:
        sp = _spool
    if sp is not None:
        flush_now(force=True)
        sp.read_frames(
            lambda fr: frames.append(fr) if fr.get("k") == "usage" else None
        )
    if not frames:
        snap = cumulative_snapshot()
        frames = [
            {"t": 0.0, "pass_wall": 0.0, "programs": {}},
            {"t": round(time.time(), 3),
             "pass_wall": snap["pass_wall_seconds"],
             "programs": snap["programs"]},
        ]
    lines: list[dict] = []
    for prev, cur in zip(frames, frames[1:]):
        t1 = float(cur.get("t", 0.0))
        if t1 <= since:
            continue
        t0 = float(prev.get("t", 0.0))
        prev_p = prev.get("programs") or {}
        for label, row in sorted((cur.get("programs") or {}).items()):
            if label in EXEMPT_LABELS:
                continue  # probe traffic is not billable tenant usage
            before = prev_p.get(label) or {}
            deltas = {
                f: round(max(
                    0.0, float(row.get(f, 0)) - float(before.get(f, 0))
                ), 6)
                for f in FIELDS
            }
            if not any(deltas.values()):
                continue
            lines.append({
                "kind": "period", "start": round(t0, 3), "end": round(t1, 3),
                "program": label, **deltas,
                "cumulative": {f: float(row.get(f, 0)) for f in FIELDS},
            })
    last = frames[-1]
    programs = {
        label: {f: float(row.get(f, 0)) for f in FIELDS}
        for label, row in sorted((last.get("programs") or {}).items())
        if label not in EXEMPT_LABELS
    }
    lines.append({
        "kind": "totals",
        "asof": round(float(last.get("t", 0.0)), 3),
        "pass_wall_seconds": round(float(last.get("pass_wall", 0.0)), 6),
        "cpu_seconds_total": round(sum(
            float(row.get("cpu_seconds", 0))
            for row in (last.get("programs") or {}).values()
        ), 6),
        "programs": programs,
    })
    secret = export_secret(environ)
    if secret is not None:
        for obj in lines:
            sign_line(obj, secret)
    return lines


def totals_from_lines(lines, secret: bytes | None = None) -> dict:
    """Aggregate export lines (the usage-report CLI's core): verifies
    every period/totals line when a secret is given (UsageExportError
    on the first tampered line), sums period deltas per program, and
    carries the newest cumulative totals through."""
    deltas: dict[str, dict] = {}
    totals: dict | None = None
    periods = 0
    for i, obj in enumerate(lines):
        kind = obj.get("kind")
        if kind not in ("period", "totals"):
            continue  # hub envelope lines (kind=source/gossip) pass through
        if secret is not None and not verify_line(obj, secret):
            raise UsageExportError(
                f"line {i} ({kind}) failed HMAC verification — tampered "
                f"or signed with a different secret"
            )
        if kind == "period":
            periods += 1
            row = deltas.setdefault(
                obj.get("program") or DEFAULT_LABEL,
                {f: 0.0 for f in FIELDS},
            )
            for f in FIELDS:
                row[f] += float(obj.get(f, 0))
        elif totals is None or float(obj.get("asof", 0)) >= \
                float(totals.get("asof", 0)):
            totals = obj
    return {
        "verified": secret is not None,
        "periods": periods,
        "programs": {
            p: {f: round(v, 6) for f, v in row.items()}
            for p, row in sorted(deltas.items())
        },
        "cumulative": (totals or {}).get("programs") or {},
        "pass_wall_seconds": float(
            (totals or {}).get("pass_wall_seconds", 0.0)),
        "cpu_seconds_total": float(
            (totals or {}).get("cpu_seconds_total", 0.0)),
    }


# --- the per-request program context (jsonlog's `program` field) ------------

_current: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "misaka_program", default=None
)


def current_program() -> str | None:
    """The program the EMITTING thread is serving (set by the registry
    lease / HTTP handlers) — utils/jsonlog.py stamps it next to trace_id
    so log <-> trace <-> tenant correlation is one grep."""
    return _current.get()


@contextlib.contextmanager
def program_scope(program: str | None):
    """Make `program` current for a request's lifetime (no-op on None)."""
    if program is None:
        yield
        return
    token = _current.set(program)
    try:
        yield
    finally:
        _current.reset(token)
