"""Per-program resource accounting: who is spending this box's time.

PR 6 made the box multi-tenant (per-program engines behind one endpoint)
but left the observability plane tenant-blind where it matters: the
metrics catalog says how busy the machine is, nothing says WHICH program
made it busy.  Admission control and fleet health scoring (ROADMAP: per-
tenant quotas, replicated fleet) both need per-tenant cost signals; this
module is that ledger.

Four accumulators per program (label = the registry program name; the
pre-registry single-program surface accounts under "default"):

  requests / values   what entered the compute lanes
  cpu_seconds         fused-pass wall time, split across the requests a
                      pass served by slot share (each request's share is
                      pass_wall * its_values / pass_values) — so the sum
                      over programs equals the total fused-pass wall time
                      by construction, which the conservation test pins
                      (tests/test_usage.py, within 5%)
  native_seconds      MEASURED time in the C++ pool attributed to this
                      program's passes, from the per-thread busy-ns
                      counters native/interpreter.cpp maintains (NOT a
                      Python-side wall-clock inference); conservation vs
                      pool busy-ns pinned within 10%
  queue_seconds       time requests waited ahead of their first dispatch
                      (serve-scheduler queue delay + direct-lane slot
                      waits) — the contention signal quotas act on

Surfaces: ``GET /debug/usage`` (this module's debug_payload), a `usage`
block per program in ``GET /programs`` listings (runtime/registry.py),
and ``misaka_usage_*`` counters on GET /metrics — program-labeled, with
the same cardinality guard discipline as the registry series (an
unauthenticated upload flood collapses to program="other").

The module also owns the per-request *program context* (a contextvar the
registry lease sets): ``current_program()`` is how the structured logs
(utils/jsonlog.py) stamp a `program` field next to `trace_id`, closing
the log <-> trace <-> tenant correlation loop in one grep.

Kill switch: ``MISAKA_USAGE=0`` turns every record call into a no-op
(the ABBA overhead gate in bench.py --usage-ab runs with it on AND off).
Stdlib-only, like metrics/tracespan/jsonlog — importable anywhere.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import threading

from misaka_tpu.utils import metrics

DEFAULT_LABEL = "default"

# Accounts that always resolve verbatim, cardinality cap or not: the
# synthetic canary (runtime/canary.py) books its probe traffic here so
# no REAL tenant is ever billed for it — collapsing it into "other"
# under label pressure would silently re-mix probe cost into a bucket
# billing exports treat as tenant traffic.  Conservation still holds:
# _canary's seconds are in both the per-program sum and the pass-wall
# anchor, and exports exclude the account wholesale by name.
EXEMPT_LABELS = ("_canary",)

# One counter family per accumulator, program-labeled.  Children are
# resolved once per program (cached on the _Account) — the serve hot path
# must not pay a label-lookup dict walk per pass.
M_USAGE_REQS = metrics.counter(
    "misaka_usage_requests_total",
    "Compute requests accounted to a program by the usage ledger",
    ("program",),
)
M_USAGE_VALUES = metrics.counter(
    "misaka_usage_values_total",
    "Values accounted to a program by the usage ledger",
    ("program",),
)
M_USAGE_CPU = metrics.counter(
    "misaka_usage_cpu_seconds_total",
    "Fused-pass wall seconds attributed to a program (slot-share split; "
    "sums across programs to misaka_serve_pass_wall_seconds_total)",
    ("program",),
)
M_USAGE_NATIVE = metrics.counter(
    "misaka_usage_native_seconds_total",
    "Measured C++-pool busy seconds attributed to a program (from the "
    "native per-thread busy-ns counters)",
    ("program",),
)
M_USAGE_QUEUE = metrics.counter(
    "misaka_usage_queue_seconds_total",
    "Seconds requests of a program waited ahead of first dispatch "
    "(scheduler queue delay + direct-lane slot waits)",
    ("program",),
)
# The conservation anchor: total fused-pass wall time, accumulated at the
# pass sites themselves (NOT derived from the per-program splits — the
# tests compare the two to catch attribution that leaks or double-counts).
M_PASS_SECONDS = metrics.counter(
    "misaka_serve_pass_wall_seconds_total",
    "Total wall seconds of fused serve passes (all programs; the "
    "conservation anchor for misaka_usage_cpu_seconds_total)",
)


class _Account:
    """One program's accumulators + its resolved metric children."""

    __slots__ = ("label", "requests", "values", "cpu_seconds",
                 "native_seconds", "queue_seconds", "_lock",
                 "_m_reqs", "_m_values", "_m_cpu", "_m_native", "_m_queue")

    def __init__(self, label: str):
        self.label = label
        self.requests = 0
        self.values = 0
        self.cpu_seconds = 0.0
        self.native_seconds = 0.0
        self.queue_seconds = 0.0
        self._lock = threading.Lock()
        self._m_reqs = M_USAGE_REQS.labels(program=label)
        self._m_values = M_USAGE_VALUES.labels(program=label)
        self._m_cpu = M_USAGE_CPU.labels(program=label)
        self._m_native = M_USAGE_NATIVE.labels(program=label)
        self._m_queue = M_USAGE_QUEUE.labels(program=label)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "requests": self.requests,
                "values": self.values,
                "cpu_seconds": round(self.cpu_seconds, 6),
                "native_seconds": round(self.native_seconds, 6),
                "queue_seconds": round(self.queue_seconds, 6),
            }


_lock = threading.Lock()
_accounts: dict[str, _Account] = {}
_ENABLED = True


def configure(environ=os.environ) -> None:
    """(Re-)read MISAKA_USAGE (kill switch; default on).  Called at
    import; the bench A/B toggles it live."""
    global _ENABLED
    _ENABLED = environ.get("MISAKA_USAGE", "1") != "0"


configure()


def enabled() -> bool:
    return _ENABLED


def _label_budget() -> int:
    return metrics.tenant_label_budget()


def account(program: str | None) -> _Account:
    """The ledger for `program` (None -> "default"), creating it within
    the cardinality budget — past MISAKA_USAGE_LABEL_MAX distinct labels,
    new programs collapse into "other" (metrics.capped_label, the guard
    shared with the SLO windows and the registry's metric series)."""
    label = program or DEFAULT_LABEL
    acct = _accounts.get(label)
    if acct is not None:
        return acct
    with _lock:
        label = metrics.capped_label(
            _accounts, label, _label_budget(), exempt=EXEMPT_LABELS
        )
        acct = _accounts.get(label)
        if acct is None:
            acct = _accounts[label] = _Account(label)
    return acct


def add_request(program: str | None, values: int) -> None:
    if not _ENABLED:
        return
    a = account(program)
    with a._lock:
        a.requests += 1
        a.values += int(values)
    a._m_reqs.inc()
    a._m_values.inc(values)


def add_cpu(program: str | None, seconds: float) -> None:
    """One request's slot share of a fused pass's wall time."""
    if not _ENABLED or seconds <= 0:
        return
    a = account(program)
    with a._lock:
        a.cpu_seconds += seconds
    a._m_cpu.inc(seconds)


def add_native(program: str | None, seconds: float) -> None:
    """Measured C++-pool busy time (busy-ns counter delta) for one of
    this program's engine calls."""
    if not _ENABLED or seconds <= 0:
        return
    a = account(program)
    with a._lock:
        a.native_seconds += seconds
    a._m_native.inc(seconds)


def add_queue(program: str | None, seconds: float) -> None:
    if not _ENABLED or seconds <= 0:
        return
    a = account(program)
    with a._lock:
        a.queue_seconds += seconds
    a._m_queue.inc(seconds)


def note_pass(seconds: float) -> None:
    """Record one fused pass's total wall time into the conservation
    anchor (called at the pass site, independent of the per-program
    splits — so the conservation tests compare two real code paths)."""
    if not _ENABLED or seconds <= 0:
        return
    M_PASS_SECONDS.inc(seconds)


def pass_seconds_total() -> float:
    return M_PASS_SECONDS.value


def snapshot() -> dict[str, dict]:
    """{program: accumulators} for every program the ledger has seen."""
    with _lock:
        accounts = list(_accounts.values())
    return {a.label: a.snapshot() for a in accounts}


def program_snapshot(program: str) -> dict | None:
    """One program's accumulators, or None when it never served (the
    /programs listing must not mint ledger entries for idle programs)."""
    a = _accounts.get(program)
    return a.snapshot() if a is not None else None


def reset() -> None:
    """Tests: wipe the ledger (metric counters keep their process-
    cumulative Prometheus semantics and are delta'd by readers)."""
    with _lock:
        _accounts.clear()


def debug_payload() -> dict:
    """The GET /debug/usage body."""
    programs = snapshot()
    payload = {
        "enabled": _ENABLED,
        "programs": programs,
        "pass_seconds_total": round(pass_seconds_total(), 6),
        "cpu_seconds_total": round(
            sum(p["cpu_seconds"] for p in programs.values()), 6
        ),
    }
    try:
        # the live native pool's measured busy/idle split (None when no
        # pool is serving); lazy import — this module stays stdlib-only
        # for every process that never runs a native engine
        from misaka_tpu.core import native_serve

        pool = native_serve.pool_counters()
        if pool is not None:
            payload["native_pool"] = pool
    except Exception:  # pragma: no cover — the ledger must always answer
        pass
    return payload


# --- the per-request program context (jsonlog's `program` field) ------------

_current: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "misaka_program", default=None
)


def current_program() -> str | None:
    """The program the EMITTING thread is serving (set by the registry
    lease / HTTP handlers) — utils/jsonlog.py stamps it next to trace_id
    so log <-> trace <-> tenant correlation is one grep."""
    return _current.get()


@contextlib.contextmanager
def program_scope(program: str | None):
    """Make `program` current for a request's lifetime (no-op on None)."""
    if program is None:
        yield
        return
    token = _current.set(program)
    try:
        yield
    finally:
        _current.reset(token)
