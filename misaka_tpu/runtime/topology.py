"""Network topology: the declarative config that compiles to kernel tables.

The reference scatters topology across three places that must agree by hand:
the master's NODE_INFO JSON (cmd/app.go:30-35), per-container PROGRAM env vars
(docker-compose.yml:35-59), and the TLS cert SAN list (openssl/certificate.conf:18-23).
Here one `Topology` object owns it all and lowers to the dense tables the
superstep kernel consumes.  The NODE_INFO JSON shape (`{name: {"type": ...}}`,
master.go:24-26) is accepted verbatim for drop-in compatibility.

Lane/stack ids are assigned in declaration order; that order is also the
deterministic arbitration priority (core/step.py) — document it, rely on it.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

import numpy as np

from misaka_tpu.core.engine import CompiledNetwork
from misaka_tpu.tis.lower import DEFAULT_PROGRAM, pad_programs
from misaka_tpu.tis.native import assemble


class TopologyError(ValueError):
    pass


@dataclass
class Topology:
    """Node declarations + per-program-node source text."""

    node_info: dict[str, str]                    # name -> "program" | "stack"
    programs: dict[str, str] = field(default_factory=dict)
    stack_cap: int = 1024
    in_cap: int = 1024
    out_cap: int = 1024

    def __post_init__(self):
        # Never mutate caller-supplied dicts (setdefault below fills gaps).
        self.node_info = dict(self.node_info)
        self.programs = dict(self.programs)
        for name, kind in self.node_info.items():
            if kind not in ("program", "stack"):
                raise TopologyError(f"invalid node type '{kind}' for node '{name}'")
        unknown = set(self.programs) - set(self.lane_ids())
        if unknown:
            raise TopologyError(
                f"programs given for non-program nodes: {sorted(unknown)}"
            )
        # Every program node runs something; a fresh node runs NOP
        # (program.go:64).
        for name in self.lane_ids():
            self.programs.setdefault(name, DEFAULT_PROGRAM)

    @classmethod
    def from_node_info_json(cls, node_info_json: str, programs: dict[str, str] | None = None, **kw) -> "Topology":
        """Accept the reference's NODE_INFO JSON shape (master.go:24-26)."""
        raw = json.loads(node_info_json)
        return cls(
            node_info={name: spec["type"] for name, spec in raw.items()},
            programs=dict(programs or {}),
            **kw,
        )

    @classmethod
    def from_json(cls, text: str, **kw) -> "Topology":
        """Single declarative file: {"nodes": {name: type}, "programs": {name: text}}."""
        raw = json.loads(text)
        return cls(node_info=dict(raw["nodes"]), programs=dict(raw.get("programs", {})), **kw)

    def lane_ids(self) -> dict[str, int]:
        return {
            name: i
            for i, name in enumerate(
                n for n, kind in self.node_info.items() if kind == "program"
            )
        }

    def stack_ids(self) -> dict[str, int]:
        return {
            name: i
            for i, name in enumerate(
                n for n, kind in self.node_info.items() if kind == "stack"
            )
        }

    def with_program(self, target: str, program: str) -> "Topology":
        """A copy with one node reprogrammed (the /load path, master.go:145-195)."""
        if target not in self.node_info:
            raise TopologyError(f"node {target} not valid on this network")
        if self.node_info[target] != "program":
            raise TopologyError(f"node {target} is not a program node")
        new_programs = dict(self.programs)
        new_programs[target] = program
        return Topology(
            node_info=dict(self.node_info),
            programs=new_programs,
            stack_cap=self.stack_cap,
            in_cap=self.in_cap,
            out_cap=self.out_cap,
        )

    def compile(self, batch: int | None = None) -> CompiledNetwork:
        """Lower every node's program and bind the superstep engine."""
        lane_ids = self.lane_ids()
        if not lane_ids:
            raise TopologyError("network has no program nodes")
        stack_ids = self.stack_ids()
        # `assemble` uses the native C++ assembler when built (make native),
        # falling back to the pure-Python frontend; outputs are parity-tested
        # identical (tests/test_native.py).
        lowered = [
            assemble(self.programs[name], lane_ids, stack_ids)
            for name in lane_ids
        ]
        code, lengths = pad_programs(lowered)
        return CompiledNetwork(
            code=code,
            prog_len=np.asarray(lengths, np.int32),
            num_stacks=max(1, len(stack_ids)),
            stack_cap=self.stack_cap,
            in_cap=self.in_cap,
            out_cap=self.out_cap,
            batch=batch,
        )
