"""Process-lifecycle guards: no server may outlive its operator.

Round-3 post-mortem (VERDICT.md weak #1): three `misaka_tpu.runtime.app`
servers launched from interactive shells survived their shells by days and
wedged the one attached TPU chip — the relay admits a single client, so a
forgotten server makes every later `jax.devices()` hang.  The reference never
hits this because its nodes live inside docker-compose, whose `down` is the
lifecycle guard (docker-compose.yml:1-74).  A bare process needs the
equivalent built in:

  * SIGTERM/SIGINT    -> stop the device loop, then exit 0 (deterministic
                         release of the chip and the HTTP socket)
  * atexit            -> same stop on any normal interpreter exit
  * orphan watchdog   -> if the parent process dies (getppid() changes), the
                         server exits: a server backgrounded from a shell
                         dies with the shell instead of leaking.  Opt out for
                         deliberate daemons with MISAKA_ORPHAN_OK=1; auto-off
                         when already init-parented at startup (container
                         PID-1 style deployments)
  * MISAKA_TTL_S=N    -> hard deadline: stop + exit after N seconds no
                         matter what (belt-and-braces for CI/bench drivers)

`make stop` (Makefile) is the manual backstop that pkills stragglers.
"""

from __future__ import annotations

import atexit
import logging
import os
import signal
import threading

_log = logging.getLogger("misaka_tpu.lifecycle")
_POLL_S = 2.0


def arm_boot_handlers() -> None:
    """Provisional SIGTERM/SIGINT handlers for the boot window.

    Server entrypoints call this BEFORE their heavy jax imports: a signal
    that lands mid-boot must still exit clean (0 / 130) — nothing holds the
    chip yet, and the operator contract (TERM => orderly exit) starts at
    exec, not at "fully booted".  install_guards() replaces these with the
    stop-aware handlers once the node exists.
    """
    signal.signal(signal.SIGTERM, lambda *_: os._exit(0))
    signal.signal(signal.SIGINT, lambda *_: os._exit(130))


def install_guards(stop, environ=os.environ, start_ppid: int | None = None) -> None:
    """Arm all guards around `stop()` (idempotent, must tolerate re-entry).

    `stop` should halt device work (e.g. master.pause).  Exit paths call it
    and then leave via os._exit so a wedged device loop or a blocked
    serve_forever cannot keep the process (and the chip) alive anyway.

    `start_ppid` is the parent pid observed as early as possible in process
    startup (app.py captures it before the heavy jax imports): if the parent
    died during our multi-second boot, getppid() has already moved to the
    reaper and polling alone would never notice.
    """
    done = threading.Event()

    def stop_once() -> None:
        if done.is_set():
            return
        done.set()
        try:
            stop()
        except Exception as e:  # pragma: no cover — best-effort on the way out
            _log.warning("stop raised during shutdown: %s", e)

    def die(reason: str, code: int = 0) -> None:
        _log.info("exiting: %s", reason)
        stop_once()
        os._exit(code)

    # Signal handlers run on the main thread, which may be blocked inside
    # serve_forever — socketserver.shutdown() would deadlock there, so exit
    # via os._exit after stopping device work (the OS reclaims sockets).
    signal.signal(signal.SIGTERM, lambda *_: die("SIGTERM"))
    signal.signal(signal.SIGINT, lambda *_: die("SIGINT", code=130))
    atexit.register(stop_once)

    ttl = float(environ.get("MISAKA_TTL_S", "0") or 0)
    parent = start_ppid if start_ppid is not None else os.getppid()
    watch_orphan = parent > 1 and environ.get("MISAKA_ORPHAN_OK") != "1"

    if watch_orphan:
        # Kernel-level guard: SIGTERM on parent death (no polling, no race
        # once armed).  prctl only covers deaths AFTER the call, so recheck
        # getppid() for a parent that died during our slow boot.
        _arm_pdeathsig()
        if os.getppid() != parent:
            die(f"parent {parent} died during startup (orphan watchdog; "
                "set MISAKA_ORPHAN_OK=1 to daemonize)")

    if not (ttl or watch_orphan):
        return

    def watchdog() -> None:
        deadline = (ttl and (_now() + ttl)) or None
        while True:
            if done.wait(_POLL_S):
                return
            if watch_orphan and os.getppid() != parent:
                die(f"parent {parent} died (orphan watchdog; "
                    "set MISAKA_ORPHAN_OK=1 to daemonize)")
            if deadline and _now() > deadline:
                die(f"MISAKA_TTL_S={ttl:g} deadline reached")

    threading.Thread(target=watchdog, name="misaka-lifecycle", daemon=True).start()


def _arm_pdeathsig() -> None:
    """Linux PR_SET_PDEATHSIG: deliver SIGTERM when the parent dies."""
    try:
        import ctypes

        PR_SET_PDEATHSIG = 1
        libc = ctypes.CDLL(None, use_errno=True)
        libc.prctl(PR_SET_PDEATHSIG, signal.SIGTERM, 0, 0, 0)
    except Exception:  # pragma: no cover — polling watchdog still covers us
        pass


def _now() -> float:
    import time

    return time.monotonic()
