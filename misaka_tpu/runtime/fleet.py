"""The replicated engine fleet: N engine processes behind one endpoint.

Why this exists: PR 3 measured the single-engine wall — one CPython
process tops out near ~3.5k HTTP requests/s no matter how fast the
native pool underneath is, and the SO_REUSEPORT frontend tier (r8) only
scales the REQUEST side of that equation.  The engine process itself
(plane handling + ServeBatcher + scatter/gather are Python) becomes the
next wall.  The reference system's whole shape is N independent nodes
behind one master (docker-compose'd gRPC program/stack nodes); this
module is that shape for the fused engine: N engine-replica
subprocesses, each with its OWN native pool and ServeBatcher, behind
the existing frontend tier acting as a data-parallel router.

    clients ──HTTP──▶ frontend workers (SO_REUSEPORT, unchanged)
                          │ FleetPlaneRouter (runtime/frontends.py)
            ┌─────────────┼──────────────┐
            ▼             ▼              ▼
        replica 0      replica 1  ...  replica N-1     (this module
        engine proc    engine proc     engine proc      supervises them)
            ▲             ▲              ▲
            └──── fleet control server ──┘  (aggregated /metrics /status
                  /healthz, POST /fleet/roll, lifecycle fan-out, proxy)

Routing policy (implemented in frontends.FleetPlaneRouter, the hash
ring lives here so both sides share one implementation):

  * stateless compute (no program address) — least-queue-depth across
    healthy replicas, ties broken by lowest replica index;
  * program-addressed compute — consistent hashing on the program name
    (HashRing below), so per-program coalescing and registry engine
    state stay sticky on one replica; on failover only ~1/N of the
    keyspace moves;
  * a replica that dies mid-frame gets the frame hedged onto a healthy
    sibling within a bounded budget; a typed 503 is answered only when
    the WHOLE fleet is down.

Failure discipline (the r9 supervisor's, applied one level up): a dead
replica is respawned with exponential backoff + jitter, a crash loop
trips a circuit breaker, and per-replica up/degraded/down health (probed
via each replica's /healthz) gates routing and rides the aggregated
/healthz + /status payloads — a shrunk fleet is never silent.

Rolling restart (`POST /fleet/roll`): one replica at a time — drain to
quiescence (the replica's compute plane answers new frames with a
reroute status the router absorbs), checkpoint through the r9
manifest-verified durable path, kill, boot the replacement with the
checkpoint restored (bit-identical state), wait healthy, readmit.  A
deploy loses zero requests.

This module imports stdlib only at module level (plus the stdlib-only
utils) — the jax-free frontend workers import HashRing from here, and
the fleet parent only pays heavy imports inside functions that need
them.
"""

from __future__ import annotations

import hashlib
import http.client
import json
import logging
import os
import signal
import subprocess
import sys
import threading
import time
from bisect import bisect_left
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

# no cycle: frontends (stdlib-only too) imports this module only lazily,
# inside functions — and its pick_free_port is the one canonical copy
from misaka_tpu.runtime.frontends import pick_free_port  # noqa: F401
from misaka_tpu.utils import faults
from misaka_tpu.utils import metrics
from misaka_tpu.utils.backoff import Backoff

log = logging.getLogger("misaka_tpu.fleet")

M_FLEET_CONFIGURED = metrics.gauge(
    "misaka_fleet_replicas_configured",
    "Engine replicas the fleet is configured for (live fleet manager)",
)
M_FLEET_ALIVE = metrics.gauge(
    "misaka_fleet_replicas_alive",
    "Engine replica processes currently alive (live fleet manager)",
)
M_FLEET_RESTARTS = metrics.counter(
    "misaka_fleet_replica_restarts_total",
    "Engine replica processes respawned by the fleet manager",
    ("reason",),  # "crash" | "roll"
)
M_FLEET_ROLLS = metrics.counter(
    "misaka_fleet_rolls_total",
    "Rolling restarts completed by the fleet manager",
    ("status",),  # "ok" | "failed"
)
M_FLEET_PEERS_UP = metrics.gauge(
    "misaka_fleet_peers_up",
    "Registered remote peers currently passing health probes",
)
M_FLEET_GOSSIP = metrics.counter(
    "misaka_fleet_gossip_total",
    "Usage-gossip exchanges driven by the fleet hub, per target outcome",
    ("status",),  # "ok" | "error"
)


# --- consistent hashing -----------------------------------------------------


class HashRing:
    """Consistent hashing over replica indices (sha1, virtual nodes).

    `lookup(key)` returns EVERY replica exactly once, in ring order from
    the key's position — a preference list the router walks for the
    first healthy replica.  The property that matters for failover and
    join/leave: removing one replica from an N-replica ring changes the
    FIRST preference of only ~1/N of the keyspace (its keys), and every
    other key keeps its owner — per-program engine state and coalescing
    stay sticky through fleet churn.
    """

    def __init__(self, replicas, vnodes: int = 64):
        self.replicas = sorted(replicas)
        self._vnodes = vnodes
        points = []
        for rid in self.replicas:
            for v in range(vnodes):
                h = hashlib.sha1(f"misaka-replica-{rid}#{v}".encode()).digest()
                points.append((int.from_bytes(h[:8], "big"), rid))
        points.sort()
        self._points = points
        self._hashes = [p[0] for p in points]

    def lookup(self, key: str) -> list[int]:
        """Preference order of replica indices for `key` (all replicas,
        each once, deterministic)."""
        if not self._points:
            return []
        h = int.from_bytes(hashlib.sha1(key.encode()).digest()[:8], "big")
        start = bisect_left(self._hashes, h) % len(self._points)
        order: list[int] = []
        seen = set()
        for i in range(len(self._points)):
            rid = self._points[(start + i) % len(self._points)][1]
            if rid not in seen:
                seen.add(rid)
                order.append(rid)
                if len(order) == len(self.replicas):
                    break
        return order

    def owner(self, key: str) -> int:
        return self.lookup(key)[0]


# --- small shared helpers ---------------------------------------------------


def parse_fleet_peers(spec: str | None) -> list[dict]:
    """`MISAKA_FLEET_PEERS="host:port[:planeport],..."` -> peer descriptors.

    `port` is the peer's HTTP control/replica port (the surface the fleet
    probes and drives the roll protocol against); its compute plane
    defaults to `port + 1` on the same host unless a third field pins it.
    Malformed entries are a hard error — a typo'd peer silently dropped
    from supervision would be worse than no peer.
    """
    peers: list[dict] = []
    for raw in (spec or "").split(","):
        entry = raw.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) not in (2, 3) or not parts[0]:
            raise ValueError(
                f"MISAKA_FLEET_PEERS entry {entry!r}: want "
                f"host:port or host:port:planeport"
            )
        try:
            port = int(parts[1])
            plane_port = int(parts[2]) if len(parts) == 3 else port + 1
        except ValueError:
            raise ValueError(
                f"MISAKA_FLEET_PEERS entry {entry!r}: ports must be "
                f"integers"
            ) from None
        peers.append({
            "host": parts[0],
            "port": port,
            "plane": f"{parts[0]}:{plane_port}",
        })
    return peers


def verify_manifest(path: str) -> None:
    """Stdlib-only strict manifest gate for a JUST-WRITTEN checkpoint:
    the sidecar must exist and its size + sha256 must match the file.

    The full verifier (runtime/master.py verify_checkpoint) tolerates
    manifest-less legacy files and stale sidecars because it gates
    RESTORES of arbitrary history; a roll checkpoint was written
    milliseconds ago by the durable save path, so anything short of an
    exact match means the save tore — abort the roll, never kill the
    replica whose state this was.  Raises RuntimeError on mismatch.
    """
    mpath = path + ".manifest"
    try:
        with open(mpath) as f:
            manifest = json.load(f)
        want_size = int(manifest["size"])
        want_sha = str(manifest["sha256"])
    except (OSError, ValueError, KeyError, TypeError) as e:
        raise RuntimeError(
            f"roll checkpoint {path}: unreadable manifest ({e})"
        ) from e
    size = os.path.getsize(path)
    if size != want_size:
        raise RuntimeError(
            f"roll checkpoint {path}: {size} bytes on disk vs "
            f"{want_size} in the manifest (torn write)"
        )
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    if h.hexdigest() != want_sha:
        raise RuntimeError(
            f"roll checkpoint {path}: sha256 mismatch against the manifest"
        )


class _ReplicaHTTP:
    """Tiny keep-alive-free HTTP helper against one replica's control
    server (control-plane calls are rare; simplicity over pooling).
    Local replicas live on loopback; registered remote peers pass their
    own host."""

    def __init__(self, port: int, timeout: float = 10.0,
                 key: str | None = None, host: str = "127.0.0.1"):
        self.host = host
        self.port = port
        self.timeout = timeout
        # the fleet's per-boot internal token (see FleetManager): the
        # parent's own control-plane calls must pass the replica-side
        # edge chain when auth is armed — an authenticated fleet that
        # could not drain/checkpoint its own replicas could never roll
        self.key = key

    def request(self, method: str, path: str, body: bytes | None = None,
                headers: dict | None = None,
                timeout: float | None = None) -> tuple[int, bytes, dict]:
        conn = http.client.HTTPConnection(
            self.host, self.port,
            timeout=self.timeout if timeout is None else timeout,
        )
        headers = dict(headers or {})
        if self.key is not None and "X-Misaka-Key" not in headers:
            headers["X-Misaka-Key"] = self.key
        try:
            conn.request(method, path, body, headers)
            resp = conn.getresponse()
            payload = resp.read()
            return resp.status, payload, dict(resp.getheaders())
        finally:
            conn.close()

    def get_json(self, path: str, timeout: float | None = None):
        status, body, _ = self.request("GET", path, timeout=timeout)
        if status != 200:
            raise RuntimeError(
                f"GET {path} on {self.host}:{self.port} -> {status}: "
                f"{body[:200].decode(errors='replace')}"
            )
        return json.loads(body)

    def post_json(self, path: str, obj,
                  timeout: float | None = None) -> tuple[int, bytes]:
        status, payload, _ = self.request(
            "POST", path, json.dumps(obj).encode(),
            {"Content-Type": "application/json"}, timeout=timeout,
        )
        return status, payload

    def post_form(self, path: str, timeout: float | None = None,
                  **fields) -> tuple[int, bytes]:
        from urllib.parse import urlencode

        body = urlencode(fields).encode()
        status, payload, _ = self.request(
            "POST", path, body,
            {"Content-Type": "application/x-www-form-urlencoded"},
            timeout=timeout,
        )
        return status, payload


# --- the fleet manager ------------------------------------------------------


class ReplicaDown(RuntimeError):
    """A control-plane call needed a live replica and none qualified."""


class FleetManager:
    """Spawns and supervises N engine-replica processes.

    Each replica is a full `misaka_tpu.runtime.app` master-mode process
    (own jax runtime, own native pool, own ServeBatcher) pinned to a
    fixed slot identity: loopback HTTP port + compute-plane unix socket
    path.  Slot identity survives respawns and rolls, so the frontend
    router re-admits a replacement the moment its plane socket accepts —
    no reconfiguration anywhere.

    Supervision mirrors the r9 FrontendSupervisor: a monitor thread
    reaps deaths and respawns on a bounded backoff curve; a slot whose
    replicas keep dying fast trips a circuit breaker.  Health is probed
    per replica (GET /healthz on its loopback port, concurrent per-slot
    prober threads — down-detection cadence must not depend on how many
    replicas are dead): "up" on a passing probe, "degraded" while
    probes fail, "down" when the process is dead or probes have failed
    past `down_after`, "draining"/"starting" during a roll.
    """

    def __init__(
        self,
        n: int,
        fleet_dir: str,
        base_env: dict | None = None,
        backoff_base: float = 0.5,
        backoff_cap: float = 15.0,
        fast_crash_s: float = 5.0,
        breaker_threshold: int = 5,
        breaker_reset_s: float = 60.0,
        poll_s: float = 0.2,
        probe_s: float = 0.5,
        down_after: int = 3,
        boot_timeout_s: float = 180.0,
        drain_timeout_s: float = 30.0,
    ):
        self.n = max(1, int(n))
        self.fleet_dir = fleet_dir
        os.makedirs(fleet_dir, exist_ok=True)
        self._base_env = dict(base_env if base_env is not None else os.environ)
        self._backoff = Backoff(base=backoff_base, cap=backoff_cap)
        self._fast_crash_s = float(fast_crash_s)
        self._breaker_threshold = max(1, int(breaker_threshold))
        self._breaker_reset_s = float(breaker_reset_s)
        self._poll_s = float(poll_s)
        self._probe_s = float(probe_s)
        self._down_after = max(1, int(down_after))
        self._boot_timeout_s = float(boot_timeout_s)
        self._drain_timeout_s = float(drain_timeout_s)
        self._lock = threading.Lock()
        self._closed = False
        # Per-boot internal control-plane credential: replicas accept it
        # as an admin-scoped key (MISAKA_EDGE_INTERNAL_TOKEN in
        # _replica_env -> runtime/edge.py resolve_tenant), so the
        # fleet's OWN drain/checkpoint/aggregation calls pass the
        # replica-side edge chain when operator auth is armed.  Random
        # per boot, never persisted, dies with this process.
        self._internal_token = os.urandom(16).hex()
        self._restarts_total = 0
        self._rolls_total = 0
        self._last_roll: dict | None = None
        self._roll_lock = threading.Lock()  # one roll at a time
        # ALL replica Popen calls run on one long-lived spawner thread:
        # each replica arms PR_SET_PDEATHSIG (lifecycle.py), and Linux
        # delivers that signal when the spawning THREAD exits, not just
        # the process — a replica forked from a transient HTTP handler
        # thread (a /fleet/roll request) would be SIGTERMed the moment
        # the response was written.
        import queue

        self._spawn_q: queue.Queue = queue.Queue()
        self._spawner = threading.Thread(
            target=self._spawner_loop, daemon=True,
            name="misaka-fleet-spawner",
        )
        self._spawner.start()
        now = time.monotonic()
        self._slots: list[dict] = []
        for i in range(self.n):
            self._slots.append({
                "idx": i,
                "port": pick_free_port(),
                "plane": os.path.join(fleet_dir, f"plane-{i}.sock"),
                "ckpt_dir": os.path.join(fleet_dir, f"replica-{i}"),
                "proc": None,
                "spawned_at": now,
                "restarts": 0,
                "fast_crashes": 0,
                "next_spawn": 0.0,
                "breaker_until": None,
                "probe_fails": 0,
                "probe_ok": False,
                "running": None,    # replica's network run state (probed)
                "degraded": False,  # replica-declared (probed /healthz)
                "rolling": False,   # roll owns this slot; monitor hands off
                "restore": None,    # checkpoint to restore on next spawn
                "run_on_boot": None,  # roll-preserved run state (one-shot)
            })
        # Static remote peers (MISAKA_FLEET_PEERS): replicas on OTHER
        # hosts this fleet routes to and supervises remotely.  They live
        # in a SEPARATE list — the monitor loop owns self._slots and
        # would try to respawn a peer it cannot spawn (the peer's own
        # host supervisor replaces its process; we probe, route, drain,
        # and checkpoint it over its control port).  Peer indices follow
        # the local slots so router/report rows stay unambiguous.
        self._peers: list[dict] = []
        for j, peer in enumerate(
            parse_fleet_peers(self._base_env.get("MISAKA_FLEET_PEERS"))
        ):
            peer.update({
                "idx": self.n + j,
                "probe_fails": 0,
                "probe_ok": False,
                "running": None,
                "degraded": False,
                "rolling": False,
                "remote": True,
            })
            self._peers.append(peer)
        # Credential for remote peer control calls: peers are separate
        # boots with their own random internal tokens, so cross-host
        # calls need a SHARED key — an operator-provisioned admin key
        # (MISAKA_FLEET_PEER_KEY, typically a pinned
        # MISAKA_EDGE_INTERNAL_TOKEN on the peer side).  Falls back to
        # this boot's internal token for same-host peer topologies.
        self._peer_key = (
            self._base_env.get("MISAKA_FLEET_PEER_KEY")
            or self._internal_token
        )
        self._gossip_s = float(
            self._base_env.get("MISAKA_GOSSIP_S", "0.5") or 0.5
        )
        # the gossip hub's per-source cumulative usage snapshots
        # (source key -> {"tenant|field": monotone counter})
        self._gossip_seen: dict[str, dict[str, float]] = {}
        self._threads: list[threading.Thread] = []

    # --- lifecycle ----------------------------------------------------------

    def start(self, wait_ready: bool = True) -> None:
        for slot in self._slots:
            self._spawn(slot)
        if wait_ready:
            deadline = time.monotonic() + self._boot_timeout_s
            for slot in self._slots:
                self._wait_replica_ready(slot, deadline)
        import weakref

        ref = weakref.ref(self)
        M_FLEET_CONFIGURED.set_function(
            lambda: f.n if (f := ref()) is not None else 0
        )
        M_FLEET_ALIVE.set_function(
            lambda: f.alive() if (f := ref()) is not None else 0
        )
        M_FLEET_PEERS_UP.set_function(
            lambda: (
                sum(1 for p in f._peers if p["probe_ok"])
                if (f := ref()) is not None else 0
            )
        )
        monitor = threading.Thread(
            target=self._monitor_loop, daemon=True, name="misaka-fleet-monitor"
        )
        monitor.start()
        self._threads.append(monitor)
        for slot in self._slots:
            t = threading.Thread(
                target=self._probe_loop, args=(slot,), daemon=True,
                name=f"misaka-fleet-probe-{slot['idx']}",
            )
            t.start()
            self._threads.append(t)
        for peer in self._peers:
            t = threading.Thread(
                target=self._peer_probe_loop, args=(peer,), daemon=True,
                name=f"misaka-fleet-peer-probe-{peer['idx']}",
            )
            t.start()
            self._threads.append(t)
        if self._gossip_s > 0 and (self._peers or self.n > 1):
            t = threading.Thread(
                target=self._gossip_loop, daemon=True,
                name="misaka-fleet-gossip",
            )
            t.start()
            self._threads.append(t)
        # Chaos harness (utils/faults.py): `replica_kill=N` SIGKILLs one
        # live replica N seconds after fleet start — the kill(9)-without-
        # kill lever the failover contract is exercised against.  Fired
        # ONCE per fleet boot (firing per spawn would kill every respawn
        # into a loop the breaker would then misread as a crash loop).
        kill_after = faults.fire("replica_kill")
        if kill_after is not None:
            threading.Thread(
                target=self._chaos_kill, args=(max(0.0, kill_after),),
                daemon=True, name="misaka-fleet-chaos-kill",
            ).start()

    def _chaos_kill(self, delay: float) -> None:
        time.sleep(delay)
        with self._lock:
            live = [s for s in self._slots
                    if s["proc"] is not None and s["proc"].poll() is None]
            if not live:
                return
            victim = live[0]
            pid = victim["proc"].pid
        log.warning("replica_kill fault: SIGKILL replica %d (pid %d)",
                    victim["idx"], pid)
        try:
            os.kill(pid, signal.SIGKILL)
        except OSError:
            pass

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            procs = [s["proc"] for s in self._slots if s["proc"] is not None]
        for p in procs:
            try:
                p.terminate()
            except OSError:
                pass
        for p in procs:
            try:
                p.wait(timeout=5)
            except (OSError, subprocess.TimeoutExpired):
                try:
                    p.kill()
                    p.wait(timeout=2)
                except (OSError, subprocess.TimeoutExpired):
                    pass
        self._spawn_q.put(None)
        for t in self._threads:
            t.join(timeout=2)

    # --- spawning -----------------------------------------------------------

    def plane_paths(self) -> list[str]:
        """Every compute-plane address the router fans across: local unix
        sockets first, then remote peers' `host:port` planes — router
        replica indices line up with slot/peer `idx`."""
        return (
            [s["plane"] for s in self._slots]
            + [p["plane"] for p in self._peers]
        )

    def _replica_env(self, slot: dict) -> dict:
        env = dict(self._base_env)
        env.update({
            # a replica must never recurse into fleet mode or spawn its
            # own frontend tier — it is one engine behind the shared one
            "MISAKA_FLEET": "0",
            "MISAKA_HTTP_WORKERS": "0",
            "MISAKA_PORT": str(slot["port"]),
            "MISAKA_PLANE_SOCKET": slot["plane"],
            "MISAKA_PLANE_SERVE": "1",
            "MISAKA_FLEET_REPLICA": str(slot["idx"]),
            # per-replica durable state: each replica checkpoints (and
            # auto-restores) under its own directory — replica states
            # are independent (they serve disjoint request streams)
            "MISAKA_CHECKPOINT_DIR": slot["ckpt_dir"],
        })
        env.pop("MISAKA_ORPHAN_OK", None)  # replicas die with the fleet
        # TLS terminates at the frontend workers: a replica serves
        # loopback HTTP to the control server's proxy and would be
        # unreachable if it wrapped its own listener
        env.pop("MISAKA_TLS_CERT", None)
        env.pop("MISAKA_TLS_KEY", None)
        from misaka_tpu.runtime import edge as edge_mod

        keyfile = edge_mod.keyfile_path(self._base_env)
        if keyfile and not self._base_env.get("MISAKA_API_KEYS"):
            # the conventional <MISAKA_PROGRAMS_DIR>/api_keys.json would
            # not resolve under the replica's per-replica store override
            # below — pin the parent's resolved path explicitly
            env["MISAKA_API_KEYS"] = keyfile
        # the fleet's own control-plane calls authenticate with this
        # per-boot token (see __init__)
        env["MISAKA_EDGE_INTERNAL_TOKEN"] = self._internal_token
        if not self._base_env.get("MISAKA_NATIVE_THREADS") and self.n > 1:
            # N replicas share one box: a full-width native pool EACH
            # (the single-engine default) oversubscribes every core N
            # times and convoys — split the cores instead.  An explicit
            # MISAKA_NATIVE_THREADS always wins (multi-host operators
            # size per host).
            env["MISAKA_NATIVE_THREADS"] = str(
                max(2, (os.cpu_count() or 8) // self.n)
            )
        programs_dir = self._base_env.get("MISAKA_PROGRAMS_DIR")
        if programs_dir:
            # per-replica registry stores: every replica can serve every
            # program (uploads fan out via the control server), but the
            # persistent stores must not share files across processes
            env["MISAKA_PROGRAMS_DIR"] = os.path.join(
                programs_dir, f"replica-{slot['idx']}"
            )
        tsdb_dir = self._base_env.get("MISAKA_TSDB_DIR")
        if tsdb_dir:
            # the durable telemetry spools (utils/tsdb.py, runtime/usage,
            # capture rotation) are single-writer per directory — same
            # per-replica split as the registry stores above; the parent
            # keeps the root for its own fleet-level history
            env["MISAKA_TSDB_DIR"] = os.path.join(
                tsdb_dir, f"replica-{slot['idx']}"
            )
        if slot["restore"]:
            env["MISAKA_FLEET_RESTORE"] = slot["restore"]
        else:
            env.pop("MISAKA_FLEET_RESTORE", None)
        if slot["run_on_boot"] is not None:
            # a roll replacement inherits its predecessor's run state (a
            # deploy must not flip a paused network back on, and the
            # restored tick must stay frozen if the operator froze it)
            env["MISAKA_AUTORUN"] = "1" if slot["run_on_boot"] else "0"
        return env

    def _spawner_loop(self) -> None:
        while True:
            item = self._spawn_q.get()
            if item is None:
                return
            slot, outcome, done = item
            try:
                self._spawn_inline(slot)
            except BaseException as e:  # re-raised on the caller's thread
                outcome.append(e)
            done.set()

    def _spawn(self, slot: dict) -> None:
        """Spawn via the spawner thread (see __init__); raises whatever
        Popen raised, on the calling thread."""
        if threading.current_thread() is self._spawner:
            self._spawn_inline(slot)
            return
        outcome: list = []
        done = threading.Event()
        self._spawn_q.put((slot, outcome, done))
        done.wait()
        if outcome:
            raise outcome[0]

    def _spawn_inline(self, slot: dict) -> None:
        os.makedirs(slot["ckpt_dir"], exist_ok=True)
        cmd = [sys.executable, "-m", "misaka_tpu.runtime.app"]
        slot["proc"] = subprocess.Popen(cmd, env=self._replica_env(slot))
        slot["spawned_at"] = time.monotonic()
        slot["probe_fails"] = 0
        slot["probe_ok"] = False
        slot["degraded"] = False  # fresh process: re-probed, not inherited
        log.info(
            "replica %d spawned (pid %d, http :%d, plane %s%s)",
            slot["idx"], slot["proc"].pid, slot["port"], slot["plane"],
            ", restoring " + slot["restore"] if slot["restore"] else "",
        )
        # restore/run_on_boot stay ARMED until this replica passes a
        # health check (_mark_healthy): a replacement that crashes
        # DURING boot gets its verified checkpoint retried on the
        # respawn instead of silently booting fresh — "a broken roll
        # never loses a replica's state".  Once the replica has served,
        # they clear, so a LATER crash respawns fresh (base-env
        # MISAKA_AUTORUN rules; stale state must not resurrect).

    def _wait_replica_ready(self, slot: dict, deadline: float) -> None:
        rh = _ReplicaHTTP(slot["port"], timeout=2.0,
                          key=self._internal_token)
        while time.monotonic() < deadline:
            proc = slot["proc"]
            if proc is not None and proc.poll() is not None:
                raise RuntimeError(
                    f"replica {slot['idx']} exited during boot "
                    f"(code {proc.returncode})"
                )
            try:
                payload = rh.get_json("/healthz")
                if payload.get("ok"):
                    slot["running"] = bool(payload.get("running"))
                    self._mark_healthy(slot)
                    return
            except (OSError, RuntimeError, ValueError):
                pass
            time.sleep(0.2)
        raise RuntimeError(
            f"replica {slot['idx']} did not become healthy within "
            f"{self._boot_timeout_s:.0f}s"
        )

    # --- supervision --------------------------------------------------------

    def alive(self) -> int:
        with self._lock:
            return sum(
                1 for s in self._slots
                if s["proc"] is not None and s["proc"].poll() is None
            )

    def replica_state(self, slot: dict) -> str:
        proc = slot["proc"]
        if proc is None or proc.poll() is not None:
            return "down"
        if slot["rolling"]:
            return "draining"
        if slot["probe_ok"]:
            return "up"
        if slot["probe_fails"] >= self._down_after:
            return "down"
        return "degraded" if slot["probe_fails"] else "starting"

    def peer_state(self, peer: dict) -> str:
        """The replica state machine, applied to a remote peer.  There is
        no local process to poll, so liveness is probe-only: the same
        up/starting/degraded/down ladder, plus "draining" while a roll
        owns the peer."""
        if peer["rolling"]:
            return "draining"
        if peer["probe_ok"]:
            return "up"
        if peer["probe_fails"] >= self._down_after:
            return "down"
        return "degraded" if peer["probe_fails"] else "starting"

    def state(self) -> dict:
        """The /healthz + /status fleet block: per-replica rows plus an
        explicit `degraded` flag (any replica not up) — the same
        no-silent-degradation contract as the frontend supervisor."""
        now = time.monotonic()
        with self._lock:
            rows = []
            for s in self._slots:
                st = self.replica_state(s)
                rows.append({
                    "replica": s["idx"],
                    "state": st,
                    "pid": s["proc"].pid if s["proc"] is not None else None,
                    "port": s["port"],
                    "restarts": s["restarts"],
                    # network run state from the last /healthz probe
                    # (<= probe_s stale; None until first probe)
                    "running": s["running"],
                    "degraded": s["degraded"],
                    "breaker_open": bool(
                        s["breaker_until"] is not None
                        and s["breaker_until"] > now
                    ),
                })
            for p in self._peers:
                # remote peers ride the same rows (same no-silent-
                # degradation contract: a down peer must surface on the
                # fleet /healthz, not vanish from it)
                rows.append({
                    "replica": p["idx"],
                    "state": self.peer_state(p),
                    "pid": None,
                    "host": p["host"],
                    "port": p["port"],
                    "restarts": None,  # the peer's own supervisor counts
                    "running": p["running"],
                    "degraded": p["degraded"],
                    "breaker_open": False,
                    "remote": True,
                })
            restarts = self._restarts_total
            rolls = self._rolls_total
            last_roll = self._last_roll
        alive = sum(1 for r in rows if r["state"] not in ("down",))
        up = sum(1 for r in rows if r["state"] == "up")
        return {
            "configured": len(rows),
            "alive": alive,
            "up": up,
            "peers": len(self._peers),
            "peers_up": sum(
                1 for r in rows
                if r.get("remote") and r["state"] == "up"
            ),
            "replicas": rows,
            "restarts_total": restarts,
            "rolls_total": rolls,
            "last_roll": last_roll,
            "degraded": up < len(rows) or any(
                r["state"] == "up" and r["degraded"] for r in rows
            ),
        }

    def up_slots(self) -> list[dict]:
        with self._lock:
            return [
                s for s in self._slots if self.replica_state(s) == "up"
            ]

    def slot_states(self) -> list[tuple[dict, str]]:
        """Every configured slot with its state, in index order — the
        fan-out path needs the non-up ones too (a skipped replica must
        be reported, never silently excluded)."""
        with self._lock:
            return [(s, self.replica_state(s)) for s in self._slots]

    def _mark_healthy(self, slot: dict) -> None:
        slot["probe_ok"] = True
        slot["probe_fails"] = 0
        if slot["rolling"]:
            # the roll owns the slot: its own readiness wait lands here
            # while slot["restore"] is armed for the REPLACEMENT — a
            # disarm now (e.g. a straggling probe of the old, still-
            # alive replica) would make the replacement silently boot
            # without restoring.  Only a post-roll probe disarms; until
            # then a boot crash inside the roll window retries the
            # checkpoint, which is exactly the contract below.
            return
        # the replica reached healthy with its restore applied: disarm
        # it (see _spawn_inline — until here a boot crash retries the
        # checkpoint; from here a crash respawns fresh)
        slot["restore"] = None
        slot["run_on_boot"] = None

    def _probe_loop(self, slot: dict) -> None:
        rh = _ReplicaHTTP(slot["port"], timeout=2.0,
                          key=self._internal_token)
        while not self._closed:
            time.sleep(self._probe_s)
            if slot["rolling"]:
                # the roll owns this slot (the same hand-off the monitor
                # honors): a probe passing against the OLD still-alive
                # replica after the roll arms slot["restore"] would
                # _mark_healthy -> disarm the checkpoint, and the
                # replacement would silently boot without restoring
                continue
            proc = slot["proc"]
            if proc is None or proc.poll() is not None:
                slot["probe_ok"] = False
                continue
            try:
                payload = rh.get_json("/healthz")
                ok = bool(payload.get("ok"))
                slot["running"] = bool(payload.get("running"))
                # replica-declared degradation (SLO page, watchdog page,
                # shrunk worker pool) surfaces on the FLEET healthz too:
                # a fleet of up-but-degraded replicas must not read green
                slot["degraded"] = bool(payload.get("degraded"))
            except (OSError, RuntimeError, ValueError):
                ok = False
            if ok:
                self._mark_healthy(slot)
            else:
                slot["probe_ok"] = False
                slot["probe_fails"] += 1

    def _peer_probe_loop(self, peer: dict) -> None:
        """Remote-peer health: GET /healthz over the peer's control port
        on the local probe cadence.  Transitions ride peer_state(); a
        peer that stops answering walks up -> degraded -> down exactly
        like a local replica whose probes fail — the compute-plane
        router's own peer accounting (suspect holds, hedges) handles the
        data plane; this loop is the fleet-/healthz + roll-gate view."""
        rh = _ReplicaHTTP(peer["port"], timeout=2.0,
                          key=self._peer_key, host=peer["host"])
        while not self._closed:
            time.sleep(self._probe_s)
            if peer["rolling"]:
                # the roll owns the peer: its drain makes /healthz read
                # degraded by design; probing through it would flap the
                # state the roll is waiting on
                continue
            try:
                payload = rh.get_json("/healthz")
                ok = bool(payload.get("ok"))
                peer["running"] = bool(payload.get("running"))
                peer["degraded"] = bool(payload.get("degraded"))
            except (OSError, RuntimeError, ValueError):
                ok = False
            if ok:
                if not peer["probe_ok"]:
                    log.info("peer %d (%s:%d) is up", peer["idx"],
                             peer["host"], peer["port"])
                peer["probe_ok"] = True
                peer["probe_fails"] = 0
            else:
                if peer["probe_ok"]:
                    log.warning("peer %d (%s:%d) failed a probe",
                                peer["idx"], peer["host"], peer["port"])
                peer["probe_ok"] = False
                peer["probe_fails"] += 1

    # --- usage gossip hub ---------------------------------------------------

    def _gossip_targets(self) -> list[tuple[str, _ReplicaHTTP]]:
        """(source-key, http helper) for every gossip participant that is
        currently up: local replicas over loopback with the internal
        token, remote peers over their control port with the peer key."""
        targets: list[tuple[str, _ReplicaHTTP]] = []
        with self._lock:
            for s in self._slots:
                if self.replica_state(s) == "up":
                    targets.append((
                        f"replica-{s['idx']}",
                        _ReplicaHTTP(s["port"], timeout=2.0,
                                     key=self._internal_token),
                    ))
        for p in self._peers:
            if self.peer_state(p) == "up":
                targets.append((
                    f"peer-{p['idx']}",
                    _ReplicaHTTP(p["port"], timeout=2.0,
                                 key=self._peer_key, host=p["host"]),
                ))
        return targets

    def _gossip_loop(self) -> None:
        """Star-topology usage gossip: every `MISAKA_GOSSIP_S` the hub
        POSTs each participant the SUM of every OTHER participant's
        cumulative per-tenant admissions and collects that participant's
        own snapshot from the response.  Sums of monotone counters are
        monotone, so each edge chain's per-source delta accounting
        (edge.apply_remote_usage) stays correct, and one round-trip per
        target per round bounds a flooded tenant's aggregate
        over-admission to ~1 + burst window / flood window instead of
        Nx (see ARCHITECTURE.md).  Piggybacks on the probe/stats
        channel: plain control-port HTTP, no new listener."""
        while not self._closed:
            time.sleep(self._gossip_s)
            self._gossip_round()

    def _gossip_round(self) -> None:
        """One hub round: exchange with every up participant."""
        for source, rh in self._gossip_targets():
            if self._closed:
                return
            merged: dict[str, float] = {}
            for other, usage in self._gossip_seen.items():
                if other == source:
                    continue
                for key, total in usage.items():
                    merged[key] = merged.get(key, 0.0) + total
            try:
                status, body = rh.post_json("/edge/gossip", {
                    "source": "fleet-hub",
                    "usage": merged,
                })
                if status != 200:
                    raise RuntimeError(f"gossip -> {status}")
                payload = json.loads(body)
                snap = payload.get("usage")
                if isinstance(snap, dict):
                    self._gossip_seen[source] = {
                        str(k): float(v) for k, v in snap.items()
                    }
                M_FLEET_GOSSIP.labels(status="ok").inc()
            except (OSError, RuntimeError, ValueError):
                # a down/draining participant just misses rounds; its
                # last snapshot keeps reconciling into the others
                M_FLEET_GOSSIP.labels(status="error").inc()

    def _monitor_loop(self) -> None:
        while True:
            time.sleep(self._poll_s)
            due: list[dict] = []
            with self._lock:
                if self._closed:
                    return
                now = time.monotonic()
                for slot in self._slots:
                    proc = slot["proc"]
                    if (
                        proc is not None and proc.poll() is not None
                        and not slot["rolling"]
                    ):
                        lifetime = now - slot["spawned_at"]
                        slot["proc"] = None
                        slot["probe_ok"] = False
                        fast = lifetime < self._fast_crash_s
                        slot["fast_crashes"] = (
                            slot["fast_crashes"] + 1 if fast else 0
                        )
                        if slot["fast_crashes"] >= self._breaker_threshold:
                            slot["breaker_until"] = (
                                now + self._breaker_reset_s
                            )
                            log.error(
                                "replica %d crash loop (%d fast deaths, "
                                "last exit %s): circuit breaker open for "
                                "%.0fs", slot["idx"], slot["fast_crashes"],
                                proc.returncode, self._breaker_reset_s,
                            )
                        else:
                            delay = self._backoff.delay_for(
                                max(0, slot["fast_crashes"] - 1)
                            )
                            slot["next_spawn"] = now + delay
                            log.warning(
                                "replica %d died (exit %s after %.1fs); "
                                "respawn in %.2fs", slot["idx"],
                                proc.returncode, lifetime, delay,
                            )
                    if slot["proc"] is None and not slot["rolling"]:
                        if slot["breaker_until"] is not None:
                            if now < slot["breaker_until"]:
                                continue
                            slot["breaker_until"] = None
                            log.warning(
                                "replica %d breaker half-open: one respawn",
                                slot["idx"],
                            )
                        elif now < slot["next_spawn"]:
                            continue
                        due.append(slot)
            spawned: list[dict] = []
            for slot in due:
                # only the monitor (or a roll holding `rolling`) mutates
                # a slot's proc, so spawning outside the lock cannot race
                # another writer — just the close() check below
                try:
                    self._spawn(slot)
                except OSError as e:
                    log.error("replica %d spawn failed (%s); backing off",
                              slot["idx"], e)
                    with self._lock:
                        slot["fast_crashes"] += 1
                        slot["next_spawn"] = (
                            time.monotonic()
                            + self._backoff.delay_for(slot["fast_crashes"] - 1)
                        )
                    continue
                spawned.append(slot)
            if not spawned:
                continue
            with self._lock:
                if self._closed:
                    for slot in spawned:
                        try:
                            slot["proc"].terminate()
                            slot["proc"].wait(timeout=2)
                        except (OSError, subprocess.TimeoutExpired):
                            pass
                    return
                for slot in spawned:
                    slot["restarts"] += 1
                    self._restarts_total += 1
                    M_FLEET_RESTARTS.labels(reason="crash").inc()
                    log.info("replica %d respawned (pid %d)",
                             slot["idx"], slot["proc"].pid)

    # --- rolling restart ----------------------------------------------------

    def roll(self, drain_timeout_s: float | None = None) -> dict:
        """Zero-loss rolling restart: drain → checkpoint → verify →
        replace → restore → readmit, one replica at a time.

        Returns a per-replica report.  Raises RuntimeError when a step
        fails (the failing replica is undrained and left serving — a
        broken roll must degrade to "deploy didn't happen", never to
        "replica lost").  Concurrent rolls are rejected.
        """
        if not self._roll_lock.acquire(blocking=False):
            raise RuntimeError("a rolling restart is already in progress")
        try:
            return self._roll_locked(
                self._drain_timeout_s if drain_timeout_s is None
                else float(drain_timeout_s)
            )
        finally:
            self._roll_lock.release()

    def _roll_locked(self, drain_timeout_s: float) -> dict:
        report: list[dict] = []
        t_start = time.monotonic()
        for slot in self._slots:
            try:
                entry = self._roll_one(slot, drain_timeout_s)
            except Exception:
                M_FLEET_ROLLS.labels(status="failed").inc()
                with self._lock:
                    self._last_roll = {
                        "ok": False,
                        "replicas": report,
                        "failed_replica": slot["idx"],
                    }
                raise
            report.append(entry)
        for peer in self._peers:
            try:
                entry = self._roll_peer(peer, drain_timeout_s)
            except Exception:
                M_FLEET_ROLLS.labels(status="failed").inc()
                with self._lock:
                    self._last_roll = {
                        "ok": False,
                        "replicas": report,
                        "failed_replica": peer["idx"],
                    }
                raise
            report.append(entry)
        with self._lock:
            self._rolls_total += 1
            self._last_roll = {
                "ok": True,
                "replicas": report,
                "duration_s": round(time.monotonic() - t_start, 3),
            }
            out = dict(self._last_roll)
        M_FLEET_ROLLS.labels(status="ok").inc()
        return out

    def _roll_one(self, slot: dict, drain_timeout_s: float) -> dict:
        idx = slot["idx"]
        rh = _ReplicaHTTP(slot["port"], timeout=10.0,
                          key=self._internal_token)
        entry: dict = {"replica": idx}
        # A roll ordered right after a failover is routine (kill one
        # replica, then deploy): wait for a replica that is merely
        # BOOTING to come up before giving up on the roll.
        heal_deadline = time.monotonic() + self._boot_timeout_s
        while True:
            with self._lock:
                state = self.replica_state(slot)
                if state == "up":
                    slot["rolling"] = True  # monitor hands the slot to us
                    break
            if time.monotonic() >= heal_deadline:
                raise RuntimeError(
                    f"roll aborted: replica {idx} is {state}, not up "
                    f"(heal the fleet before rolling)"
                )
            time.sleep(0.2)
        try:
            # 1. drain: the replica's compute plane answers new frames
            #    with the reroute status; the router shifts traffic to
            #    siblings with zero client-visible errors.  In-flight
            #    frames finish.
            t0 = time.monotonic()
            try:
                was_running = bool(rh.get_json("/healthz").get("running"))
            except (OSError, RuntimeError, ValueError):
                was_running = True  # serving is the safe default
            status, body = rh.post_form("/fleet/drain", state="on")
            if status != 200:
                raise RuntimeError(
                    f"replica {idx}: drain request failed "
                    f"({status}: {body[:200].decode(errors='replace')})"
                )
            deadline = time.monotonic() + drain_timeout_s
            quiescent = 0
            while time.monotonic() < deadline:
                payload = json.loads(rh.post_form("/fleet/drain",
                                                  state="on")[1])
                if (
                    payload.get("inflight", 1) == 0
                    and payload.get("http_inflight", 0) == 0
                ):
                    quiescent += 1
                    if quiescent >= 2:  # two consecutive clean reads
                        break
                else:
                    quiescent = 0
                time.sleep(0.05)
            else:
                raise RuntimeError(
                    f"replica {idx}: did not drain to quiescence within "
                    f"{drain_timeout_s:.0f}s"
                )
            entry["drained_in_s"] = round(time.monotonic() - t0, 3)

            # 2. checkpoint through the durable manifest-verified path
            name = f"fleet-roll-{int(time.time())}"
            status, body = rh.post_form("/checkpoint", name=name, timeout=60)
            if status != 200:
                raise RuntimeError(
                    f"replica {idx}: roll checkpoint failed "
                    f"({status}: {body[:200].decode(errors='replace')})"
                )
            ckpt = os.path.join(slot["ckpt_dir"], name + ".npz")
            verify_manifest(ckpt)
            entry["checkpoint"] = ckpt

            # 3. replace: terminate (the replica is quiescent), boot the
            #    replacement restoring the verified checkpoint on the
            #    SAME port + plane path — the router re-admits it the
            #    moment the plane socket accepts again.
            proc = slot["proc"]
            slot["restore"] = ckpt
            slot["run_on_boot"] = was_running
            try:
                proc.terminate()
                proc.wait(timeout=10)
            except (OSError, subprocess.TimeoutExpired):
                try:
                    proc.kill()
                    proc.wait(timeout=5)
                except (OSError, subprocess.TimeoutExpired):
                    pass
            t_boot = time.monotonic()
            self._spawn(slot)
            with self._lock:
                slot["restarts"] += 1
                self._restarts_total += 1
            M_FLEET_RESTARTS.labels(reason="roll").inc()
            self._wait_replica_ready(
                slot, time.monotonic() + self._boot_timeout_s
            )
            entry["booted_in_s"] = round(time.monotonic() - t_boot, 3)
            entry["restored"] = True
            return entry
        except Exception:
            # leave the replica serving if it still can: undrain — and
            # keep retrying in the background, because the roll failure
            # may BE this replica being wedged, in which case the
            # inline undrain fails too and the replica would otherwise
            # sit draining forever behind a passing /healthz (1/N of
            # capacity silently parked with no degraded signal)
            self._undrain_async(slot)
            raise
        finally:
            with self._lock:
                slot["rolling"] = False

    def _roll_peer(self, peer: dict, drain_timeout_s: float) -> dict:
        """Drive one REMOTE peer through the roll protocol: drain to
        quiescence -> checkpoint -> undrain -> readmit.

        Same drain/quiescence/checkpoint steps as a local slot, with two
        honest differences a remote boundary forces: the checkpoint is
        trusted on the peer's 200 (its durable save path verifies the
        manifest on its own disk — this host cannot read it), and the
        process is NOT replaced (the peer host's own supervisor owns its
        process lifecycle; a roll leaves the peer checkpointed and
        serving, ready for its supervisor to restart it restore-armed).
        A failed step undrains and raises — same "deploy didn't happen,
        replica not lost" contract as the local path.
        """
        idx = peer["idx"]
        rh = _ReplicaHTTP(peer["port"], timeout=10.0,
                          key=self._peer_key, host=peer["host"])
        entry: dict = {"replica": idx, "remote": True,
                       "host": peer["host"]}
        heal_deadline = time.monotonic() + self._boot_timeout_s
        while True:
            state = self.peer_state(peer)
            if state == "up":
                peer["rolling"] = True  # peer prober hands off (skips)
                break
            if time.monotonic() >= heal_deadline:
                raise RuntimeError(
                    f"roll aborted: peer {idx} ({peer['host']}:"
                    f"{peer['port']}) is {state}, not up "
                    f"(heal the fleet before rolling)"
                )
            time.sleep(0.2)
        try:
            t0 = time.monotonic()
            status, body = rh.post_form("/fleet/drain", state="on")
            if status != 200:
                raise RuntimeError(
                    f"peer {idx}: drain request failed "
                    f"({status}: {body[:200].decode(errors='replace')})"
                )
            deadline = time.monotonic() + drain_timeout_s
            quiescent = 0
            while time.monotonic() < deadline:
                payload = json.loads(rh.post_form("/fleet/drain",
                                                  state="on")[1])
                if (
                    payload.get("inflight", 1) == 0
                    and payload.get("http_inflight", 0) == 0
                ):
                    quiescent += 1
                    if quiescent >= 2:
                        break
                else:
                    quiescent = 0
                time.sleep(0.05)
            else:
                raise RuntimeError(
                    f"peer {idx}: did not drain to quiescence within "
                    f"{drain_timeout_s:.0f}s"
                )
            entry["drained_in_s"] = round(time.monotonic() - t0, 3)

            name = f"fleet-roll-{int(time.time())}"
            status, body = rh.post_form("/checkpoint", name=name,
                                        timeout=60)
            if status != 200:
                raise RuntimeError(
                    f"peer {idx}: roll checkpoint failed "
                    f"({status}: {body[:200].decode(errors='replace')})"
                )
            entry["checkpoint"] = name
            entry["restored"] = False  # peer host owns process replacement

            status, body = rh.post_form("/fleet/drain", state="off")
            if status != 200:
                raise RuntimeError(
                    f"peer {idx}: undrain failed "
                    f"({status}: {body[:200].decode(errors='replace')})"
                )
            # readmit: a direct probe, not the prober thread (it skips
            # while `rolling` is held)
            t_ready = time.monotonic()
            readmit_deadline = t_ready + self._boot_timeout_s
            while True:
                try:
                    if rh.get_json("/healthz").get("ok"):
                        break
                except (OSError, RuntimeError, ValueError):
                    pass
                if time.monotonic() >= readmit_deadline:
                    raise RuntimeError(
                        f"peer {idx}: not healthy after undrain"
                    )
                time.sleep(0.2)
            peer["probe_ok"] = True
            peer["probe_fails"] = 0
            entry["readmitted_in_s"] = round(
                time.monotonic() - t_ready, 3
            )
            return entry
        except Exception:
            # leave the peer serving if it still can (same rationale as
            # the local undrain retryer, minus process replacement)
            try:
                rh.post_form("/fleet/drain", state="off")
            except Exception:
                pass
            raise
        finally:
            peer["rolling"] = False

    def _undrain_async(self, slot: dict) -> None:
        """Best-effort background undrain after a failed roll step.
        Retries until the undrain lands, the replica is replaced (a
        respawn boots undrained), a NEW roll takes the slot (its own
        failure path spawns its own retryer), or the manager closes."""
        proc = slot["proc"]

        def loop() -> None:
            # our roll's `finally` clears `rolling` right after this
            # thread is spawned; wait it out before treating `rolling`
            # as "a newer roll owns the slot"
            settle = time.monotonic() + 2.0
            while slot["rolling"] and time.monotonic() < settle:
                time.sleep(0.02)
            rh = _ReplicaHTTP(slot["port"], timeout=5.0,
                              key=self._internal_token)
            while not self._closed:
                if slot["rolling"] or slot["proc"] is not proc:
                    return
                if proc is None or proc.poll() is not None:
                    return
                try:
                    status, _ = rh.post_form("/fleet/drain", state="off")
                    if status == 200:
                        return
                except Exception:
                    pass  # wedged replica: keep trying
                time.sleep(0.5)

        threading.Thread(
            target=loop, daemon=True,
            name=f"misaka-fleet-undrain-{slot['idx']}",
        ).start()


# --- the fleet control server -----------------------------------------------

# routes fanned out to EVERY up replica (lifecycle must stay consistent
# across the fleet; /programs uploads must land everywhere so failover
# and ring reshuffles find the program on any sibling)
_FANOUT_ROUTES = frozenset({
    "/run", "/pause", "/reset", "/load", "/programs",
    "/checkpoint", "/restore",
    # fault (re-)arming must reach every replica: the observatory drill
    # injects a scoped serve_delay/replica_blackhole on a RUNNING fleet
    "/debug/faults",
})

# stateful singleton routes proxied to ONE deterministic replica: the
# jax profiler is process-global with paired start/stop calls, so
# round-robin would land /profile/stop on a different replica than its
# /profile/start (409 "not running" while the capture runs forever on
# the first); flamegraph reads pin with them so repeated scrapes watch
# one process
_STICKY_ROUTES = frozenset({
    "/profile/start", "/profile/stop", "/debug/flamegraph",
})


def relabel_metrics_text(text: str, replica: int) -> tuple[str, list[str]]:
    """Inject `replica="<i>"` into every sample of one replica's
    Prometheus exposition.  Returns (sample_lines, header_lines): headers
    (# HELP / # TYPE) are returned separately so the aggregator emits
    each exactly once across the fleet."""
    samples: list[str] = []
    headers: list[str] = []
    label = f'replica="{replica}"'
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("#"):
            headers.append(line)
            continue
        brace = line.find("{")
        if brace == -1:
            # name value  ->  name{replica="i"} value
            name, sep, rest = line.partition(" ")
            if not sep:
                continue  # malformed; drop rather than mislabel
            samples.append(f"{name}{{{label}}} {rest}")
        else:
            samples.append(f"{line[:brace + 1]}{label},{line[brace + 1:]}")
    return samples, headers


def make_fleet_http_server(
    fleet: FleetManager, port: int = 0, host: str = "127.0.0.1"
) -> ThreadingHTTPServer:
    """The fleet control-plane HTTP server (the proxy target the frontend
    workers use for every non-compute route, and the operator surface for
    POST /fleet/roll).

    Aggregation contract:
      * GET /metrics    — every up replica's exposition with a
                          `replica` label injected, one HELP/TYPE per
                          family, plus this process's own series
                          (fleet gauges, frontend supervisor);
      * GET /healthz    — fleet block with per-replica rows; `degraded`
                          whenever any replica is not up (or the
                          frontend pool / any replica says so);
      * GET /status     — fleet block + each replica's own /status row;
      * GET /debug/requests, /debug/perfetto — merged across replicas
                          (perfetto pids are offset per replica so the
                          UI shows which replica served each request);
      * POST /fleet/roll — the zero-loss rolling restart;
      * lifecycle POSTs (/run /pause /reset /load /programs ...) fan out
        to every up replica; everything else proxies to one up replica
        (program-addressed paths ride the hash ring for stickiness).
    """
    ring = HashRing(range(fleet.n))
    rr_counter = [0]
    import re

    from misaka_tpu.runtime import edge as edge_mod
    from misaka_tpu.utils import tsdb as tsdb_mod
    from misaka_tpu.utils import watchdog as watchdog_mod

    # The parent retains its OWN history too (fleet gauges, frontend
    # supervisor, restart counters — the watchdog's replica-restart rule
    # reads them here), next to the replica-merged /debug/series below.
    tsdb_mod.ensure_started()
    watchdog_mod.ensure_started()

    program_re = re.compile(r"^/programs/([^/]+)(/.*)?$")

    # The control server runs the edge chain's AUTH stage only: the
    # operator surface (/fleet/roll, lifecycle fan-out) must reject a bad
    # key HERE — a roll is not proxied, so no replica would — while
    # quota/admission stay with the replica a request lands on (running
    # them here too would double-bill every proxied compute request).
    _kf_path = edge_mod.keyfile_path()
    _auth_on = (
        os.environ.get("MISAKA_EDGE", "1") != "0"
        and os.environ.get("MISAKA_EDGE_AUTH", "1") != "0"
    )
    control_chain = edge_mod.EdgeChain(
        keyfile=edge_mod.KeyFile(_kf_path) if (_kf_path and _auth_on)
        else None,
        quota_enabled=False,
        admission_enabled=False,
        # minted tenant tokens must authenticate HERE too (the operator
        # surface /fleet/roll is exactly what a short-lived admin token
        # is for) — same secret as every replica, zero coordination
        token_secret=edge_mod.token_secret() if _auth_on else None,
    )

    def _merged_series(name: str, window_s: float,
                       labels: dict | None = None) -> list[dict]:
        """One series family across the fleet: every up replica's rows
        with a `replica="<i>"` label injected (the relabeling
        aggregator's discipline, applied to history), plus the parent's
        own local rows.  A `replica` label filter is resolved HERE —
        it selects which replicas to fetch — and never forwarded: the
        replicas' own series carry no replica label (it is injected on
        this side), so forwarding it would match nothing."""
        from urllib.parse import quote, urlencode

        labels = dict(labels or {})
        want_replica = labels.pop("replica", None)
        qs = urlencode({"name": name, "window": f"{window_s:g}s"})
        extra = "".join(
            f"&label={quote(f'{k}={v}')}" for k, v in labels.items()
        )
        slots = [
            s for s in fleet.up_slots()
            if want_replica is None or str(s["idx"]) == want_replica
        ]
        # remote peers ride the same label discipline: their retained
        # history merges under replica="<peer idx>" (peer indices follow
        # the local slots, so the drill-down filter stays unambiguous)
        peers = [
            p for p in fleet._peers
            if p["probe_ok"]
            and (want_replica is None or str(p["idx"]) == want_replica)
        ]
        fetched = _gather(
            slots + peers,
            lambda s: _ReplicaHTTP(
                s["port"], timeout=5.0,
                key=fleet._peer_key if s.get("remote")
                else fleet._internal_token,
                host=s.get("host") or "127.0.0.1",
            ).get_json(f"/debug/series?{qs}{extra}"),
        )
        rows: list[dict] = []
        for slot, payload in zip(slots + peers, fetched):
            if payload is None:
                continue
            for row in payload.get("series", ()):
                row["labels"] = {
                    **row.get("labels", {}), "replica": str(slot["idx"]),
                }
                rows.append(row)
        if want_replica is None:
            # the parent's own series carry no replica label, so any
            # replica filter excludes them by definition
            rows.extend(tsdb_mod.query(name, labels, window_s))
        return rows

    def _gather(slots: list[dict], fn):
        """Apply `fn(slot)` to every slot CONCURRENTLY and return the
        results in slot order (None where fn raised).  The aggregation
        routes must not query replicas serially: one wedged-but-alive
        replica would stall every /metrics scrape by its full timeout —
        monitoring degrading exactly during the grey failure it should
        be showing.  Concurrency bounds the whole fetch to the slowest
        single replica."""
        out: list = [None] * len(slots)

        def run(i: int, slot: dict) -> None:
            try:
                out[i] = fn(slot)
            except Exception:
                out[i] = None

        threads = [
            threading.Thread(target=run, args=(i, s), daemon=True)
            for i, s in enumerate(slots)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return out

    class FleetHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            log.debug(fmt, *args)

        def _reply(self, code: int, data: bytes, ctype: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _text(self, code: int, body: str) -> None:
            self._reply(code, body.encode(), "text/plain; charset=utf-8")

        def _json(self, obj, code: int = 200) -> None:
            self._reply(
                code, (json.dumps(obj) + "\n").encode(), "application/json"
            )

        def _read_body(self) -> bytes:
            length = int(self.headers.get("Content-Length") or 0)
            return self.rfile.read(length) if length else b""

        def _edge_check(self, path: str, method: str) -> bool:
            """The control surface's auth stage; True = admitted.  Call
            AFTER the body is read (keep-alive stays synchronized)."""
            if not control_chain.armed:
                return True
            m = program_re.match(path)
            program = (
                m.group(1).partition("@")[0] if m
                else self.headers.get("X-Misaka-Program") or None
            )
            d = control_chain.check(
                path, method,
                key=edge_mod.key_from_headers(self.headers),
                program=program, values=0,
            )
            if d.reject is None:
                return True
            data = d.reject.message.encode()
            self.send_response(d.reject.status)
            self.send_header("Content-Type", "text/plain; charset=utf-8")
            self.send_header("Content-Length", str(len(data)))
            for k, v in d.reject.headers():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)
            return False

        def _pick_slot(self, path: str) -> dict | None:
            """A healthy replica for a proxied request: hash-ring owner
            for program-addressed paths (stickiness), round-robin
            otherwise."""
            up = fleet.up_slots()
            if not up:
                return None
            m = program_re.match(path)
            if m:
                by_idx = {s["idx"]: s for s in up}
                for idx in ring.lookup(m.group(1).partition("@")[0]):
                    if idx in by_idx:
                        return by_idx[idx]
            if path in _STICKY_ROUTES:
                return min(up, key=lambda s: s["idx"])
            rr_counter[0] += 1
            return up[rr_counter[0] % len(up)]

        def _proxy(self, method: str, body: bytes | None = None) -> None:
            slot = self._pick_slot(self.path.split("?", 1)[0])
            if slot is None:
                self._text(503, "fleet down: no healthy engine replica")
                return
            headers = {}
            for h in ("Content-Type", "X-Misaka-Program", "X-Misaka-Trace",
                      "X-Misaka-Key", "Authorization"):
                v = self.headers.get(h)
                if v:
                    headers[h] = v
            rh = _ReplicaHTTP(slot["port"], timeout=60.0)
            try:
                status, payload, resp_headers = rh.request(
                    method, self.path, body, headers
                )
            except (OSError, http.client.HTTPException) as e:
                # HTTPException too (MSK002): a replica dying MID-response
                # raises BadStatusLine, not an OSError — the router must
                # answer a typed 502 either way, not crash the handler
                self._text(502, f"replica {slot['idx']} unreachable: {e}")
                return
            self.send_response(status)
            ctype = resp_headers.get(
                "Content-Type", "text/plain; charset=utf-8"
            )
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(payload)))
            self.send_header("X-Misaka-Replica", str(slot["idx"]))
            for h in ("X-Misaka-Trace", "Server-Timing", "Deprecation",
                      "Link", "Retry-After", "WWW-Authenticate"):
                v = resp_headers.get(h)
                if v:
                    self.send_header(h, v)
            self.end_headers()
            self.wfile.write(payload)

        def _fanout(self, body: bytes) -> None:
            """Apply one lifecycle POST to every up replica; a uniform
            outcome with the WHOLE fleet reached answers as one replica
            did, anything else is reported per replica — including
            replicas skipped because they were down/draining.  A /pause
            that silently missed a mid-roll replica would leave the
            fleet divergent (that replica free-running against paused
            siblings) behind a success response."""
            states = fleet.slot_states()
            if not any(st == "up" for _, st in states):
                self._text(503, "fleet down: no healthy engine replica")
                return
            headers = {}
            ctype = self.headers.get("Content-Type")
            if ctype:
                headers["Content-Type"] = ctype
            for h in ("X-Misaka-Key", "Authorization"):
                # credentials fan out with the request: every replica's
                # own edge chain authenticates the lifecycle change
                v = self.headers.get(h)
                if v:
                    headers[h] = v

            def apply(slot: dict) -> tuple[int, bytes]:
                rh = _ReplicaHTTP(slot["port"], timeout=60.0)
                try:
                    status, payload, _ = rh.request(
                        "POST", self.path, body, headers
                    )
                except (OSError, http.client.HTTPException) as e:
                    status, payload = 502, str(e).encode()
                return status, payload

            # concurrent like the GET aggregations (_gather): one
            # wedged replica must not stall the fan-out by its full
            # 60s timeout per sibling
            up = [slot for slot, st in states if st == "up"]
            applied = dict(
                zip((s["idx"] for s in up), _gather(up, apply))
            )
            results = []
            ok = True
            skipped = 0
            for slot, st in states:
                if st != "up":
                    results.append({
                        "replica": slot["idx"],
                        "status": 503,
                        "body": f"replica {st}; lifecycle change "
                                f"not applied",
                        "skipped": True,
                    })
                    ok = False
                    skipped += 1
                    continue
                status, payload = (
                    applied.get(slot["idx"])
                    or (502, b"fan-out request failed")
                )
                results.append({
                    "replica": slot["idx"],
                    "status": status,
                    "body": payload[:500].decode(errors="replace"),
                })
                ok = ok and 200 <= status < 300
            if not skipped and (len(results) == 1 or all(
                r["body"] == results[0]["body"]
                and r["status"] == results[0]["status"] for r in results
            )):
                # uniform outcome across the whole fleet: answer exactly
                # what one replica said, success or not (keeps `curl -d
                # value=5 /run` -> "Success" ergonomics, and a
                # fleet-wide 400 "parse error" stays a 400 — rewriting
                # it to 502 would misclassify a bad request as fleet
                # unavailability)
                first = results[0]
                self._text(first["status"], first["body"])
                return
            self._json({"ok": ok, "replicas": results},
                       code=200 if ok else 502)

        def do_GET(self):
            try:
                path = self.path.split("?", 1)[0]
                if not self._edge_check(path, "GET"):
                    return
                if path == "/healthz":
                    st = fleet.state()
                    up_rows = [
                        r for r in st["replicas"] if r["state"] == "up"
                    ]
                    payload = {
                        "ok": st["up"] > 0,
                        "engine": "fleet",
                        # the single-engine /healthz contract: `running`
                        # is the NETWORK run state, not process liveness
                        # — a fully paused fleet must not read as
                        # serving (probed per replica, <= probe_s stale)
                        "running": bool(up_rows) and all(
                            r.get("running") for r in up_rows
                        ),
                        "fleet": st,
                        "degraded": st["degraded"],
                    }
                    sup = getattr(self.server, "misaka_supervisor", None)
                    if sup is not None:
                        fs = sup.state()
                        payload["frontends"] = fs
                        payload["degraded"] = (
                            payload["degraded"] or fs["degraded"]
                        )
                    # parent-side watchdog (replica restart rate) and
                    # canary state, same contract as the engine /healthz
                    wd_state = watchdog_mod.overall_state()
                    if wd_state is not None:
                        payload["watchdog"] = wd_state
                        payload["degraded"] = (
                            payload["degraded"] or wd_state == "page"
                        )
                    from misaka_tpu.runtime import canary as canary_mod

                    cst = canary_mod.state_payload()
                    if cst is not None:
                        payload["canary"] = {
                            "failing_tier": cst["failing_tier"],
                            "consecutive_full_failures":
                                cst["consecutive_full_failures"],
                            "tiers": {
                                t: v.get("ok")
                                for t, v in cst["tiers"].items()
                            },
                        }
                    self._json(payload)
                    return
                if path in ("/fleet", "/fleet/state"):
                    self._json(fleet.state())
                    return
                if path == "/status":
                    st = fleet.state()
                    payload = {
                        "engine": "fleet",
                        "fleet": st,
                        "replicas": {},
                    }
                    sup = getattr(self.server, "misaka_supervisor", None)
                    if sup is not None:
                        payload["frontends"] = sup.state()

                    def fetch_status(slot: dict):
                        rh = _ReplicaHTTP(slot["port"], timeout=5.0,
                                          key=fleet._internal_token)
                        try:
                            return rh.get_json("/status")
                        except (OSError, RuntimeError, ValueError) as e:
                            return {"error": str(e)}

                    slots = fleet.up_slots()
                    for slot, row in zip(
                        slots, _gather(slots, fetch_status)
                    ):
                        payload["replicas"][str(slot["idx"])] = (
                            row if row is not None else {"error": "fetch"}
                        )
                    self._json(payload)
                    return
                if path == "/metrics":
                    sample_lines: list[str] = []
                    header_seen: dict[str, str] = {}
                    slots = fleet.up_slots()
                    fetched = _gather(
                        slots,
                        lambda s: _ReplicaHTTP(
                            s["port"], timeout=5.0
                        ).request("GET", "/metrics"),
                    )
                    for slot, resp in zip(slots, fetched):
                        if resp is None:
                            continue
                        status, body, _ = resp
                        if status != 200:
                            continue
                        samples, headers = relabel_metrics_text(
                            body.decode(errors="replace"), slot["idx"]
                        )
                        sample_lines.extend(samples)
                        for h in headers:
                            header_seen.setdefault(h, h)
                    # the parent's own series (fleet gauges, frontend
                    # supervisor, build info) ride unlabeled — but their
                    # HELP/TYPE lines dedupe against the replica
                    # headers, since both sides register many of the
                    # same families and a second TYPE line for one name
                    # is invalid exposition
                    for line in metrics.render().splitlines():
                        if line.startswith("#"):
                            header_seen.setdefault(line, line)
                        elif line.strip():
                            sample_lines.append(line)
                    out = []
                    out.extend(header_seen.values())
                    out.extend(sample_lines)
                    self._send_metrics("\n".join(out))
                    return
                if path == "/debug/requests":
                    merged = {"recent": [], "slowest": [], "replicas": {}}
                    qs = ("?" + self.path.split("?", 1)[1]
                          if "?" in self.path else "")
                    slots = fleet.up_slots()
                    fetched = _gather(
                        slots,
                        lambda s: _ReplicaHTTP(
                            s["port"], timeout=5.0,
                            key=fleet._internal_token,
                        ).get_json("/debug/requests" + qs),
                    )
                    for slot, payload in zip(slots, fetched):
                        if payload is None:
                            continue
                        for key in ("recent", "slowest"):
                            for row in payload.get(key, ()):
                                row["replica"] = slot["idx"]
                                merged[key].append(row)
                        merged["replicas"][str(slot["idx"])] = {
                            "enabled": payload.get("enabled"),
                        }
                    merged["slowest"].sort(
                        key=lambda r: -(r.get("duration_ms") or 0)
                    )
                    self._json(merged)
                    return
                if path == "/debug/perfetto":
                    events = []
                    slots = fleet.up_slots()
                    fetched = _gather(
                        slots,
                        lambda s: _ReplicaHTTP(
                            s["port"], timeout=10.0,
                            key=fleet._internal_token,
                        ).get_json("/debug/perfetto"),
                    )
                    for slot, payload in zip(slots, fetched):
                        if payload is None:
                            continue
                        base = (slot["idx"] + 1) * 100
                        for ev in payload.get("traceEvents", ()):
                            if "pid" in ev:
                                ev["pid"] = base + int(ev["pid"])
                            if (
                                ev.get("ph") == "M"
                                and ev.get("name") == "process_name"
                            ):
                                ev["args"]["name"] = (
                                    f"replica {slot['idx']} · "
                                    f"{ev['args'].get('name', '')}"
                                )
                            events.append(ev)
                    self._json({"traceEvents": events,
                                "displayTimeUnit": "ms"})
                    return
                if path == "/debug/alerts":
                    # one replica's SLO/watchdog view (sticky, like the
                    # flamegraph) PLUS the parent's own watchdog —
                    # replica restart-rate and fleet-canary rules fire
                    # HERE, and proxying alone would hide them
                    slot = self._pick_slot(path)
                    payload = {}
                    if slot is not None:
                        try:
                            payload = _ReplicaHTTP(
                                slot["port"], timeout=5.0,
                                key=fleet._internal_token,
                            ).get_json("/debug/alerts")
                            payload["replica"] = slot["idx"]
                        except (OSError, RuntimeError, ValueError):
                            payload = {}
                    from misaka_tpu.utils import tracespan

                    wd = watchdog_mod.debug_payload()
                    for rule in wd.get("rules", ()):
                        if rule.get("state") != "ok":
                            rule["exemplars"] = \
                                tracespan.slowest_exemplars()
                    payload["fleet_watchdog"] = wd
                    self._json(payload)
                    return
                if path == "/debug/series":
                    # replica-merged history: every replica's series
                    # under replica="<i>" labels + the parent's own
                    from urllib.parse import parse_qs

                    try:
                        name, labels, window_s = tsdb_mod.parse_query(
                            parse_qs(
                                self.path.split("?", 1)[1]
                                if "?" in self.path else ""
                            )
                        )
                    except tsdb_mod.TSDBError as e:
                        self._text(400, str(e))
                        return
                    if name is None:
                        merged = tsdb_mod.index_payload()
                        merged["replicas"] = {}
                        slots = fleet.up_slots()
                        for slot, payload in zip(slots, _gather(
                            slots,
                            lambda s: _ReplicaHTTP(
                                s["port"], timeout=5.0,
                                key=fleet._internal_token,
                            ).get_json("/debug/series"),
                        )):
                            if payload is None:
                                continue
                            for n, c in payload.get("names", {}).items():
                                merged["names"][n] = (
                                    merged["names"].get(n, 0) + c
                                )
                            merged["replicas"][str(slot["idx"])] = {
                                "series_count":
                                    payload.get("series_count", 0),
                                "dropped_series":
                                    payload.get("dropped_series", 0),
                            }
                        self._json(merged)
                        return
                    self._json({
                        "name": name,
                        "window_s": window_s,
                        "series": _merged_series(name, window_s, labels),
                    })
                    return
                if path == "/usage/export":
                    # fleet-hub billing aggregation: every up replica's
                    # and remote peer's SIGNED export lines verbatim
                    # (signatures stay verifiable end-to-end — the hub
                    # cannot forge what it never re-signs), each source
                    # introduced by an unsigned {"kind":"source"}
                    # envelope, plus the gossip hub's fleet-wide
                    # cumulative counters as a trailing summary
                    from urllib.parse import parse_qs as _pq

                    q = _pq(self.path.split("?", 1)[1]
                            if "?" in self.path else "")
                    since = (q.get("since") or ["0"])[0]
                    sources = fleet.up_slots() + [
                        p for p in fleet._peers if p["probe_ok"]
                    ]
                    fetched = _gather(
                        sources,
                        lambda s: _ReplicaHTTP(
                            s["port"], timeout=10.0,
                            key=fleet._peer_key if s.get("remote")
                            else fleet._internal_token,
                            host=s.get("host") or "127.0.0.1",
                        ).request(
                            "GET", f"/usage/export?since={since}"
                        ),
                    )
                    out: list[str] = []
                    for src, got in zip(sources, fetched):
                        envelope = {
                            "kind": "source",
                            "replica": str(src["idx"]),
                            "remote": bool(src.get("remote")),
                            "ok": bool(got and got[0] == 200),
                        }
                        out.append(json.dumps(
                            envelope, separators=(",", ":")
                        ))
                        if got and got[0] == 200:
                            out.extend(
                                ln for ln in
                                got[1].decode(errors="replace").splitlines()
                                if ln.strip()
                            )
                    out.append(json.dumps({
                        "kind": "fleet_gossip",
                        "sources": {
                            k: dict(v)
                            for k, v in fleet._gossip_seen.items()
                        },
                    }, separators=(",", ":")))
                    self._reply(
                        200, ("\n".join(out) + "\n").encode(),
                        "application/x-ndjson",
                    )
                    return
                if path == "/debug/dashboard":
                    # the same self-contained page the engine serves,
                    # over the replica-merged series: the `replica`
                    # label filter becomes the per-replica drill-down
                    from urllib.parse import parse_qs

                    from misaka_tpu.runtime import canary as canary_mod
                    from misaka_tpu.utils import dashboard as dash_mod

                    q = {
                        k: v[0] for k, v in parse_qs(
                            self.path.split("?", 1)[1]
                            if "?" in self.path else ""
                        ).items()
                    }
                    try:
                        window_s = tsdb_mod.parse_window(
                            q.get("window", "1h")
                        )
                    except tsdb_mod.TSDBError as e:
                        self._text(400, str(e))
                        return
                    extra = {"watchdog": watchdog_mod.debug_payload()}
                    cst = canary_mod.state_payload()
                    if cst is not None:
                        extra["canary"] = cst
                    html = dash_mod.render_html(
                        _merged_series, window_s, extra
                    )
                    self._reply(
                        200, html.encode(), "text/html; charset=utf-8"
                    )
                    return
                # anything else: proxy to one healthy replica
                self._proxy("GET")
            except Exception as e:  # defensive: never kill the server
                log.exception("fleet handler error")
                try:
                    self._text(500, f"internal error: {e}")
                except Exception:
                    pass

        def _send_metrics(self, text: str) -> None:
            if not text.endswith("\n"):
                text += "\n"
            self._reply(200, text.encode(), metrics.CONTENT_TYPE)

        def do_POST(self):
            try:
                path = self.path.split("?", 1)[0]
                body = self._read_body()
                if not self._edge_check(path, "POST"):
                    return
                if path == "/fleet/drain":
                    # replica-internal roll control: proxying it would
                    # arm drain on a ROUND-ROBIN replica the caller
                    # cannot target again to undrain — capacity lost
                    # until a roll or restart.  The roll drives drain on
                    # each replica's own loopback port directly.
                    self._text(
                        400,
                        "/fleet/drain is replica-internal (the roll "
                        "protocol drives it); use POST /fleet/roll",
                    )
                    return
                if path == "/fleet/roll":
                    try:
                        report = fleet.roll()
                    except RuntimeError as e:
                        code = (
                            409 if "already in progress" in str(e) else 500
                        )
                        self._text(code, f"rolling restart failed: {e}")
                        return
                    self._json(report)
                    return
                if path in _FANOUT_ROUTES:
                    self._fanout(body)
                    return
                self._proxy("POST", body)
            except Exception as e:
                log.exception("fleet handler error")
                try:
                    self._text(500, f"internal error: {e}")
                except Exception:
                    pass

    return ThreadingHTTPServer((host, port), FleetHandler)


# --- app entrypoint ---------------------------------------------------------


def run_fleet(n: int, environ=None) -> None:
    """`MISAKA_FLEET=N` entrypoint (called by runtime/app.py): spawn and
    supervise N engine replicas, the frontend worker tier routing across
    them, and the fleet control server; serve until signalled."""
    environ = dict(os.environ if environ is None else environ)
    from misaka_tpu.runtime import frontends
    from misaka_tpu.runtime.lifecycle import install_guards
    from misaka_tpu.utils import buildinfo

    buildinfo.install_metric()
    public_port = int(environ.get("MISAKA_PORT", "8000"))
    fleet_dir = (
        environ.get("MISAKA_FLEET_DIR")
        or environ.get("MISAKA_CHECKPOINT_DIR")
        or f"/tmp/misaka-fleet-{os.getpid()}"
    )
    fleet = FleetManager(
        n,
        fleet_dir,
        base_env=environ,
        probe_s=float(environ.get("MISAKA_FLEET_PROBE_S", "0.5") or 0.5),
        drain_timeout_s=float(
            environ.get("MISAKA_FLEET_DRAIN_S", "30") or 30
        ),
    )
    install_guards(fleet.close, environ)
    log.info("booting %d engine replicas under %s", fleet.n, fleet_dir)
    fleet.start(wait_ready=True)

    server = make_fleet_http_server(fleet, port=0)
    control_port = server.server_address[1]
    # The frontend tier is the public surface: default it ON in fleet
    # mode (a fleet without frontends would serve nothing).
    workers = int(
        environ.get("MISAKA_HTTP_WORKERS", "") or max(2, fleet.n)
    )
    # Plane connections per (worker, replica) pair: the fleet default is
    # 1 for a multi-replica fleet — frame pipelining already comes from
    # having N replicas, and a second connection per replica only splits
    # each worker's backlog into smaller frames (measured: the 4-replica
    # 64-client lane coalesces ~30% more values/s at 1 conn than 2).
    # The single-plane default stays 2 (there, a second in-flight frame
    # is the only pipelining).  MISAKA_PLANE_CONNS overrides either way.
    plane_conns = int(
        environ.get("MISAKA_PLANE_CONNS", "")
        or (1 if fleet.n > 1 else 2)
    )
    supervisor = frontends.FrontendSupervisor(
        workers,
        public_port,
        f"http://127.0.0.1:{control_port}",
        ",".join(fleet.plane_paths()),
        plane_conns=plane_conns,
        fleet=True,  # a 1-replica fleet still needs the reroute grace
    )
    server.misaka_supervisor = supervisor
    log.info(
        "fleet up: %d replicas, control on 127.0.0.1:%d, %d frontend "
        "workers on :%d", fleet.n, control_port, workers, public_port,
    )
    # The fleet-level canary (runtime/canary.py): probes the PUBLIC
    # endpoint — edge through the frontend tier, full-stack through the
    # router to a replica — with the per-boot internal (admin) token.
    # Full-stack only when the replicas run registries; the parent
    # registers the program over the fanned-out POST /programs.
    from misaka_tpu.runtime import canary as canary_mod

    scheme = "https" if environ.get("MISAKA_TLS_CERT") else "http"
    canary_mod.ensure_started(
        f"{scheme}://127.0.0.1:{public_port}",
        token=fleet._internal_token,
        full_stack=bool(environ.get("MISAKA_PROGRAMS_DIR")),
        environ=environ,
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        supervisor.close()
        fleet.close()
