"""Process entrypoint: env-var bootstrap compatible with the reference's cmd/app.go.

The reference starts one process per node, dispatched on NODE_TYPE
(cmd/app.go:12-40).  The TPU build fuses the whole network into one process,
so the master's env surface is what survives:

  NODE_INFO        {"name": {"type": "program"|"stack"}, ...}  (app.go:30-35)
  MISAKA_PROGRAMS  {"name": "<TIS source>", ...}   per-program-node source —
                   replaces the per-container PROGRAM env (app.go:20-25)
  MISAKA_TOPOLOGY  path to a single declarative JSON file
                   {"nodes": ..., "programs": ...} (alternative to the above)
  MISAKA_PORT      HTTP port (default 8000 = clientPort, master.go:19)
  MISAKA_AUTORUN   "1" to start running immediately (default: wait for /run)
  MISAKA_CHECKPOINT_DIR  enable HTTP /checkpoint & /restore, storing named
                   .npz snapshots in this directory (disabled when unset)

NODE_TYPE=program / NODE_TYPE=stack have no fused-mode meaning: those
processes' entire job (interpret asm / hold a stack) lives inside the jitted
kernel.  Setting them exits with an explanatory error rather than pretending.

Run: python -m misaka_tpu.runtime.app
"""

from __future__ import annotations

import json
import logging
import os
import sys

from misaka_tpu.runtime.master import MasterNode, make_http_server
from misaka_tpu.runtime.topology import Topology


def build_topology_from_env(environ=os.environ) -> Topology:
    path = environ.get("MISAKA_TOPOLOGY")
    if path:
        with open(path) as f:
            return Topology.from_json(f.read())
    node_info = environ.get("NODE_INFO")
    if not node_info:
        raise SystemExit(
            "set NODE_INFO (reference JSON shape) or MISAKA_TOPOLOGY (file path)"
        )
    programs = json.loads(environ.get("MISAKA_PROGRAMS", "{}"))
    return Topology.from_node_info_json(node_info, programs)


def main() -> None:
    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(name)s %(message)s"
    )
    node_type = os.environ.get("NODE_TYPE", "master")
    if node_type != "master":
        raise SystemExit(
            f"NODE_TYPE={node_type!r}: program/stack nodes are lanes of the "
            "fused TPU kernel, not processes; run the master (NODE_TYPE=master)"
        )
    topology = build_topology_from_env()
    master = MasterNode(topology)
    if os.environ.get("MISAKA_AUTORUN") == "1":
        master.run()
    port = int(os.environ.get("MISAKA_PORT", "8000"))
    server = make_http_server(
        master, port, checkpoint_dir=os.environ.get("MISAKA_CHECKPOINT_DIR")
    )
    logging.getLogger("misaka_tpu.app").info("starting http server on :%d", port)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        master.pause()
        sys.exit(0)


if __name__ == "__main__":
    main()
