"""Process entrypoint: env-var bootstrap compatible with the reference's cmd/app.go.

The reference starts one process per node, dispatched on NODE_TYPE
(cmd/app.go:12-40).  The TPU build fuses the whole network into one process,
so the master's env surface is what survives:

  NODE_INFO        {"name": {"type": "program"|"stack"}, ...}  (app.go:30-35)
  MISAKA_PROGRAMS  {"name": "<TIS source>", ...}   per-program-node source —
                   replaces the per-container PROGRAM env (app.go:20-25)
  MISAKA_TOPOLOGY  path to a single declarative JSON file
                   {"nodes": ..., "programs": ...} — or a reference-style
                   docker-compose .yml, imported directly (runtime/compose.py)
  MISAKA_PORT      HTTP port (default 8000 = clientPort, master.go:19)
  MISAKA_PIDFILE   pidfile path for external supervisors; default is
                   <tmpdir>/misaka-app-<pid>.pid — never the CWD, so a
                   server booted from a source checkout leaves the tree
                   clean.  "0"/"off" disables the file.  Removed at exit.
  MISAKA_HTTP_WORKERS  N > 0 starts the multi-process serving plane
                   (runtime/frontends.py): N frontend worker processes
                   share MISAKA_PORT via SO_REUSEPORT, coalesce their
                   concurrent compute requests locally, and feed fused
                   frames to this engine over a unix-socket compute plane
                   (MISAKA_PLANE_SOCKET, MISAKA_PLANE_CONNS per worker,
                   MISAKA_PLANE_WINDOW_US coalesce window); non-compute
                   routes proxy to the engine's own server.  Default 0 =
                   single-process serving, exactly as before.
  MISAKA_NATIVE_EDGE  with MISAKA_HTTP_WORKERS > 0: 1 (default) puts the
                   C++ epoll edge (native/frontend.cpp via
                   runtime/frontends.NativeFrontendSupervisor) on the
                   public port — HTTP keep-alive and the MSK1 binary
                   protocol terminate in C++ with no GIL on the data
                   path, hot compute routes ship plane frames directly,
                   everything else proxies to the CPython workers (moved
                   to a loopback port).  0, any build/start failure, or
                   an armed MISAKA_TLS_CERT (the native tier does not
                   terminate TLS) falls back to the worker tier on the
                   public port, exactly the r8 topology
  MISAKA_NATIVE_EDGE_THREADS  native edge event-loop threads (default
                   min(8, cores/2), floor 2)
  MISAKA_NATIVE_EDGE_MAX_CONNS  per-process open client connection cap on
                   the native edge (default 4096; excess connects are
                   accepted-and-closed)
  MISAKA_FLEET     N >= 1 starts the replicated engine fleet
                   (runtime/fleet.py): this process supervises N engine
                   replica subprocesses (each with its own native pool
                   and serve scheduler) and the frontend workers route
                   across them — least-queue-depth for stateless
                   compute, consistent hashing on program ID for
                   registry traffic, per-replica health gating
                   admission, failed frames hedged onto siblings, and a
                   typed 503 only when the whole fleet is down.  POST
                   /fleet/roll performs a zero-loss rolling restart
                   (drain -> manifest-verified checkpoint -> replace ->
                   bit-identical restore, one replica at a time);
                   /metrics aggregates every replica with a `replica`
                   label; /status + /healthz carry per-replica rows.
                   Knobs: MISAKA_FLEET_DIR (replica state + plane
                   sockets; defaults to MISAKA_CHECKPOINT_DIR or /tmp),
                   MISAKA_FLEET_PROBE_S (health probe cadence, 0.5),
                   MISAKA_FLEET_DRAIN_S (per-replica drain budget in a
                   roll, 30), MISAKA_FLEET_DOWN_GRACE_S (how long the
                   router rides out a whole-fleet outage before the
                   typed 503, 5).  Default 0 = single-engine serving,
                   exactly as before.  (MISAKA_PLANE_SERVE=1,
                   MISAKA_FLEET_REPLICA, and MISAKA_FLEET_RESTORE are
                   the fleet's internal replica-side envs.)
  MISAKA_SERVE_BATCH  "0" disables the in-engine serve scheduler
                   (ServeBatcher): requests then claim instance slots
                   directly (the pre-r8 behavior).  Scheduler knobs:
                   MISAKA_BATCH_WINDOW_US (extra coalesce window while a
                   pass is in flight, default 0 = purely adaptive),
                   MISAKA_BATCH_MAX (values per fused pass, default
                   B x in_cap), MISAKA_BATCH_PASSES (dispatcher workers,
                   default min(4, B))
  MISAKA_MAX_BODY  request-body ceiling for the bulk lanes in bytes
                   (default 64 MiB; oversized bodies answer 413, a
                   missing Content-Length on /compute_raw answers 411)
  MISAKA_FAST_HTTP "0" restores the stock stdlib HTTP request parser
                   (default: the serving-plane fast parser, ~100us less
                   Python per request)
  MISAKA_AUTORUN   "1" to start running immediately (default: wait for /run)
  MISAKA_BATCH     run N independent network instances in lockstep and serve
                   concurrent /compute requests round-robin across them
                   (default: one instance, strictly serialized /compute)
  MISAKA_CHUNK_STEPS  device-loop ticks per engine call (default 128;
                   serving deployments tune up — the committed bench
                   harness runs 2048 for fewer round trips per pass)
  MISAKA_ENGINE    device-loop chunk runner: "auto" (default — the fused
                   Pallas kernel when batched+untraced+on-TPU+within budget;
                   the native C++ host tier when NO TPU is attached and a
                   toolchain exists [MISAKA_NATIVE_AUTO=0 disables,
                   MISAKA_NATIVE_AUTO_MAX_BATCH caps it, default 4096];
                   the XLA scan engine otherwise), "scan", "fused" (require
                   the kernel), "fused-interpret" (CI coverage off-TPU),
                   "gather" (model-parallel only: the first-generation
                   occupancy-gather sharded kernel, kept for A/B runs
                   against the default statically-routed kernel), "native"
                   (the host C++ interpreter, core/native_serve.py — zero
                   device dispatches per /compute; unbatched = the
                   interactive-latency tier, MISAKA_BATCH=B = B replicas
                   sharded over OS threads [MISAKA_NATIVE_THREADS], the
                   host throughput tier; single-chip, needs g++)
  MISAKA_SIMD      native-tier execution ladder (r16): "auto"/unset = SIMD
                   struct-of-arrays group ticks, 8 replicas per AVX2 lane
                   when the CPU has AVX2; "generic" = the same group engine
                   without AVX2 codegen (the forced feature-detection
                   fallback); "0"/"off" = the shipped scalar per-replica
                   interpreter.  Every rung is bit-identical
                   (tests/test_simd.py); /status.native shows the live rung
  MISAKA_SPECIALIZE  "0" disables per-program specialized native ticks
                   (core/specialize.py: registry activation — and the boot
                   engine — compile the program's tables into a cached
                   per-program interpreter .so; any failure falls back to
                   the generic engine with
                   misaka_native_specialize_total{status} counting why)
  MISAKA_SPEC_CACHE  specialization compile-cache dir for the boot engine
                   (default: a per-user tmp dir; the registry caches next
                   to its version store instead)
  MISAKA_SPEC_CACHE_MAX_MB / MISAKA_SPEC_CACHE_MAX_ENTRIES
                   size/entry LRU bound on the specialization disk cache
                   (defaults 256 MiB / 64 entries; evictions count on
                   misaka_specialize_cache_evictions_total — r17)
  MISAKA_SPEC_SWITCH_MAX  total-instruction budget for the generated
                   switch-threaded specialized tick (default 4096; over
                   budget keeps the table-baked generic tick, 0 disables
                   the switch layer — r17)
  MISAKA_NATIVE_RESIDENT  "0" disables resident-state native serving
                   (r17): every serve call then pays the full state
                   import/export round trip like r16.  Default on; the
                   resident_fallback chaos point forces per-call
                   fallback; misaka_native_resident_total counts
                   hit/miss/export/fallback
  MISAKA_POOL_SPIN_US  native pool dispenser spin budget in microseconds
                   before a worker parks on the futex (default 50 — r17)
  MISAKA_NATIVE_TRACE  "0" disables the native flight recorder (r18):
                   bounded lock-free per-thread event rings inside the
                   C++ pool journal serve-call lifecycle, dispenser
                   phases (spin/yield/park), per-unit rung-tagged tick
                   execution, and residency events — dumped raw at GET
                   /debug/native_trace, unified with request traces in
                   GET /debug/perfetto (worker-thread unit spans under
                   the same X-Misaka-Trace ID), and derived into
                   misaka_native_dispenser_* / misaka_native_units_* /
                   misaka_native_caller_inline_units_total metrics.
                   Default on (overhead A/B'd >= 0.95; docs/
                   OBSERVABILITY.md "Native flight recorder")
  MISAKA_NATIVE_TRACE_RING  records per per-thread ring (default 2048,
                   32 B each = 64 KiB/thread; oldest dropped, counted on
                   misaka_native_trace_dropped_total)
  MISAKA_PLANE_PIPELINE  max in-flight frames per compute-plane
                   connection, BOTH ends (default 4; 1 restores the r16
                   ping-pong; the shm plane always runs depth 1 — r17)
  MISAKA_PLANE_SHM "1" = zero-copy compute plane: frontend workers ship
                   frame payloads through one shared-memory segment per
                   plane connection instead of unix-socket copies (frame
                   headers, metadata, secret handshake, drain/probe/hedge
                   semantics stay on the socket; a pre-shm engine or a
                   box without /dev/shm silently keeps socket payloads —
                   misaka_plane_shm_frames_total proves engagement).
                   Default off
  MISAKA_CLIENT_WIRE  client-side: "text" forces MisakaClient's bulk lanes
                   back to the decimal forms ("auto" default probes
                   /healthz wire_binary and speaks the headered binary
                   protocol, utils/wire.py)
  MISAKA_DATA_PARALLEL   shard the batch axis over N chips (requires
                   MISAKA_BATCH divisible by N); MISAKA_MODEL_PARALLEL
                   shards program-node lanes over M chips via the ICI-
                   collective engine (parallel/sharded.py).  Together they
                   serve over a (data=N, model=M) jax.sharding.Mesh of N*M
                   devices — the replacement for compose scale-out
                   (docker-compose.yml:26-74); /status reports the mesh
  MISAKA_CHECKPOINT_DIR  enable HTTP /checkpoint & /restore, storing named
                   .npz snapshots in this directory (disabled when unset;
                   fused master only — per-process nodes hold their own
                   state, which the distributed master cannot snapshot).
                   Every save is durable: tmp + fsync + atomic replace,
                   plus a size/sha256 manifest that load verifies — a
                   torn or corrupt file is rejected, never installed
  MISAKA_AUTOCKPT  N > 0: snapshot the live state into
                   MISAKA_CHECKPOINT_DIR every N seconds as auto-*.npz,
                   keeping the newest MISAKA_AUTOCKPT_KEEP (default 4);
                   at boot the newest VALID auto snapshot is restored
                   automatically (corrupt ones are skipped, falling back
                   to older snapshots) — crash recovery without operator
                   intervention
  MISAKA_PROGRAMS_DIR  arm the program registry (runtime/registry.py):
                   persistent store for uploaded/versioned TIS networks.
                   POST /programs uploads (TIS source, topology JSON, or
                   compose YAML), content-addressed + versioned
                   (name@<sha12>, mutable name@latest alias); compute
                   routes gain program addressing (POST
                   /programs/<name>/compute[_batch|_raw] and the
                   X-Misaka-Program header on the legacy routes, which
                   default to the boot network, seeded as program
                   MISAKA_DEFAULT_PROGRAM [default "default"]).  Each
                   active program serves on its own engine;
                   MISAKA_REGISTRY_MAX_ACTIVE (default 4) caps live
                   engines with LRU eviction through the durable
                   checkpoint path (state restores bit-identically on
                   re-activation).  Publishing a new version under live
                   traffic hot-swaps with zero client-visible errors
                   (MISAKA_SWAP_DRAIN_S bounds the old engine's drain,
                   default 30).  Unset = the single-program surface,
                   exactly as before
  MISAKA_FAULTS    chaos harness (utils/faults.py): arm named fault
                   points, e.g. "worker_exit=2,ckpt_torn_write=0.5,
                   rpc_drop@0.01,swap_during_load=0.3" — `make
                   chaos-smoke` drives the recovery paths with it; leave
                   unset in production
  MISAKA_TRACE_CAP enable the per-lane instruction trace ring (core/trace.py)
                   with this many ticks of history; decoded listings served
                   at GET /debug/isa_trace?last=N (GET /trace is a
                   deprecated alias; disabled when unset; debug path —
                   recording costs one extra store per tick and forces the
                   scan engine).  With MISAKA_BATCH, traces the instance
                   selected by MISAKA_TRACE_INSTANCE (default 0)
  MISAKA_TRACE_REQUESTS  "0" kills per-REQUEST distributed tracing
                   (utils/tracespan.py; default on — every request gets a
                   trace ID honoring an inbound X-Misaka-Trace header, a
                   span tree across frontend/plane/scheduler/rpc hops, a
                   Server-Timing response header, and a slot in the
                   flight recorder served at GET /debug/requests +
                   GET /debug/perfetto).  MISAKA_TRACE_SAMPLE thins root
                   traces (default 1.0), MISAKA_TRACE_RING /
                   MISAKA_TRACE_SLOWEST bound the recorder (256 / 32);
                   docs/OBSERVABILITY.md "Request tracing"
  MISAKA_CAPTURE   "0" kills the wire-level capture/replay plane
                   (runtime/capture.py; default available, disarmed):
                   POST /captures/start (admin) records raw
                   request/response payload bytes at every serving
                   surface (engine routes, CPython workers, C++ edge)
                   plus a per-program anchor checkpoint, so the window
                   replays byte-for-byte — offline via tools/replay.py
                   (`misaka_tpu replay`), and as a deploy gate via
                   POST /programs?verify=replay (divergence = 409 with
                   per-request diffs, nothing swapped).
                   docs/OBSERVABILITY.md "Traffic capture & shadow
                   replay"
  MISAKA_CAPTURE_MB  capture ring memory budget in MiB (default 64);
                   overrun evicts oldest-first and counts
                   misaka_capture_dropped_total — a flood costs
                   history, never memory
  MISAKA_CAPTURE_SAMPLE  uniform share of requests recorded while armed
                   (default 1.0); an inbound X-Misaka-Trace bypasses
                   sampling on every surface, so a targeted repro is
                   always captured
  MISAKA_CAPTURE_DIR  default directory for POST /captures/export
                   segments (default "captures/" under the CWD)
  MISAKA_REPLAY_VERIFY_MAX  most-recent captured records the
                   ?verify=replay deploy gate replays (default 256)
  MISAKA_NATIVE_CODEC  /compute_batch decimal codec backend: unset = auto
                   (native C++ when a toolchain exists), "0" = numpy,
                   "1" = require native (utils/textcodec.py)
  MISAKA_PROFILE_DIR  enable jax.profiler capture of the live device loop via
                   POST /profile/start + /profile/stop, traces written under
                   this directory (disabled when unset)
  MISAKA_LOG_JSON  "1" for structured JSON logging (utils/jsonlog.py): one
                   JSON object per line with time/level/logger/msg, the
                   HTTP route, trace_id, and the registry program where a
                   request is in scope, so container log pipelines parse
                   server logs without grok rules.  MISAKA_SLOW_REQ_MS=N
                   auto-emits a warning line (trace ID + program attached)
                   for any request over N ms.  The metrics plane itself is
                   always on: GET /metrics serves Prometheus text
                   exposition, GET /healthz cheap liveness
                   (docs/OBSERVABILITY.md has the catalog)
  MISAKA_SLO       declare service objectives, e.g. "p99<25ms,err<0.1%"
                   (utils/slo.py): per-program sliding-window latency
                   quantiles + error rates feed a multi-window burn-rate
                   engine — ok/warning/page states at GET /debug/alerts,
                   page => /healthz `degraded`, misaka_slo_* gauges on
                   /metrics.  Per-program overrides ride the registry
                   (`slo` field on POST /programs).  Knobs:
                   MISAKA_SLO_WINDOWS (default "10,60,300,3600" seconds),
                   MISAKA_SLO_MIN_EVENTS (default 10).  Unset + no
                   overrides = the engine is disarmed, zero serving cost
  MISAKA_USAGE     "0" disables per-program usage accounting
                   (runtime/usage.py; default on): values/requests,
                   CPU-seconds split across requests by slot share,
                   MEASURED native-pool seconds, and queue-delay seconds
                   per program — GET /debug/usage, `usage` blocks in
                   GET /programs, misaka_usage_* counters
                   (MISAKA_USAGE_LABEL_MAX caps label cardinality, 64)
  MISAKA_SAMPLER   "0" disables the always-on continuous profiler
                   (utils/sampler.py; default on): ~67 Hz all-thread
                   stack sampling into a decayed folded-stack aggregate,
                   served at GET /debug/flamegraph (?html=1 for the
                   self-contained viewer) with the native pool's measured
                   busy/idle split alongside.  Knobs: MISAKA_SAMPLER_HZ,
                   MISAKA_SAMPLER_MAX_STACKS (4096),
                   MISAKA_SAMPLER_DECAY_S (120), MISAKA_SAMPLER_BUDGET
                   (0.02 — the duty-cycle cap: the sampler measures its
                   own per-sample cost and stretches its period to stay
                   under this fraction of one core)
  MISAKA_TSDB      "0" disables the embedded time-series history
                   (utils/tsdb.py; default on): a governed collector
                   samples the metrics registry every
                   MISAKA_TSDB_INTERVAL_S (5) into staged rings
                   (interval x 720 / 1m x 360 / 5m x 288 — 1h/6h/24h),
                   counters as rates, histograms as :p50/:p99/:rate
                   series, queried at GET /debug/series and drawn at
                   GET /debug/dashboard (self-contained sparklines,
                   per-program/per-replica drill-down).  Bounded:
                   MISAKA_TSDB_MAX_SERIES (512; ~38 KiB each, ~20 MiB
                   worst case, overflow dropped LOUDLY) and
                   MISAKA_TSDB_BUDGET (0.01 duty-cycle cap, sampler
                   discipline).  History snapshots into checkpoints
                   (strictly-newer merge on restore), so /debug/series
                   survives a /fleet/roll.
  MISAKA_TSDB_DIR  arm the DURABLE telemetry plane (unset = today's
                   in-memory behavior, byte-identical).  The TSDB
                   collector spools finalized ring slots to fsync'd
                   length-prefixed segments under this directory (torn
                   tails truncated on reopen), adds a coarse
                   long-horizon tier (MISAKA_TSDB_LONG_S, default 300s
                   slots x MISAKA_TSDB_LONG_SLOTS, default 4032 = two
                   weeks), and reloads both at boot — /debug/series
                   answers window=7d across restarts and kill -9.
                   Knobs: MISAKA_TSDB_DISK_MB (64; oldest segments
                   evicted LOUDLY via misaka_tsdb_spool_dropped_total),
                   MISAKA_TSDB_SEG_KB (1024, rotation size).  The same
                   switch arms the usage ledger spool under
                   <dir>/usage (MISAKA_USAGE_SPOOL=0 opts out;
                   MISAKA_USAGE_DISK_MB 16, MISAKA_USAGE_SEG_KB 256,
                   MISAKA_USAGE_FLUSH_S 15) and the always-on capture
                   spool under <dir>/capture (MISAKA_CAPTURE_SPOOL=0
                   opts out; MISAKA_CAPTURE_DISK_MB 256,
                   MISAKA_CAPTURE_SEG_KB 4096, MISAKA_CAPTURE_SEG_S
                   300; rotated spool-<seq>.mskcap segments replay
                   independently, POST /captures/rotate cuts one on
                   demand, MISAKA_REPLAY_HISTORY (2) widens
                   ?verify=replay over the newest rotated segments).
                   Billing: GET /usage/export serves HMAC-signed JSONL
                   periods (secret: MISAKA_USAGE_SECRET, else the
                   MISAKA_PLANE_SECRET[_FILE] plane secret), verified
                   by `misaka_tpu usage-report --secret ...`; fleet
                   hubs aggregate replicas + remote peers verbatim.
                   docs/OBSERVABILITY.md "Durable telemetry"
  MISAKA_CANARY    "0" disables the synthetic canary (runtime/canary.py;
                   default on when serving via this entrypoint): every
                   MISAKA_CANARY_INTERVAL_S (5) it probes /healthz, the
                   compute plane, a direct engine compute, and the FULL
                   public stack with the pinned known-answer program
                   `_canary`, attributing a failure to the first broken
                   tier (the `canary` block on /healthz,
                   misaka_canary_* series).  Canary traffic bills to
                   the exempt `_canary` usage account and never feeds
                   SLO windows.
  MISAKA_WATCHDOG  regression watchdog rules over the TSDB
                   (utils/watchdog.py; "0" disables, unset = defaults:
                   canary failing 15s pages, p99 2x over its own 1h
                   median for 5m warns, replica restarts >4/h warn).
                   Grammar: "[name=]series[{label=value}] <|> N[x@win]
                   [for Ns] [->warning|page]", comma-separated; findings
                   ride GET /debug/alerts with exemplar trace IDs and a
                   page raises the /healthz degraded flag.  Knobs:
                   MISAKA_WATCHDOG_RECENT_S (60),
                   MISAKA_WATCHDOG_MIN_POINTS (5).
                   POST /debug/faults (admin) re-arms MISAKA_FAULTS on
                   a running server (fleet-wide fan-out) — the drill
                   entry point.
  MISAKA_TLS_CERT / MISAKA_TLS_KEY  serve the PUBLIC HTTP listener over
                   TLS (stdlib ssl; PEM cert chain + private key).  In
                   single-process mode the engine's own listener wraps;
                   with MISAKA_HTTP_WORKERS / MISAKA_FLEET the frontend
                   workers terminate TLS and the engine / fleet control
                   server stay loopback HTTP.  Unset = plain HTTP,
                   exactly as before.  `make cert` output works:
                   MISAKA_TLS_CERT=deploy/certs/service.pem
                   MISAKA_TLS_KEY=deploy/certs/service.key
  MISAKA_API_KEYS  arm API-key auth (runtime/edge.py): path to a
                   reloadable JSON key file ({"keys": [{"key": ...,
                   "tenant": ..., "admin": bool, "programs": [...],
                   "quota": "spec"}]}); defaults to
                   <MISAKA_PROGRAMS_DIR>/api_keys.json when that file
                   exists.  Keys map requests to TENANTS (quota,
                   fair-share, and the misaka_edge_* metric labels);
                   admin routes (/run /pause /load /checkpoint
                   /fleet/roll ...) need "admin": true keys; /healthz +
                   /metrics stay open for probes/scrapers.  The file
                   hot-reloads on mtime change — rotate keys without a
                   restart.  Unset = no auth, exactly as before
  MISAKA_QUOTA     env-default per-tenant quota spec, e.g.
                   "rps<100,vps<500000,cpu<0.5" (requests/s, values/s,
                   core-seconds/s against the usage ledger over
                   MISAKA_QUOTA_CPU_WINDOW_S [60]).  Field-wise
                   overridable per program (`quota` field on POST
                   /programs) and per key (key-file `quota`); exhaustion
                   answers typed 429 + Retry-After.
                   MISAKA_QUOTA_BURST_S (2) sets bucket burst depth
  MISAKA_ADMISSION_HIGH  overload admission control's soft watermark in
                   ServeBatcher waiting VALUES (default: clears the
                   largest MISAKA_MAX_BODY-legal request — tune DOWN to
                   your latency budget, waiting/rate ~= delay): beyond
                   it, tenants above their fair share of the recent
                   admission window shed with typed 429 + Retry-After
                   (a paging SLO halves the watermark; 2x is the
                   hard cap that sheds everyone).  Frontend workers add
                   a local frame-backlog cap, MISAKA_PLANE_DEPTH_MAX
                   (256 frames)
  MISAKA_EDGE      "0" kills the WHOLE edge chain; per-stage switches
                   MISAKA_EDGE_AUTH / MISAKA_EDGE_QUOTA /
                   MISAKA_EDGE_ADMISSION=0 disarm one layer (the A/B
                   overhead gate isolates stages with these)
  MISAKA_PLANE_SECRET  shared-secret handshake on the compute plane
                   (runtime/frontends.py): every plane connection must
                   open with an HMAC of this secret or it is closed
                   (MISAKA_PLANE_SECRET_FILE reads it from a file).
                   Unset = open plane, exactly as before
  MISAKA_PLANE_TLS_CERT / _KEY / _CA  mTLS on TCP compute planes: a
                   plane address of "host:port" form (MISAKA_PLANE_SOCKET
                   or a fleet peer's plane) serves/dials TLS 1.2+ with
                   this cert/key, pinned to the given CA on BOTH sides
                   (client certs required; hostname checks off — the CA
                   is the identity).  Files are mtime-watched and
                   hot-reloaded like the api-key table, so certificates
                   rotate without a restart; plaintext or wrong-CA peers
                   are refused with a typed, counted close
                   (misaka_plane_tls_rejected_total).  Set all three or
                   none.  The HMAC handshake above still runs INSIDE the
                   TLS session as the inner authenticator.  Unix-socket
                   planes ignore these
  MISAKA_FLEET_PEERS  static remote peers for the fleet
                   ("host:port[,host2:port2...]", port = the peer
                   replica's HTTP control port; its compute plane
                   defaults to port+1, or pin it with host:port:planeport):
                   the fleet probes each peer's /healthz on the local
                   cadence, routes compute frames across their TCP
                   planes with the same hedging/suspect-hold machinery
                   as local replicas, and drives them through
                   drain -> checkpoint -> readmit on /fleet/roll
                   (process replacement stays with the peer host's own
                   supervisor).  MISAKA_FLEET_PEER_KEY is the admin key
                   those cross-host control calls authenticate with
                   (typically the peers' pinned
                   MISAKA_EDGE_INTERNAL_TOKEN)
  MISAKA_GOSSIP_S  usage-gossip cadence for fleet-coherent quotas
                   (default 0.5; "0" disables): the fleet hub exchanges
                   cumulative per-tenant admission counters with every
                   replica and peer over POST /edge/gossip, and each
                   edge chain drains its local token buckets by the
                   remote usage — bounding a flooded tenant's aggregate
                   admission across N replicas to ~1 + burst/window
                   instead of Nx
  MISAKA_TOKEN_SECRET  HMAC secret for signed short-lived tenant tokens
                   (runtime/edge.py; MISAKA_TOKEN_SECRET_FILE reads a
                   file; defaults to MISAKA_PLANE_SECRET so one fleet
                   secret covers both): POST /edge/token (admin) mints
                   "mst1." bearer tokens carrying tenant/expiry/scope,
                   verified locally by every replica sharing the secret
                   — no key-table distribution, no coordination
  MISAKA_LANE_SMALL  priority-lane split for the serve scheduler in
                   VALUES (default 8192): entries at or under it ride
                   the hot lane and preempt bulk backlog in pass
                   packing — an interactive request never queues behind
                   a 64 MiB bulk body.  0 = single lane, as before
  MISAKA_COORDINATOR  join a multi-host jax.distributed runtime before any
                   device touch ("host:port", or "auto" on Cloud TPU pods);
                   with MISAKA_NUM_PROCESSES + MISAKA_PROCESS_ID
                   (parallel/multihost.py; unset = single-host)

Deployment modes (NODE_TYPE dispatch, mirroring cmd/app.go:17-39):
  * NODE_TYPE unset / "master" (default): the fused single-process TPU
    engine — the whole network in one jitted kernel.  This is the product.
  * MISAKA_MODE=distributed + NODE_TYPE=master: the reference's distributed
    control plane — HTTP surface + gRPC command fan-out + Master data-plane
    service, for networks of per-process nodes (runtime/nodes.py).
  * NODE_TYPE=program: one TIS interpreter process (MASTER_URI + PROGRAM
    envs, app.go:20-25), serving the Program gRPC service.
  * NODE_TYPE=stack: one LIFO storage process serving the Stack service.
Per-process nodes honor CERT_FILE/KEY_FILE for TLS (app.go:15-16; plain TCP
when unset), NODE_ADDRS ({name: "host:port"}) and MISAKA_GRPC_PORT for
addressing (the reference hardcodes :8001).

Run: python -m misaka_tpu.runtime.app
"""

from __future__ import annotations

import atexit
import json
import logging
import os
import sys
import tempfile

if __name__ == "__main__":
    # Provisional boot-window handlers, armed BEFORE the multi-second jax
    # imports below (exec path only — a library import must not touch the
    # importer's signal table); `python -m misaka_tpu serve` arms the same
    # handlers in its own entry (runtime/lifecycle.arm_boot_handlers).
    from misaka_tpu.runtime.lifecycle import arm_boot_handlers

    arm_boot_handlers()

# Captured at package import, before the heavy jax imports below: if our
# launching shell dies during the multi-second boot,
# lifecycle.install_guards compares against this and exits instead of
# leaking (runtime/lifecycle.py).
from misaka_tpu import PPID_AT_IMPORT as _PPID_AT_START
from misaka_tpu.runtime.lifecycle import install_guards
from misaka_tpu.runtime.master import MasterNode, make_http_server
from misaka_tpu.runtime.topology import Topology


def build_topology_from_env(environ=os.environ) -> Topology:
    # Capacity knobs (MISAKA_STACK_CAP / MISAKA_IN_CAP / MISAKA_OUT_CAP):
    # ring/stack depths trade capacity for VMEM residency — the fused Pallas
    # engine's budget (core/fused.py) is only reachable from env config when
    # these are settable (e.g. MISAKA_IN_CAP=128 MISAKA_STACK_CAP=16).
    caps = {}
    for env_name, field in (
        ("MISAKA_STACK_CAP", "stack_cap"),
        ("MISAKA_IN_CAP", "in_cap"),
        ("MISAKA_OUT_CAP", "out_cap"),
    ):
        v = environ.get(env_name)
        if v:
            caps[field] = int(v)
    path = environ.get("MISAKA_TOPOLOGY")
    if path:
        if path.endswith((".yml", ".yaml")):
            # a reference docker-compose file: run its whole deployment as
            # one fused network (runtime/compose.py)
            from misaka_tpu.runtime.compose import load_compose

            return load_compose(path, **caps)
        with open(path) as f:
            return Topology.from_json(f.read(), **caps)
    node_info = environ.get("NODE_INFO")
    if not node_info:
        raise SystemExit(
            "set NODE_INFO (reference JSON shape) or MISAKA_TOPOLOGY (file path)"
        )
    programs = json.loads(environ.get("MISAKA_PROGRAMS", "{}"))
    return Topology.from_node_info_json(node_info, programs, **caps)


def _write_pidfile(environ=os.environ) -> str | None:
    """Drop this server's pidfile for external supervisors.

    The path is MISAKA_PIDFILE when set ("0"/"off" disables the file
    entirely); the default lives under the system run/tmp dir, never the
    CWD — a server started from a source checkout must not litter the
    tree (`git status` stays clean after a local boot).  Best-effort:
    an unwritable path logs and serves on.
    """
    spec = environ.get("MISAKA_PIDFILE", "")
    if spec in ("0", "off"):
        return None
    path = spec or os.path.join(
        tempfile.gettempdir(), f"misaka-app-{os.getpid()}.pid"
    )
    try:
        with open(path, "w") as f:
            f.write(f"{os.getpid()}\n")
    except OSError as e:
        logging.getLogger("misaka_tpu.app").warning(
            "pidfile %s unwritable (%s); serving without one", path, e
        )
        return None

    def _rm(p=path):
        try:
            os.unlink(p)
        except OSError:
            pass

    # atexit (not the serve loop's finally) so BOTH serve paths and the
    # KeyboardInterrupt -> sys.exit(0) route all clean up the file.
    atexit.register(_rm)
    return path


def _serve_http(
    master,
    environ=os.environ,
    checkpoint_dir: str | None = None,
    profile_dir: str | None = None,
    registry=None,
) -> None:
    port = int(environ.get("MISAKA_PORT", "8000"))
    log_ = logging.getLogger("misaka_tpu.app")
    pidfile = _write_pidfile(environ)
    if pidfile:
        log_.info("pidfile %s", pidfile)
    workers = int(environ.get("MISAKA_HTTP_WORKERS", "0") or 0)
    # The synthetic canary (runtime/canary.py) probes the PUBLIC surface
    # from inside this process; with API-key auth armed it needs a key,
    # so mint the per-boot internal token the fleet parent would have
    # (admin-scoped synthetic tenant, never leaves the process tree —
    # frontend workers inherit it through their env).
    from misaka_tpu.runtime import canary as canary_mod
    from misaka_tpu.runtime import edge as edge_mod

    if (
        edge_mod.keyfile_path(environ)
        and not environ.get("MISAKA_EDGE_INTERNAL_TOKEN")
        and environ.get("MISAKA_EDGE", "1") != "0"
    ):
        environ["MISAKA_EDGE_INTERNAL_TOKEN"] = os.urandom(16).hex()
    scheme = "https" if environ.get("MISAKA_TLS_CERT") else "http"

    def arm_canary(server) -> None:
        canary_mod.ensure_started(
            f"{scheme}://127.0.0.1:{port}",
            registry=registry, server=server, environ=environ,
        )
    if workers > 0 and hasattr(master, "compute_coalesced"):
        # The multi-process serving plane (runtime/frontends.py): N
        # frontend worker processes share the PUBLIC port via SO_REUSEPORT
        # and feed coalesced frames to this engine over a unix socket; the
        # engine's own HTTP server moves to a loopback port as the proxy
        # target for non-compute routes.  One CPython process tops out
        # near ~3.5k requests/s on pure request handling — this is the
        # tier that scales the HTTP surface past one GIL.
        sys.setswitchinterval(0.001)  # many handler threads; avoid convoys
        from misaka_tpu.runtime import frontends

        server = make_http_server(
            master, 0, checkpoint_dir=checkpoint_dir,
            profile_dir=profile_dir, registry=registry,
            # TLS terminates at the frontend workers (they inherit
            # MISAKA_TLS_* from this env); the engine's own listener is
            # their loopback proxy target and must stay plain HTTP
            tls=False,
        )
        engine_port = server.server_address[1]
        plane_path = environ.get(
            "MISAKA_PLANE_SOCKET", f"/tmp/misaka-plane-{os.getpid()}.sock"
        )
        plane = frontends.start_compute_plane(
            master, plane_path, registry=registry
        )
        server.misaka_plane = plane  # POST /fleet/drain reaches it
        # Supervised worker pool (not bare spawn_frontends): a dead worker
        # is respawned with backoff, a crash loop trips a circuit breaker,
        # and the pool's health rides /healthz + /status (the server reads
        # the misaka_supervisor attribute) — a shrunk pool is never silent.
        # r19 native edge: when available, the C++ epoll tier takes the
        # PUBLIC port and the worker pool moves to a loopback port as its
        # proxy target; any failure here (kill switch, TLS, no toolchain,
        # injected edge_native_build fault) leaves the r8 topology —
        # workers on the public port — completely unchanged.
        native_sup = None
        worker_port = port
        plane_conns = int(environ.get("MISAKA_PLANE_CONNS", "2"))
        if (
            environ.get("MISAKA_NATIVE_EDGE", "1") != "0"
            and not environ.get("MISAKA_TLS_CERT")
        ):
            try:
                worker_port = frontends.pick_free_port()
                native_sup = frontends.NativeFrontendSupervisor(
                    port=port,
                    proxy_port=worker_port,
                    plane_path=plane_path,
                    registry=registry,
                    healthz_url=f"http://127.0.0.1:{engine_port}/healthz",
                    plane_conns=plane_conns,
                    environ=environ,
                )
                server.misaka_native_edge = native_sup
            except Exception as e:
                log_.warning(
                    "native edge unavailable (%s); CPython workers take "
                    "the public port", e,
                )
                native_sup = None
                worker_port = port
        supervisor = frontends.FrontendSupervisor(
            workers, worker_port, f"http://127.0.0.1:{engine_port}",
            plane_path, plane_conns=plane_conns,
        )
        server.misaka_supervisor = supervisor
        log_.info(
            "engine http on 127.0.0.1:%d; %d supervised frontend workers "
            "on :%d (plane %s)%s", engine_port, workers, worker_port,
            plane_path,
            f"; native edge on :{port}" if native_sup is not None else "",
        )
        arm_canary(server)  # probes the PUBLIC (frontend) port + plane
        try:
            server.serve_forever()
        except KeyboardInterrupt:
            master.pause()
            sys.exit(0)
        finally:
            if native_sup is not None:
                native_sup.close()
            supervisor.close()
            plane.close()
        return
    server = make_http_server(
        master, port, checkpoint_dir=checkpoint_dir, profile_dir=profile_dir,
        registry=registry,
    )
    plane = None
    if (
        environ.get("MISAKA_PLANE_SERVE") == "1"
        and hasattr(master, "compute_coalesced")
    ):
        # A fleet engine replica (runtime/fleet.py): serve the compute
        # plane even with no frontend workers of our own — the SHARED
        # frontend tier (owned by the fleet parent) connects to it, and
        # POST /fleet/drain drives it through rolling restarts.
        from misaka_tpu.runtime import frontends

        plane_path = environ.get(
            "MISAKA_PLANE_SOCKET", f"/tmp/misaka-plane-{os.getpid()}.sock"
        )
        plane = frontends.start_compute_plane(
            master, plane_path, registry=registry
        )
        server.misaka_plane = plane
        log_.info("compute plane serving at %s", plane_path)
    log_.info("starting http server on :%d", port)
    arm_canary(server)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        master.pause()
        sys.exit(0)
    finally:
        if plane is not None:
            plane.close()


def _specialize_dir(environ=os.environ) -> str | None:
    """The boot master's native specialization cache dir, or None when the
    layer is killed (MISAKA_SPECIALIZE=0) — MasterNode only compiles
    specialized ticks when a cache dir is named."""
    if environ.get("MISAKA_SPECIALIZE", "1") in ("0", "off"):
        return None
    from misaka_tpu.core import specialize

    # default_cache_dir() owns the MISAKA_SPEC_CACHE lookup
    return specialize.default_cache_dir()


def main() -> None:
    if os.environ.get("MISAKA_LOG_JSON") == "1":
        # structured logs for container pipelines: one JSON object per
        # line, with the HTTP route attached where a request is in scope
        from misaka_tpu.utils.jsonlog import install

        install(level=logging.INFO)
    else:
        logging.basicConfig(
            level=logging.INFO, format="%(asctime)s %(name)s %(message)s"
        )
    environ = os.environ
    node_type = environ.get("NODE_TYPE", "master")
    cert, key = environ.get("CERT_FILE"), environ.get("KEY_FILE")

    # Multi-host bootstrap must precede any XLA backend touch
    # (parallel/multihost.py); a no-op unless MISAKA_COORDINATOR is set.
    from misaka_tpu.parallel.multihost import initialize_from_env

    initialize_from_env(environ)

    if node_type == "program":
        from misaka_tpu.runtime.nodes import ProgramNodeProcess, Resolver

        node = ProgramNodeProcess(
            master_uri=environ.get("MASTER_URI", "last_order"),
            resolver=Resolver.from_env(environ),
            cert_file=cert,
            key_file=key,
            grpc_port=int(environ.get("MISAKA_GRPC_PORT", "8001")),
        )
        install_guards(node.close, environ, start_ppid=_PPID_AT_START)
        program = environ.get("PROGRAM")
        if program:
            try:
                node.load_program(program)
            except Exception as e:  # reference logs and keeps NOP (app.go:22-24)
                logging.getLogger("misaka_tpu.app").warning(
                    "Could not load default program: %s", e
                )
        node.start()
        threading_event_forever()
    elif node_type == "stack":
        from misaka_tpu.runtime.nodes import StackNodeProcess

        node = StackNodeProcess(
            cert_file=cert,
            key_file=key,
            grpc_port=int(environ.get("MISAKA_GRPC_PORT", "8001")),
        )
        install_guards(node.close, environ, start_ppid=_PPID_AT_START)
        node.start()
        threading_event_forever()
    elif node_type == "master" and environ.get("MISAKA_MODE") == "distributed":
        from misaka_tpu.runtime.nodes import MasterNodeProcess, Resolver

        node_info = json.loads(environ.get("NODE_INFO", "{}"))
        if not node_info:
            raise SystemExit("distributed master requires NODE_INFO")
        master = MasterNodeProcess(
            node_info,
            resolver=Resolver.from_env(environ),
            cert_file=cert,
            key_file=key,
            grpc_port=int(environ.get("MISAKA_GRPC_PORT", "8001")),
        )
        install_guards(master.close, environ, start_ppid=_PPID_AT_START)
        master.start()
        if environ.get("MISAKA_AUTORUN") == "1":
            try:
                master.run()
            except Exception as e:  # peers may not be up yet; /run retries
                logging.getLogger("misaka_tpu.app").warning("autorun failed: %s", e)
        # No checkpoint_dir: state lives in the per-process nodes, which the
        # distributed master cannot snapshot (the fused engine can).
        _serve_http(master, environ)
    elif node_type == "master":
        fleet_n = int(environ.get("MISAKA_FLEET", "0") or 0)
        if fleet_n >= 1 and not environ.get("MISAKA_FLEET_REPLICA"):
            # The replicated engine fleet (runtime/fleet.py): this
            # process becomes the fleet parent — it spawns N engine
            # replicas (each a full master-mode subprocess of this same
            # entrypoint), the frontend worker tier routing across
            # them, and the aggregating control server.  MISAKA_FLEET=1
            # still runs the fleet plumbing, but a 1-replica roll has a
            # client-visible gap: the replacement's engine boot (tens of
            # seconds) exceeds the router's MISAKA_FLEET_DOWN_GRACE_S
            # (default 5s), so requests in that window answer 503 —
            # zero-loss rolls need N >= 2 (or a grace raised past the
            # boot time, with clients that tolerate the stall).  0/unset
            # keeps single-engine serving exactly as before.
            from misaka_tpu.runtime.fleet import run_fleet

            run_fleet(fleet_n, environ)
            return
        topology = build_topology_from_env()
        trace_cap = int(environ.get("MISAKA_TRACE_CAP", "0")) or None
        batch = int(environ.get("MISAKA_BATCH", "0")) or None
        master = MasterNode(
            topology,
            trace_cap=trace_cap,
            batch=batch,
            # serving deployments tune this up (the committed bench
            # harness runs 2048: fewer engine round trips per pass)
            chunk_steps=int(environ.get("MISAKA_CHUNK_STEPS", "0")) or 128,
            engine=environ.get("MISAKA_ENGINE", "auto"),
            trace_instance=int(environ.get("MISAKA_TRACE_INSTANCE", "0")),
            data_parallel=int(environ.get("MISAKA_DATA_PARALLEL", "0")) or None,
            model_parallel=int(environ.get("MISAKA_MODEL_PARALLEL", "0")) or None,
            # intStack.go:9-45 is unbounded; capacity auto-grows on wedge
            # unless disabled (MISAKA_STACK_AUTOGROW=0)
            stack_autogrow=environ.get("MISAKA_STACK_AUTOGROW", "1") != "0",
            # per-program specialized native ticks for the boot engine
            # (core/specialize.py; MISAKA_SPECIALIZE=0 kills, content-keyed
            # compile cache shared per user — a restart reuses the .so)
            native_spec_dir=_specialize_dir(environ),
        )
        install_guards(master.pause, environ, start_ppid=_PPID_AT_START)
        log_ = logging.getLogger("misaka_tpu.app")
        checkpoint_dir = environ.get("MISAKA_CHECKPOINT_DIR")
        autockpt_s = float(environ.get("MISAKA_AUTOCKPT", "0") or 0)
        autockpt = None
        if autockpt_s > 0 and not checkpoint_dir:
            raise SystemExit(
                "MISAKA_AUTOCKPT requires MISAKA_CHECKPOINT_DIR (snapshots "
                "need a directory to rotate in)"
            )
        fleet_restore = environ.get("MISAKA_FLEET_RESTORE")
        if autockpt_s > 0:
            # Crash recovery BEFORE any traffic or autorun: install the
            # newest auto snapshot that passes the durability gate,
            # falling back across torn/corrupt ones (runtime/master.py
            # AutoCheckpointer) — then keep snapshotting on the interval.
            from misaka_tpu.runtime.master import AutoCheckpointer

            if fleet_restore:
                # a roll replacement loads its strictly-newer roll
                # checkpoint below — the auto-restore would be a full
                # engine-state load immediately thrown away, and every
                # wasted boot second extends the roll's reduced-capacity
                # window
                log_.info("skipping auto-restore: fleet roll checkpoint "
                          "takes precedence")
            else:
                restored = AutoCheckpointer.restore_latest(
                    master, checkpoint_dir
                )
                if restored:
                    log_.info("auto-restored checkpoint %s", restored)
                else:
                    log_.info(
                        "no valid auto checkpoint under %s; fresh state",
                        checkpoint_dir,
                    )
            autockpt = AutoCheckpointer(
                master, checkpoint_dir, autockpt_s,
                keep=int(environ.get("MISAKA_AUTOCKPT_KEEP", "4")),
            )
        if fleet_restore:
            # A rolling-restart replacement replica (runtime/fleet.py
            # roll): restore the drained predecessor's manifest-verified
            # checkpoint BEFORE any traffic — the replacement continues
            # bit-identically where the old replica stopped.  Takes
            # precedence over an auto-checkpoint restore (skipped above:
            # the roll checkpoint is strictly newer, cut at quiescence
            # moments ago).
            master.load_checkpoint(fleet_restore)
            log_.info("restored fleet roll checkpoint %s", fleet_restore)
        registry = None
        programs_dir = environ.get("MISAKA_PROGRAMS_DIR")
        if programs_dir:
            # The program registry (runtime/registry.py): the boot network
            # seeds the pinned default program; uploads, per-program
            # engines, LRU eviction, and hot-swap layer on top.
            from misaka_tpu.runtime.registry import ProgramRegistry

            caps = {}
            for env_name, field in (
                ("MISAKA_STACK_CAP", "stack_cap"),
                ("MISAKA_IN_CAP", "in_cap"),
                ("MISAKA_OUT_CAP", "out_cap"),
            ):
                if environ.get(env_name):
                    caps[field] = int(environ[env_name])
            registry = ProgramRegistry(
                programs_dir,
                batch=batch,
                engine=environ.get("MISAKA_ENGINE", "auto"),
                caps=caps,
            )
            default_name = environ.get("MISAKA_DEFAULT_PROGRAM", "default")
            # seed from the master's LIVE topology (an auto-restored
            # checkpoint may carry different programs than the boot env)
            registry.seed(default_name, master)
            log_.info(
                "program registry armed (dir %s, default program %r, "
                "max_active %d)", programs_dir, default_name,
                registry._max_active,
            )
        if environ.get("MISAKA_AUTORUN") == "1":
            master.run()
        try:
            _serve_http(
                master,
                environ,
                checkpoint_dir=checkpoint_dir,
                profile_dir=environ.get("MISAKA_PROFILE_DIR"),
                registry=registry,
            )
        finally:
            if autockpt is not None:
                autockpt.close()
            if registry is not None:
                registry.close()
    else:
        raise SystemExit(f"'{node_type}' not a valid node type")


def threading_event_forever() -> None:
    """Park the main thread while daemon servers run (the reference blocks in
    Serve, program.go:105)."""
    import threading

    threading.Event().wait()


if __name__ == "__main__":
    main()
