"""Per-process compatibility nodes: drop-in peers for the reference deployment.

The fused single-process engine (misaka_tpu/core) is the product; this module
is the *deployment-parity* mode — one OS process per node speaking the
reference's gRPC protocol (misaka_tpu/transport), so a misaka_tpu node can
replace any container in the reference's docker-compose topology, or mix with
original Go nodes on one network.

Three node kinds, mirroring internal/nodes/:
  * ProgramNodeProcess — the TIS interpreter VM (program.go:24-432): registers
    acc/bak, instruction ptr, four cap-1 inbound ports, a free-running
    execute loop, and the Program gRPC service.  Executes the *same parsed
    token rows* as the Go reference (shared frontend: misaka_tpu.tis.parser).
  * StackNodeProcess — shared LIFO storage + the Stack service (stack.go).
  * MasterNodeProcess — control plane: HTTP surface + command broadcast +
    the Master data-plane service (master.go).

Deliberate divergences from the reference (each documented at the site):
  * One reused channel per peer instead of a fresh TLS dial per message
    (quirk #6) — semantics identical, latency strictly better.
  * Transient RPC errors are retried on the same instruction (matching the
    reference's update()-error semantics, program.go:80-92) instead of
    log.Fatalf-ing the process (quirk #8).
  * A cancelled Stack.Pop wakes cleanly instead of leaking a consumer that
    later swallows a value (quirk #4).
  * /compute request/response pairing is serialized (quirk #2).
  * /load dials the target's real gRPC port; the reference dials :8000 where
    nothing listens (quirk #1).
"""

from __future__ import annotations

import json
import logging
import os
import queue
import threading
import time
from collections import deque

import grpc
import numpy as np
from google.protobuf import empty_pb2

from misaka_tpu.runtime.master import (
    BroadcastError,
    ComputeTimeout,
    PeerUnavailable,
)
from misaka_tpu.tis.parser import TISParseError, parse
from misaka_tpu.transport import rpc
from misaka_tpu.transport import messenger_pb2 as pb
from misaka_tpu.utils import metrics
from misaka_tpu.utils import tracespan

# Distributed-mode metrics (the same registry the fused master renders at
# GET /metrics; runtime.master.make_http_server serves this control plane
# too).  One process per node in production, so these are per-node series;
# the loopback test cluster shares one process and simply aggregates.
_C_DIST_REQS = metrics.counter(
    "misaka_dist_compute_requests_total",
    "compute/compute_many calls on the distributed control plane",
)
_C_DIST_VALUES = metrics.counter(
    "misaka_dist_compute_values_total",
    "Values submitted through the distributed compute lanes",
)
_C_DIST_TIMEOUTS = metrics.counter(
    "misaka_dist_compute_timeouts_total",
    "Distributed compute calls that raised ComputeTimeout",
)
_C_DIST_INPUTS = metrics.counter(
    "misaka_dist_inputs_total", "Values handed to program nodes via GetInput"
)
_C_DIST_OUTPUTS = metrics.counter(
    "misaka_dist_outputs_total", "Values received from program nodes via SendOutput"
)
_C_DIST_BROADCASTS = metrics.counter(
    "misaka_dist_broadcasts_total", "Control-plane command fan-outs by command",
    ("command",),
)
_C_STACK_PUSH = metrics.counter(
    "misaka_stack_push_total", "Stack-node Push RPCs served (this process)"
)
_C_STACK_POP = metrics.counter(
    "misaka_stack_pop_total", "Stack-node Pop RPCs served (this process)"
)
_C_PROG_INSTRS = metrics.counter(
    "misaka_program_instructions_total",
    "Instructions committed by program nodes in this process",
)
_C_RPC_RETRIES = metrics.counter(
    "misaka_rpc_retries_total",
    "RPC failures retried with backoff (node execute loops, this process)",
)
_C_DIST_PEER_UNAVAIL = metrics.counter(
    "misaka_dist_peer_unavailable_total",
    "Distributed computes refused fast (PeerUnavailable / HTTP 503) because "
    "a peer was down — distinct from genuine compute timeouts",
)
_G_PEER_STATE = metrics.gauge(
    "misaka_peer_state",
    "Control-plane peer health by name (0=down, 1=degraded, 2=up)",
    ("peer",),
)

_M64 = 1 << 64


def _wrap64(v: int) -> int:
    """Wrap to Go's 64-bit int: acc/bak are `int` (program.go:27-28); local
    arithmetic wraps at 64 bits while the wire truncates to sint32
    (rpc._i32 at every Send/Push/SendOutput)."""
    v &= _M64 - 1
    return v - _M64 if v >= (1 << 63) else v

log = logging.getLogger("misaka_tpu.nodes")

_EMPTY = empty_pb2.Empty
_POLL = 0.05  # seconds between cancellation checks while blocked


class NodeCancelled(Exception):
    """A blocking op was interrupted by Pause/Reset (ctx cancellation,
    program.go:196-204)."""


class Resolver:
    """Node name -> dial target.  The reference hardcodes `<name>:8001`
    (grpcPort, master.go:20); NODE_ADDRS overrides let one host run many
    nodes on distinct ports."""

    def __init__(self, addrs: dict[str, str] | None = None, default_port: int = rpc.GRPC_PORT):
        self._addrs = dict(addrs or {})
        self._port = default_port

    @classmethod
    def from_env(cls, environ) -> "Resolver":
        addrs = json.loads(environ.get("NODE_ADDRS", "{}"))
        port = int(environ.get("MISAKA_GRPC_PORT", rpc.GRPC_PORT))
        return cls(addrs, default_port=port)

    def set_addr(self, name: str, target: str) -> None:
        """Late registration — lets tests bind ephemeral ports first."""
        self._addrs[name] = target

    def resolve(self, name: str) -> str:
        return self._addrs.get(name) or f"{name}:{self._port}"


class _ClientPool:
    """One lazily-dialed, reused client per (service, peer)."""

    def __init__(self, resolver: Resolver, cert_file: str | None):
        self._resolver = resolver
        self._cert = cert_file
        self._clients: dict[tuple[type, str], rpc._Stub] = {}
        self._lock = threading.Lock()

    def get(self, cls, name: str):
        key = (cls, name)
        with self._lock:
            client = self._clients.get(key)
            if client is None:
                client = cls(self._resolver.resolve(name), cert_file=self._cert)
                self._clients[key] = client
            return client

    def close(self) -> None:
        with self._lock:
            for c in self._clients.values():
                c.close()
            self._clients.clear()


class _Lifecycle:
    """isRunning + generation-based cancellation, shared by all node kinds.

    The reference pairs an unsynchronized isRunning flag (quirk #3) with a
    context.Context recreated on every stop (stopNode, program.go:196-204).
    Here: a lock-guarded flag plus a monotonically increasing generation;
    blocked ops capture the generation and bail when it moves.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._running = False
        self._gen = 0
        self._run_signal = threading.Event()

    @property
    def is_running(self) -> bool:
        return self._running

    @property
    def gen(self) -> int:
        return self._gen

    def start(self) -> bool:
        with self._lock:
            if self._running:
                return False
            self._running = True
            self._run_signal.set()
            return True

    def stop(self) -> bool:
        """stopNode: cancel in-flight blocking ops, clear running."""
        with self._lock:
            was = self._running
            self._running = False
            self._gen += 1
            self._run_signal.clear()
            return was

    def cancelled(self, gen: int) -> bool:
        return self._gen != gen

    def check(self, gen: int) -> None:
        if self._gen != gen:
            raise NodeCancelled()

    def wait_for_run(self) -> None:
        self._run_signal.wait(_POLL)


def _await_future(fut: grpc.Future, life: _Lifecycle, gen: int):
    """Block on an in-flight RPC, aborting if the node is paused/reset —
    the Go pattern of passing the node ctx into every client call."""
    while True:
        try:
            return fut.result(timeout=_POLL)
        except grpc.FutureTimeoutError:
            if life.cancelled(gen):
                fut.cancel()
                raise NodeCancelled()


class ProgramNodeProcess:
    """One TIS interpreter as an OS process (ProgramNode, program.go:24-432)."""

    def __init__(
        self,
        master_uri: str,
        resolver: Resolver | None = None,
        cert_file: str | None = None,
        key_file: str | None = None,
        grpc_port: int = rpc.GRPC_PORT,
        host: str = "0.0.0.0",
    ):
        self._master_uri = master_uri
        self._resolver = resolver or Resolver()
        self._cert, self._key = cert_file, key_file
        self._grpc_port = grpc_port
        self._host = host
        self._pool = _ClientPool(self._resolver, cert_file)

        self._life = _Lifecycle()
        self._state_lock = threading.Lock()  # guards acc/bak/ptr/asm swaps
        self.acc = 0
        self.bak = 0
        self.ptr = 0
        # Hold latch for a consumed-but-uncommitted port value: once a source
        # port is read, the value survives instruction retries (transient RPC
        # errors, pause/resume) until the instruction commits — the same
        # consume-then-park discipline as the fused kernel (core/fused.py
        # pass 1).  The reference re-reads the port on retry and silently
        # loses the consumed value (program.go:80-92 + :435-472).
        self._hold: int | None = None
        self._asm: list[list[str]] = [["NOP"]]  # fresh node default (program.go:64)
        self._label_map: dict[str, int] = {}
        # Inbound ports r0..r3: cap-1 queues (bufferSize=1, program.go:21,:60-63).
        self._ports = [queue.Queue(maxsize=1) for _ in range(4)]

        self._shutdown = threading.Event()
        self._loop: threading.Thread | None = None
        self._server: grpc.Server | None = None

    # --- lifecycle ----------------------------------------------------------

    def start(self) -> int:
        """Start the run loop and gRPC server; returns the bound port."""
        self._loop = threading.Thread(target=self._run_loop, daemon=True)
        self._loop.start()
        self._server, port = rpc.make_server(
            {"Program": _ProgramServicer(self)},
            self._grpc_port,
            self._cert,
            self._key,
            host=self._host,
        )
        self._server.start()
        log.info("program node serving grpc on :%d", port)
        self._grpc_port = port
        return port

    def close(self) -> None:
        self._shutdown.set()
        self._life.stop()
        if self._server:
            self._server.stop(grace=0.2)
        if self._loop:
            self._loop.join(timeout=2)
        self._pool.close()

    def load_program(self, source: str) -> None:
        """Parse + install a program (LoadProgram, program.go:178-193); a
        parse error leaves the old program in place."""
        tokens, label_map = parse(source)
        with self._state_lock:
            self._asm = tokens
            self._label_map = label_map

    def run_cmd(self) -> None:
        if self._life.start():
            log.info("node was run")
        else:
            log.info("node is already running")

    def pause_cmd(self) -> None:
        if self._life.stop():
            log.info("node was paused")
        else:
            log.info("node is already paused")

    def reset_cmd(self) -> None:
        self._life.stop()
        self._reset_state()
        log.info("node was reset")

    def _reset_state(self) -> None:
        """resetNode (program.go:207-216): zero registers, fresh ports."""
        with self._state_lock:
            self.acc = 0
            self.bak = 0
            self.ptr = 0
            self._hold = None
            self._ports = [queue.Queue(maxsize=1) for _ in range(4)]

    # --- the interpreter loop ----------------------------------------------

    def _run_loop(self) -> None:
        """Free-running execute loop (program.go:78-92): on error, log and
        retry the same instruction (ptr not advanced)."""
        backoff = rpc.Backoff(
            base=0.05,
            cap=float(os.environ.get("MISAKA_RPC_BACKOFF_MAX", "") or 5.0),
        )
        while not self._shutdown.is_set():
            gen = self._life.gen
            if not self._life.is_running:
                self._life.wait_for_run()
                continue
            try:
                # _state_lock serializes each instruction's commit against
                # pause/reset/load state mutation: a reset arriving while an
                # RPC response is in flight must zero state strictly AFTER
                # the instruction finishes, or the commit would clobber the
                # fresh ptr/acc (observed: OUT completing against a reset
                # left ptr=1, making the lane skip its IN on re-run).
                with self._state_lock:
                    self._life.check(gen)  # stop raced the lock acquisition
                    self._update(gen)
            except NodeCancelled:
                backoff.reset()  # lifecycle moved; retry cadence starts over
                continue
            except TISParseError as e:  # unreachable post-load; defensive
                log.warning("program error: %s", e)
            except rpc.RpcError as e:
                # Reference log.Fatalf's here (quirk #8); retry the SAME
                # instruction instead — with bounded exponential backoff
                # (rpc.Backoff): the retry never gives up, but a dead peer
                # is no longer hammered at poll rate, and no single sleep
                # exceeds MISAKA_RPC_BACKOFF_MAX (default 5s), so recovery
                # after the peer returns stays prompt.
                _C_RPC_RETRIES.inc()
                delay = backoff.next_delay()
                log.warning("rpc error (retry in %.2fs): %s", delay, e)
                self._backoff_wait(delay, gen)
            else:
                backoff.reset()

    def _backoff_wait(self, delay: float, gen: int) -> None:
        """Sleep out a backoff delay, waking early on shutdown or any
        lifecycle transition — a pause/reset/load landing mid-backoff must
        take effect now, not after a multi-second sleep."""
        deadline = time.monotonic() + delay
        while not self._shutdown.is_set():
            if self._life.cancelled(gen) or not self._life.is_running:
                return
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            self._shutdown.wait(min(_POLL, remaining))

    def _update(self, gen: int) -> None:
        """One instruction (update(), program.go:219-432).  Taken jumps set
        ptr and return; everything else falls through to the wrap increment
        `ptr = (ptr+1) % len(asm)` (program.go:429)."""
        # One consistent view of the program for this instruction: a /load
        # swapping self._asm mid-step must not skew the fetch or the wrap.
        asm = self._asm
        self.ptr %= len(asm)
        tokens = asm[self.ptr]
        kind = tokens[0]

        if kind == "NOP":
            pass
        elif kind == "SWP":
            self.acc, self.bak = self.bak, self.acc
        elif kind == "SAV":
            self.bak = self.acc
        elif kind == "NEG":
            self.acc = _wrap64(-self.acc)
        elif kind == "MOV_VAL_LOCAL":
            self._write_local(int(tokens[1]), tokens[2])
        elif kind == "MOV_VAL_NETWORK":
            self._send_value(int(tokens[1]), tokens[2], gen)
        elif kind == "MOV_SRC_LOCAL":
            self._write_local(self._get_from_src(tokens[1], gen), tokens[2])
        elif kind == "MOV_SRC_NETWORK":
            self._send_value(self._get_from_src(tokens[1], gen), tokens[2], gen)
        elif kind in ("ADD_VAL", "SUB_VAL", "ADD_SRC", "SUB_SRC"):
            v = int(tokens[1]) if kind.endswith("_VAL") else self._get_from_src(tokens[1], gen)
            self.acc = _wrap64(self.acc + (v if kind.startswith("ADD") else -v))
        elif kind in ("JMP", "JEZ", "JNZ", "JGZ", "JLZ"):
            taken = (
                kind == "JMP"
                or (kind == "JEZ" and self.acc == 0)
                or (kind == "JNZ" and self.acc != 0)
                or (kind == "JGZ" and self.acc > 0)
                or (kind == "JLZ" and self.acc < 0)
            )
            if taken:
                self.ptr = self._label_map[tokens[1]]
                return  # taken jumps skip the wrap increment (program.go:319)
        elif kind in ("JRO_VAL", "JRO_SRC"):
            v = int(tokens[1]) if kind == "JRO_VAL" else self._get_from_src(tokens[1], gen)
            self._hold = None  # committed (early return skips the shared clear)
            self.ptr = max(0, min(self.ptr + v, len(asm) - 1))  # IntClamp (math.go:17)
            return
        elif kind in ("PUSH_VAL", "PUSH_SRC"):
            v = int(tokens[1]) if kind == "PUSH_VAL" else self._get_from_src(tokens[1], gen)
            client = self._pool.get(rpc.StackClient, tokens[2])
            _await_future(client._Push.future(pb.ValueMessage(value=rpc._i32(v))), self._life, gen)
        elif kind == "POP":
            client = self._pool.get(rpc.StackClient, tokens[1])
            v = _await_future(client._Pop.future(_EMPTY()), self._life, gen).value
            self._write_local(int(v), tokens[2])
        elif kind == "IN":
            client = self._pool.get(rpc.MasterClient, self._master_uri)
            v = _await_future(client._GetInput.future(_EMPTY()), self._life, gen).value
            self._write_local(int(v), tokens[1])
        elif kind in ("OUT_VAL", "OUT_SRC"):
            v = int(tokens[1]) if kind == "OUT_VAL" else self._get_from_src(tokens[1], gen)
            client = self._pool.get(rpc.MasterClient, self._master_uri)
            _await_future(
                client._SendOutput.future(pb.ValueMessage(value=rpc._i32(v))), self._life, gen
            )

        self._hold = None  # instruction committed: release the port latch
        self.ptr = (self.ptr + 1) % len(asm)
        _C_PROG_INSTRS.inc()

    def _write_local(self, v: int, dst: str) -> None:
        """ACC stores, NIL discards (program.go:237-239)."""
        if dst == "ACC":
            self.acc = v

    def _get_from_src(self, src: str, gen: int) -> int:
        """getFromSrc (program.go:435-472): ACC/NIL immediate; ports block
        until a peer's Send lands, cancellable by pause/reset.  A port value
        is latched into self._hold so the instruction can retry (rpc error,
        pause) without losing it; _update clears the latch on commit."""
        if src == "ACC":
            return self.acc
        if src == "NIL":
            return 0
        if self._hold is not None:
            return self._hold
        q = self._ports[int(src[1])]
        while True:
            try:
                v = q.get(timeout=_POLL)
                self._hold = v
                return v
            except queue.Empty:
                self._life.check(gen)

    def _send_value(self, v: int, target: str, gen: int) -> None:
        """MOV to `name:Rk` — the Send RPC (sendValue, program.go:475-506).
        Blocks while the remote port is full (back-pressure via the
        blocking handler, program.go:160-175)."""
        name, port = target.rsplit(":", 1)
        client = self._pool.get(rpc.ProgramClient, name)
        fut = client._Send.future(
            pb.SendMessage(value=rpc._i32(v), register=int(port[1]))
        )
        _await_future(fut, self._life, gen)

    # --- inbound Send (the gRPC handler side) -------------------------------

    def deliver(self, value: int, register: int, context) -> None:
        """Blocking delivery into a cap-1 port (Send handler, program.go:160-175).
        Re-reads self._ports each poll so a reset (fresh queues) receives the
        value instead of stranding it in an orphaned buffer (the reference
        blocks forever on the old channel — strictly better)."""
        if not 0 <= register <= 3:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, "not a valid register")
        while context.is_active():
            try:
                self._ports[register].put(int(value), timeout=_POLL)
                return
            except queue.Full:
                continue
        raise NodeCancelled()  # caller went away; nothing to do


class _ProgramServicer:
    """gRPC Program service handlers (program.go:111-175)."""

    def __init__(self, node: ProgramNodeProcess):
        self._node = node

    def run(self, request, context):
        self._node.run_cmd()
        return _EMPTY()

    def pause(self, request, context):
        self._node.pause_cmd()
        return _EMPTY()

    def reset(self, request, context):
        self._node.reset_cmd()
        return _EMPTY()

    def load(self, request, context):
        """Reset then load (Load handler, program.go:150-157); parse errors
        become INVALID_ARGUMENT and leave the old program."""
        self._node.reset_cmd()
        try:
            self._node.load_program(request.program)
        except TISParseError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        return _EMPTY()

    def send(self, request, context):
        self._node.deliver(request.value, request.register, context)
        log.debug("received value")
        return _EMPTY()


class StackNodeProcess:
    """Shared LIFO storage process (StackNode, stack.go:17-155).

    The IntStack's empty-check races (quirk #5) and the cancelled-pop
    goroutine leak (quirk #4) are fixed by a single Condition guarding the
    list; pop waits on it and re-checks both emptiness and generation.
    """

    def __init__(
        self,
        cert_file: str | None = None,
        key_file: str | None = None,
        grpc_port: int = rpc.GRPC_PORT,
        host: str = "0.0.0.0",
    ):
        self._cert, self._key = cert_file, key_file
        self._grpc_port = grpc_port
        self._host = host
        self._life = _Lifecycle()
        self._cond = threading.Condition()
        self._stack: list[int] = []
        self._server: grpc.Server | None = None

    def start(self) -> int:
        self._server, port = rpc.make_server(
            {"Stack": _StackServicer(self)},
            self._grpc_port,
            self._cert,
            self._key,
            host=self._host,
        )
        self._server.start()
        log.info("stack node serving grpc on :%d", port)
        self._grpc_port = port
        return port

    def close(self) -> None:
        self._life.stop()
        with self._cond:
            self._cond.notify_all()
        if self._server:
            self._server.stop(grace=0.2)

    def push(self, value: int) -> None:
        with self._cond:
            self._stack.append(int(value))
            self._cond.notify()
        _C_STACK_PUSH.inc()

    def pop_blocking(self, context) -> int:
        """Blocks until a value exists (waitPop, stack.go:133-155); a
        pause/reset cancels with the reference's error message."""
        with self._cond:
            gen = self._life.gen
            while not self._stack:
                if self._life.cancelled(gen) or not context.is_active():
                    context.abort(grpc.StatusCode.CANCELLED, "stack pop cancelled")
                self._cond.wait(_POLL)
            _C_STACK_POP.inc()
            return self._stack.pop()

    def clear(self) -> None:
        with self._cond:
            self._stack.clear()

    @property
    def depth(self) -> int:
        with self._cond:
            return len(self._stack)


class _StackServicer:
    """gRPC Stack service handlers (stack.go:63-114)."""

    def __init__(self, node: StackNodeProcess):
        self._node = node

    def run(self, request, context):
        if self._node._life.start():
            log.info("node was run")
        else:
            log.info("node is already running")
        return _EMPTY()

    def pause(self, request, context):
        if self._node._life.stop():
            log.info("node was paused")
        else:
            log.info("node is already paused")
        with self._node._cond:
            self._node._cond.notify_all()
        return _EMPTY()

    def reset(self, request, context):
        self._node._life.stop()
        self._node.clear()
        with self._node._cond:
            self._node._cond.notify_all()
        log.info("node was reset")
        return _EMPTY()

    def push(self, request, context):
        self._node.push(request.value)
        return _EMPTY()

    def pop(self, request, context):
        return pb.ValueMessage(value=rpc._i32(self._node.pop_blocking(context)))


PEER_UP, PEER_DEGRADED, PEER_DOWN = "up", "degraded", "down"
_PEER_STATE_VALUE = {PEER_DOWN: 0.0, PEER_DEGRADED: 1.0, PEER_UP: 2.0}


class _PeerHealth:
    """Per-peer health states for the distributed control plane.

      up        — the last probe (or broadcast RPC) succeeded
      degraded  — 1..down_after-1 consecutive failures: transient blips,
                  traffic still flows (the node retry loops absorb them)
      down      — >= down_after consecutive failures: compute_many fails
                  FAST with PeerUnavailable instead of parking its full
                  timeout against a pipeline that cannot move

    Fed by the master's background prober (transport-level ready()
    checks, no RPC side effects) and by broadcast results; read by the
    compute path and /status; exported as the misaka_peer_state labeled
    gauge (0=down, 1=degraded, 2=up).  One recovery observation flips a
    peer straight back to up — the network heals without master restart.
    """

    def __init__(self, peers, down_after: int = 3):
        self._lock = threading.Lock()
        self._down_after = max(1, int(down_after))
        self._peers: dict[str, dict] = {
            name: {"state": PEER_UP, "failures": 0, "last_error": None}
            for name in peers
        }
        for name in self._peers:
            _G_PEER_STATE.labels(peer=name).set(_PEER_STATE_VALUE[PEER_UP])

    def record_ok(self, name: str) -> None:
        with self._lock:
            p = self._peers.setdefault(
                name, {"state": PEER_UP, "failures": 0, "last_error": None}
            )
            recovered = p["state"] == PEER_DOWN
            p["state"], p["failures"], p["last_error"] = PEER_UP, 0, None
        _G_PEER_STATE.labels(peer=name).set(_PEER_STATE_VALUE[PEER_UP])
        if recovered:
            log.info("peer %s is back up", name)

    def record_failure(self, name: str, error: str) -> None:
        with self._lock:
            p = self._peers.setdefault(
                name, {"state": PEER_UP, "failures": 0, "last_error": None}
            )
            p["failures"] += 1
            p["last_error"] = error
            was = p["state"]
            p["state"] = (
                PEER_DOWN if p["failures"] >= self._down_after else PEER_DEGRADED
            )
            state = p["state"]
        _G_PEER_STATE.labels(peer=name).set(_PEER_STATE_VALUE[state])
        if state == PEER_DOWN and was != PEER_DOWN:
            log.warning("peer %s marked down: %s", name, error)

    def down_peers(self) -> list[str]:
        with self._lock:
            return [n for n, p in self._peers.items() if p["state"] == PEER_DOWN]

    def snapshot(self) -> dict[str, dict]:
        with self._lock:
            return {n: dict(p) for n, p in self._peers.items()}


class MasterNodeProcess:
    """Distributed control plane (MasterNode, master.go:29-351): HTTP routes
    served via runtime.master.make_http_server (duck-typed), command fan-out
    over gRPC, and the Master data-plane service for program nodes' IN/OUT.
    """

    def __init__(
        self,
        node_info: dict[str, dict],
        resolver: Resolver | None = None,
        cert_file: str | None = None,
        key_file: str | None = None,
        grpc_port: int = rpc.GRPC_PORT,
        host: str = "0.0.0.0",
    ):
        self.node_info = dict(node_info)
        self._resolver = resolver or Resolver()
        self._cert, self._key = cert_file, key_file
        self._grpc_port = grpc_port
        self._host = host
        self._pool = _ClientPool(self._resolver, cert_file)
        self._life = _Lifecycle()
        # The reference uses cap-1 chans (master.go:58-59); unbounded deques
        # here only relax producer blocking, pairing is what matters.  A
        # Condition (not queue.Queue) so GetInput can re-check cancellation
        # immediately before every dequeue: a handler orphaned by reset must
        # not wake from a stale blocking get holding a fresh epoch's value.
        self._io_cond = threading.Condition()
        self._in_q: "deque[int]" = deque()
        self._out_q: "deque[int]" = deque()
        self._compute_lock = threading.Lock()
        self._stale_outputs = 0
        # bumped by _drain_queues (reset/load): a compute whose request was
        # wiped must NOT mark its missing outputs stale — nothing is coming,
        # and phantom stale entries would mispair every later request (the
        # fused MasterNode guards the same race with its epoch,
        # master.py _collect_slot)
        self._epoch = 0
        self._server: grpc.Server | None = None
        # /status additions (uptime_seconds / requests_total), mirroring the
        # fused MasterNode's observability surface
        self._created_mono = time.monotonic()
        self._requests_total = 0
        # Peer health (up/degraded/down): a background prober drives the
        # transport-level ready() check per peer; compute fails fast with
        # PeerUnavailable while any peer is down (MISAKA_PEER_DOWN_AFTER
        # consecutive failures, default 3; probe cadence MISAKA_PEER_PROBE_S,
        # default 1s — ~3s from peer death to fail-fast).
        self._health = _PeerHealth(
            self.node_info,
            down_after=int(os.environ.get("MISAKA_PEER_DOWN_AFTER", "") or 3),
        )
        self._probe_interval = float(
            os.environ.get("MISAKA_PEER_PROBE_S", "") or 1.0
        )
        self._probe_stop = threading.Event()
        self._prober: threading.Thread | None = None

    def start(self) -> int:
        self._server, port = rpc.make_server(
            {"Master": _MasterServicer(self)},
            self._grpc_port,
            self._cert,
            self._key,
            host=self._host,
        )
        self._server.start()
        self._prober = threading.Thread(
            target=self._probe_loop, daemon=True, name="misaka-peer-probe"
        )
        self._prober.start()
        log.info("master serving grpc on :%d", port)
        self._grpc_port = port
        return port

    def close(self) -> None:
        self._probe_stop.set()
        self._life.stop()
        if self._server:
            self._server.stop(grace=0.2)
        if self._prober is not None:
            self._prober.join(timeout=2)
        self._pool.close()

    def _probe_loop(self) -> None:
        """Background peer-health prober: one transport-level reachability
        check per peer per interval (rpc._Stub.ready — channel READY wait,
        no RPC side effects).  This is what notices a peer that died
        between broadcasts: the data plane is inbound-only (program nodes
        dial the master), so without active probing a dead peer is
        invisible until a request wedges against it.

        Peers are probed CONCURRENTLY (one thread per peer per sweep,
        like _broadcast): each dead peer blocks its ready() call for the
        full probe timeout, so a serial sweep would make down-detection
        latency scale with how many peers are dead — the cadence must
        stay one interval regardless of cluster size."""
        probe_timeout = min(1.0, self._probe_interval)

        def probe(name: str, info: dict) -> None:
            cls = (
                rpc.StackClient
                if info.get("type") == "stack"
                else rpc.ProgramClient
            )
            try:
                ok = self._pool.get(cls, name).ready(timeout=probe_timeout)
            except Exception as e:  # a broken channel counts as down
                self._health.record_failure(name, repr(e))
                return
            if ok:
                self._health.record_ok(name)
            else:
                self._health.record_failure(
                    name, "unreachable (connectivity probe timed out)"
                )

        while not self._probe_stop.wait(self._probe_interval):
            threads = [
                threading.Thread(target=probe, args=(name, info), daemon=True)
                for name, info in self.node_info.items()
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()

    # --- command broadcast (master.go:269-351) ------------------------------

    def _broadcast(self, command: str) -> None:
        """Concurrent fan-out, one thread per node; any error fails the whole
        broadcast (master.go:271-294)."""
        errors: list[Exception] = []
        lock = threading.Lock()
        # the HTTP request's trace does not cross thread creation by
        # itself (contextvars are per-thread): hand it to each fan-out
        # thread so the rpc.<Method> spans + wire metadata ride along
        trace = tracespan.current()

        def call(name: str, info: dict) -> None:
            try:
                cls = rpc.StackClient if info.get("type") == "stack" else rpc.ProgramClient
                client = self._pool.get(cls, name)
                with tracespan.use(trace):
                    getattr(client, command)(timeout=10)
                self._health.record_ok(name)
            except Exception as e:  # noqa: BLE001 — collected, not swallowed
                self._health.record_failure(name, str(e))
                with lock:
                    errors.append(e)

        threads = [
            threading.Thread(target=call, args=(name, info))
            for name, info in self.node_info.items()
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        _C_DIST_BROADCASTS.labels(command=command).inc()
        if errors:
            raise BroadcastError(str(errors[0]))

    # --- the HTTP-facing surface (duck-typed for make_http_server) ----------

    def run(self) -> None:
        self._life.start()  # isRunning=true before fan-out (master.go:93)
        self._broadcast("run")
        log.info("network was run")

    def pause(self) -> None:
        self._broadcast("pause")
        self._life.stop()
        log.info("network was paused")

    def reset(self) -> None:
        self._broadcast("reset")
        self._life.stop()
        self._drain_queues()
        log.info("network was reset")

    def load(self, target: str, program: str) -> None:
        """Validate target, reset network, Load the target node
        (master.go:145-195) — at the node's real gRPC port (fixes quirk #1)."""
        if target not in self.node_info:
            from misaka_tpu.runtime.topology import TopologyError

            raise TopologyError(f"node {target} not valid on this network")
        self._broadcast("reset")
        self._life.stop()
        self._drain_queues()
        client = self._pool.get(rpc.ProgramClient, target)
        try:
            client.load(program, timeout=10)
        except grpc.RpcError as e:
            raise BroadcastError(e.details() or str(e))

    def compute(self, value: int, timeout: float = 30.0) -> int:
        """One value in, one out, correlated (fixes quirk #2 — the reference
        pairs whatever output arrives first, master.go:216-219)."""
        return self.compute_many([value], timeout=timeout)[0]

    def compute_many(self, values, timeout: float = 30.0,
                     return_array: bool = False):
        """A FIFO stream of values through the distributed cluster in ONE
        request: len(values) in, len(values) out, pairing strictly ordered.

        This is the /compute_batch (and, via compute_spread, /compute_raw)
        lane for the per-process control plane: the reference moves one
        value per HTTP round trip (master.go:197-224); here a whole stream
        costs one queue append and the pipeline stays full.

        Fails FAST with PeerUnavailable (never a silent full-timeout park)
        when the health plane tracks any peer as down: a value stream
        cannot cross a dead node, so refusing at the door keeps the error
        typed, the latency bounded, and the input queue free of orphans.
        Recovery needs no master restart — the prober flips the peer back
        up and the next request flows.
        """
        # ingress truncates to the sint32 wire exactly like the reference
        # (every value crosses gRPC as sint32 anyway, messenger.proto:34-41)
        arr = np.asarray(values, dtype=np.int64).astype(np.int32)
        if arr.ndim != 1:
            raise ValueError(f"values must be a flat sequence, got shape {arr.shape}")
        if arr.size == 0:
            return np.empty((0,), np.int32) if return_array else []
        down = self._health.down_peers()
        if down:
            _C_DIST_PEER_UNAVAIL.inc()
            raise PeerUnavailable(
                f"peer(s) down: {', '.join(sorted(down))} — compute refused "
                f"(recovers automatically when the peer returns)"
            )
        _C_DIST_REQS.inc()
        _C_DIST_VALUES.inc(arr.size)
        outs: list[int] = []
        with self._compute_lock:
            self._requests_total += 1  # /status reads the int atomically
            deadline = time.monotonic() + timeout
            with self._io_cond:
                epoch = self._epoch
                self._in_q.extend(int(v) for v in arr)
                self._io_cond.notify_all()
                while len(outs) < arr.size:
                    while not self._out_q:
                        if self._epoch != epoch:
                            # reset/load wiped this request: nothing further
                            # is coming and nothing may be marked stale
                            _C_DIST_TIMEOUTS.inc()
                            raise ComputeTimeout(
                                "request wiped by reset/load mid-collect"
                            )
                        down = self._health.down_peers()
                        if down:
                            # a peer died mid-request: fail NOW with the
                            # typed error instead of burning the rest of
                            # the timeout.  The outputs still owed will
                            # surface when the peer returns — stale-mark
                            # them so later pairing survives (the same
                            # discipline as the timeout branch).  Counted
                            # on its OWN series: an alert tuned on real
                            # timeouts must not fire on peer outages.
                            self._stale_outputs += arr.size - len(outs)
                            _C_DIST_PEER_UNAVAIL.inc()
                            raise PeerUnavailable(
                                f"peer(s) down mid-compute: "
                                f"{', '.join(sorted(down))} "
                                f"({len(outs)}/{arr.size} value(s) collected)"
                            )
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            # outputs still owed to this request surface later:
                            # mark them stale so pairing survives the failure
                            self._stale_outputs += arr.size - len(outs)
                            _C_DIST_TIMEOUTS.inc()
                            raise ComputeTimeout(
                                f"no output for {arr.size - len(outs)}/"
                                f"{arr.size} value(s) after {timeout}s"
                            )
                        # slice the wait so a peer going down mid-collect is
                        # noticed within a probe interval, not at timeout
                        self._io_cond.wait(min(remaining, 0.25))
                    if self._epoch != epoch:
                        # outputs now in the queue belong to the NEW epoch:
                        # consuming them would fabricate results for wiped
                        # inputs and starve the next request (the fused
                        # master re-checks per chunk for the same reason)
                        raise ComputeTimeout(
                            "request wiped by reset/load mid-collect"
                        )
                    v = self._out_q.popleft()
                    if self._stale_outputs:
                        self._stale_outputs -= 1
                        continue
                    outs.append(v)
        out = np.asarray(outs, np.int32)
        return out if return_array else out.tolist()

    def compute_spread(self, values, timeout: float = 30.0,
                       return_array: bool = False):
        """Same stream through the single pipeline (no instance striping in
        the distributed mode) — exists so /compute_raw serves here too."""
        return self.compute_many(values, timeout=timeout, return_array=return_array)

    @property
    def is_running(self) -> bool:
        return self._life.is_running

    def status(self) -> dict:
        with self._io_cond:
            in_depth, out_depth = len(self._in_q), len(self._out_q)
        return {
            "running": self._life.is_running,
            "mode": "distributed",
            "served_engine": "distributed-grpc",
            "uptime_seconds": round(time.monotonic() - self._created_mono, 3),
            "requests_total": self._requests_total,
            "nodes": dict(self.node_info),
            # the health plane's view: {name: {state, failures, last_error}}
            # — state "down" is what compute fails fast on (PeerUnavailable)
            "peers": self._health.snapshot(),
            "in_queue": in_depth,
            "out_queue": out_depth,
        }

    def _drain_queues(self) -> None:
        with self._io_cond:
            self._in_q.clear()
            self._out_q.clear()
            self._stale_outputs = 0
            self._epoch += 1
            self._io_cond.notify_all()  # wake waiters to observe the wipe

    # --- data plane (Master service, master.go:233-249) ---------------------

    def get_input_blocking(self, context) -> int:
        """Blocks until a client value exists (GetInput, master.go:233-242).

        The cancellation checks sit immediately before the dequeue: a handler
        whose caller was reset away aborts without consuming a fresh epoch's
        value.  (The reference can lose an input here the same way its
        cancelled stack Pop loses a push, quirk #4.)
        """
        with self._io_cond:
            gen = self._life.gen
            while True:
                if self._life.cancelled(gen) or not context.is_active():
                    context.abort(grpc.StatusCode.CANCELLED, "main input cancelled")
                if self._in_q:
                    _C_DIST_INPUTS.inc()
                    return self._in_q.popleft()
                self._io_cond.wait(_POLL)

    def send_output(self, value: int) -> None:
        with self._io_cond:
            self._out_q.append(int(value))
            self._io_cond.notify_all()
        _C_DIST_OUTPUTS.inc()


class _MasterServicer:
    def __init__(self, node: MasterNodeProcess):
        self._node = node

    def get_input(self, request, context):
        return pb.ValueMessage(value=rpc._i32(self._node.get_input_blocking(context)))

    def send_output(self, request, context):
        self._node.send_output(request.value)
        return _EMPTY()


def build_loopback_cluster(node_info, programs, master_name: str = "last_order"):
    """Spin the whole wire-compatible cluster on loopback ephemeral ports.

    node_info: {name: "program"|"stack"}; programs: {name: source}.  Returns
    (master, close): a started (not yet /run) MasterNodeProcess plus a
    close() that tears everything down in dependency order — master first,
    then program nodes, then stacks — so no free-running execute loop is
    left retrying RPCs against an already-closed peer.  Shared by the
    cross-mode differential suite and the parity replayer's --local mode.
    """
    resolver = Resolver()
    stacks: list[StackNodeProcess] = []
    progs: list[ProgramNodeProcess] = []
    master: MasterNodeProcess | None = None

    def close() -> None:
        for n in ([master] if master is not None else []) + progs + stacks:
            n.close()

    try:
        for name, kind in node_info.items():
            if kind == "stack":
                s = StackNodeProcess(grpc_port=0, host="127.0.0.1")
                resolver.set_addr(name, f"127.0.0.1:{s.start()}")
                stacks.append(s)
        for name, kind in node_info.items():
            if kind == "program":
                p = ProgramNodeProcess(
                    master_uri=master_name, resolver=resolver,
                    grpc_port=0, host="127.0.0.1",
                )
                p.load_program(programs[name])
                resolver.set_addr(name, f"127.0.0.1:{p.start()}")
                progs.append(p)
        master = MasterNodeProcess(
            node_info={n: {"type": k} for n, k in node_info.items()},
            resolver=resolver, grpc_port=0, host="127.0.0.1",
        )
        resolver.set_addr(master_name, f"127.0.0.1:{master.start()}")
    except BaseException:
        close()
        raise
    return master, close
