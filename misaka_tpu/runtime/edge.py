"""The production edge: one route table + ordered middleware chain shared
by every HTTP surface of the serving plane.

The reference ships its control plane over gRPC **with TLS** and nothing
else at the door; this build's ROADMAP ("heavy traffic from millions of
users") needs the rest of a production edge too.  Before this module,
edge policy was scattered: app.py owned the listeners, utils/httpfast.py
the parsing, runtime/frontends.py its own copy of the body-limit checks —
and NOTHING kept one overloaded or abusive tenant from saturating the
ServeBatcher and timing everyone else out after a 30s ComputeTimeout.

This module extracts the route table + middleware chain so edge policy
composes per route, identically on all three serving surfaces:

  * the ENGINE server (runtime/master.py make_http_server) — direct HTTP;
  * the FRONTEND workers (runtime/frontends.py) — TLS termination + the
    local backpressure guard; auth/quota/admission for their hot-route
    traffic run engine-side per compute-plane frame (state must be
    global: N workers each holding 1/Nth of a token bucket would not be
    a quota);
  * the FLEET control server (runtime/fleet.py) — auth on the operator
    surface, policy enforced by the replica a request lands on.

The chain, in order (each stage has its own kill switch):

  1. AUTH (MISAKA_EDGE_AUTH=0 disables) — API keys in a reloadable JSON
     file (MISAKA_API_KEYS, or <MISAKA_PROGRAMS_DIR>/api_keys.json when
     present).  Keys map to TENANTS; lookups are constant-time HMAC
     digests of the presented key, never the key itself.  Missing key ->
     401; known key without the required scope (admin routes, program
     allowlists) -> 403.  The key file hot-reloads on mtime change — no
     restart to rotate a key.
  2. QUOTA (MISAKA_EDGE_QUOTA=0 disables) — per-tenant token buckets for
     requests/s (`rps`) and values/s (`vps`), plus a `cpu` budget (core-
     seconds per second over a sliding window) enforced against the PR 7
     usage ledger's per-program cpu_seconds.  Specs use the MISAKA_SLO
     grammar shape: MISAKA_QUOTA="rps<100,vps<500000,cpu<0.5".
     Precedence is FIELD-WISE, most specific wins:
     key-file entry  >  program upload metadata (`quota` form field)  >
     MISAKA_QUOTA env default.  Exhaustion answers a typed 429 with a
     computed Retry-After.
  3. ADMISSION (MISAKA_EDGE_ADMISSION=0 disables) — a concurrency/queue-
     depth governor fed by the LIVE ServeBatcher waiting-values signal
     and the SLO burn-rate state: beyond the soft watermark
     (MISAKA_ADMISSION_HIGH values, halved while any SLO pages) tenants
     above their fair share of the recent admission window are shed
     (typed 429 + Retry-After) while under-share neighbors keep flowing;
     beyond the hard watermark (2x) everything is shed — the plane keeps
     headroom and admitted requests never die of ComputeTimeout.

TLS rides next to the chain (MISAKA_TLS_CERT/MISAKA_TLS_KEY wrap the
public listener via stdlib ssl), and the fleet compute plane gets a
shared-secret handshake (MISAKA_PLANE_SECRET): a connecting PlaneClient
must present an HMAC of the plane protocol tag before any frame is read.

Every decision is observable: misaka_edge_admitted_total{tenant} /
misaka_edge_rejected_total{reason,tenant} (cardinality-guarded like every
per-tenant series), and a rejected traced request carries an `edge.reject`
span with the tenant + reason.

Stdlib-only (+ the stdlib-only utils.metrics/faults/tracespan/slo): the
jax-free frontend workers import this module.
"""

from __future__ import annotations

import base64
import collections
import hashlib
import hmac
import json
import logging
import os
import ssl
import threading
import time

from misaka_tpu.utils import faults, metrics

log = logging.getLogger(__name__)

# --- metrics ----------------------------------------------------------------

M_EDGE_ADMITTED = metrics.counter(
    "misaka_edge_admitted_total",
    "Requests admitted through the edge middleware chain, by tenant",
    ("tenant",),
)
M_EDGE_REJECTED = metrics.counter(
    "misaka_edge_rejected_total",
    "Requests rejected at the edge, by reason "
    "(unauthenticated/forbidden/rate/values/cpu/overload) and tenant",
    ("reason", "tenant"),
)
M_PLANE_TLS_REJECTED = metrics.counter(
    "misaka_plane_tls_rejected_total",
    "Plane connections refused at the mTLS gate, by reason "
    "(plaintext/bad_cert/handshake)",
    ("reason",),
)
M_PLANE_TLS_RELOADS = metrics.counter(
    "misaka_plane_tls_reloads_total",
    "Plane TLS cert/key/CA hot-reload attempts, by status (ok/error)",
    ("status",),
)
M_EDGE_TOKENS = metrics.counter(
    "misaka_edge_tokens_total",
    "Tenant-token operations, by op (mint/ok/expired/invalid)",
    ("op",),
)
M_EDGE_GOSSIP_ROUNDS = metrics.counter(
    "misaka_edge_gossip_rounds_total",
    "Usage-gossip applications at this replica, by status (ok/stale/error)",
    ("status",),
)
M_EDGE_GOSSIP_DRAINED = metrics.counter(
    "misaka_edge_gossip_drained_total",
    "Tokens drained from local quota buckets to reconcile remote usage, "
    "by field (rps/vps)",
    ("field",),
)

# Tenant label cardinality rides the ONE health-plane budget
# (MISAKA_USAGE_LABEL_MAX via metrics.tenant_label_budget): client-chosen
# tenant names must not mint unbounded series.
_tenant_labels_lock = threading.Lock()
_tenant_labels: set[str] = set()


def tenant_metric_label(tenant: str | None) -> str:
    """`tenant` resolved against the shared cardinality budget (new
    tenants past the cap collapse to "other").  Lock-free on the hot
    path: a known label is a plain set read (GIL-atomic); only a NEW
    label takes the lock."""
    label = tenant or "default"
    if label in _tenant_labels:
        return label
    with _tenant_labels_lock:
        label = metrics.capped_label(
            _tenant_labels, label, metrics.tenant_label_budget()
        )
        _tenant_labels.add(label)
    return label


# Program-keyed edge STATE (cpu meters) rides its own capped set — the
# same budget, but program names must not consume the tenant slots.
_program_labels_lock = threading.Lock()
_program_labels: set[str] = set()


def _program_state_label(program: str) -> str:
    if program in _program_labels:
        return program
    with _program_labels_lock:
        label = metrics.capped_label(
            _program_labels, program, metrics.tenant_label_budget()
        )
        _program_labels.add(label)
    return label


# Per-tenant metric children resolved once (the labels() walk + its lock
# must not run per admitted request — the r12 ledger's discipline).
_children_lock = threading.Lock()
_admitted_children: dict[str, object] = {}


def _admitted_child(label: str):
    c = _admitted_children.get(label)
    if c is None:
        with _children_lock:
            c = _admitted_children.setdefault(
                label, M_EDGE_ADMITTED.labels(tenant=label)
            )
    return c


_rejected_children: dict[tuple[str, str], object] = {}


def _rejected_child(reason: str, label: str):
    # a shed is the edge's highest-QPS state — the rejection path must
    # not pay the labels() walk per request either
    k = (reason, label)
    c = _rejected_children.get(k)
    if c is None:
        with _children_lock:
            c = _rejected_children.setdefault(
                k, M_EDGE_REJECTED.labels(reason=reason, tenant=label)
            )
    return c


# --- decisions --------------------------------------------------------------


class EdgeReject(Exception):
    """A typed edge rejection: HTTP status + machine-readable reason +
    optional Retry-After seconds.  Raised by middleware `check` hooks and
    rendered by each surface (HTTP header Retry-After; plane frames ship
    it as a JSON body so the frontend can restore the header).  `tenant`
    is attached where known so a worker honoring the Retry-After locally
    can report its shed counts under the right label."""

    def __init__(self, status: int, reason: str, message: str,
                 retry_after: float | None = None,
                 tenant: str | None = None):
        super().__init__(message)
        self.status = int(status)
        self.reason = reason
        self.message = message
        self.retry_after = retry_after
        self.tenant = tenant

    def headers(self) -> list[tuple[str, str]]:
        out = []
        if self.retry_after is not None:
            # ceil to whole seconds: Retry-After is delta-seconds
            out.append(("Retry-After", str(max(1, int(-(-self.retry_after // 1))))))
        if self.status == 401:
            out.append(("WWW-Authenticate",
                        'Bearer realm="misaka", charset="UTF-8"'))
        return out

    def to_wire(self) -> bytes:
        """The plane-frame body shape: JSON so the frontend worker can
        rebuild the Retry-After header client-side."""
        obj = {"error": self.message, "reason": self.reason}
        if self.retry_after is not None:
            obj["retry_after"] = round(self.retry_after, 3)
        if self.tenant is not None:
            obj["tenant"] = self.tenant
        return json.dumps(obj).encode()

    @staticmethod
    def from_wire(status: int, body: bytes) -> "EdgeReject | None":
        """Inverse of to_wire (None when the body is not an edge payload)."""
        try:
            obj = json.loads(body.decode())
            if not isinstance(obj, dict) or "reason" not in obj:
                return None
            return EdgeReject(
                status, str(obj["reason"]), str(obj.get("error", "")),
                retry_after=float(obj["retry_after"])
                if obj.get("retry_after") is not None else None,
                tenant=str(obj["tenant"])
                if obj.get("tenant") is not None else None,
            )
        except (ValueError, TypeError, UnicodeDecodeError):
            return None


# every reason the chain can emit — frame-carried shed reports are
# clamped to this set so wire metadata cannot mint label values
REASONS = frozenset({
    "unauthenticated", "forbidden", "rate", "values", "cpu", "overload",
})


def count_shed(tenant: str | None, reason: str, n: int = 1) -> None:
    """Record `n` edge rejections made AWAY from a chain (the frontend
    workers' local shed cache honors an engine-issued Retry-After and
    ships its counts back in frame metadata — without this the headline
    misaka_edge_rejected_total would under-report by the cache's whole
    hit rate during exactly the floods it exists to measure)."""
    _rejected_child(
        reason if reason in REASONS else "other",
        tenant_metric_label(tenant),
    ).inc(max(1, int(n)))


class Decision:
    """One edge evaluation: the resolved tenant (always set — metrics and
    traces label rejections too) and the rejection, if any."""

    __slots__ = ("tenant", "reject", "key_entry")

    def __init__(self, tenant: str | None, reject: EdgeReject | None = None,
                 key_entry: "dict | None" = None):
        self.tenant = tenant
        self.reject = reject
        self.key_entry = key_entry


# --- route table ------------------------------------------------------------

# Which middleware stages apply per route class.  The table is the
# composition contract every surface shares:
#   * OPEN      — no edge at all (load-balancer probes, Prometheus
#                 scrapers; locking these behind keys breaks monitoring);
#   * COMPUTE   — the full chain: auth + quota + admission (the data
#                 plane is where overload and abuse live);
#   * ADMIN     — auth with the `admin` scope (lifecycle and operator
#                 mutations; no quota/admission — a /pause must land even
#                 during an overload shed);
#   * READ      — auth only (introspection: /status, /debug/*, registry
#                 listings).
OPEN_ROUTES = frozenset({"/healthz", "/metrics"})
COMPUTE_ROUTES = frozenset({"/compute", "/compute_batch", "/compute_raw"})
ADMIN_ROUTES = frozenset({
    "/run", "/pause", "/reset", "/load", "/checkpoint", "/restore",
    "/profile/start", "/profile/stop", "/fleet/roll", "/fleet/drain",
    "/debug/faults",  # fault injection is an operator mutation
    # the capture plane records raw request/response payloads — arming,
    # exporting, and reading it are operator actions, not tenant reads
    "/captures/start", "/captures/stop", "/captures/export",
    "/captures/rotate", "/debug/captures",
    # the billing export carries per-tenant totals for EVERY tenant —
    # an operator read, not a tenant one
    "/usage/export",
    # minting tenant tokens hands out credentials; gossip mutates quota
    # bucket state — both are fleet/operator mutations
    "/edge/token", "/edge/gossip",
})


def route_policy(route: str, method: str = "POST") -> tuple[str, ...]:
    """The ordered middleware stages for one (route, method).  Returns a
    tuple drawn from ("auth", "auth_admin", "quota", "admission")."""
    if route in OPEN_ROUTES:
        return ()
    if route in COMPUTE_ROUTES:
        return ("auth", "quota", "admission")
    if route in ADMIN_ROUTES:
        return ("auth_admin",)
    if route == "/programs" and method == "POST":
        # publishing a program version mutates the registry: admin scope
        return ("auth_admin",)
    return ("auth",)


# --- API key file -----------------------------------------------------------


def _digest(key: str) -> bytes:
    """Constant-shape identifier for a presented key: HMAC-SHA256 under a
    fixed tag.  Lookups compare digests (hmac.compare_digest), so neither
    the table walk nor the comparison leaks key bytes through timing."""
    return hmac.new(b"misaka-api-key-v1", key.encode(), hashlib.sha256).digest()


class KeyFile:
    """A reloadable API-key table.

    File shape (JSON, lives next to MISAKA_PROGRAMS_DIR by convention):

        {"keys": [
          {"key": "alice-secret", "tenant": "alice", "admin": true},
          {"key": "bob-secret", "tenant": "bob",
           "programs": ["dense"], "quota": "rps<50,vps<20000"}
        ]}

    Entries: `key` (required), `tenant` (required — the label quotas,
    fair-share, and metrics use), `admin` (default false — required for
    ADMIN_ROUTES), `programs` (optional allowlist; a request addressed to
    a program outside it is 403), `quota` (optional per-key spec,
    field-wise overriding the program/env specs), `disabled` (true ->
    403, the revocation-without-deletion state).

    Hot reload: the file's mtime+size are stat'd at most every 0.5s; a
    change swaps the parsed table atomically.  A file that fails to parse
    KEEPS the previous table (and logs loudly) — a typo'd rotation must
    not open the edge or lock every tenant out.
    """

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._by_digest: dict[bytes, dict] = {}
        self._stamp: tuple[float, int] | None = None
        self._next_stat = 0.0
        self._load(force=True)

    def _load(self, force: bool = False) -> None:
        try:
            st = os.stat(self.path)
            stamp = (st.st_mtime, st.st_size)
        except OSError:
            if force:
                log.warning("edge: key file %s unreadable; no keys loaded",
                            self.path)
            return
        if not force and stamp == self._stamp:
            return
        try:
            with open(self.path) as f:
                obj = json.load(f)
            entries = obj["keys"] if isinstance(obj, dict) else obj
            table: dict[bytes, dict] = {}
            for e in entries:
                key = e["key"]
                tenant = e["tenant"]
                if not isinstance(key, str) or not isinstance(tenant, str):
                    raise ValueError("key and tenant must be strings")
                quota_spec = None
                if e.get("quota") is not None:
                    # parse ONCE at load: the hot path reads the dict
                    quota_spec = parse_quota_spec(e["quota"])
                    if quota_spec.pop("cpu", None) is not None:
                        # cpu budgets are measured per PROGRAM (the
                        # usage ledger's attribution unit) — a key-level
                        # cpu field would bill one tenant for a program
                        # all tenants share, shedding the innocent one
                        log.warning(
                            "edge: key for tenant %r declares a `cpu` "
                            "quota; cpu budgets are per-program (use "
                            "the POST /programs quota field or "
                            "MISAKA_QUOTA) — ignored", tenant,
                        )
                table[_digest(key)] = {
                    "tenant": tenant,
                    "admin": bool(e.get("admin")),
                    "programs": (
                        frozenset(e["programs"])
                        if e.get("programs") is not None else None
                    ),
                    "quota": e.get("quota"),
                    "quota_spec": quota_spec,
                    "disabled": bool(e.get("disabled")),
                }
        except (OSError, ValueError, TypeError, KeyError) as e:
            log.error("edge: key file %s failed to parse (%s); keeping the "
                      "previous table", self.path, e)
            self._stamp = stamp  # don't re-parse the same broken file hot
            return
        self._by_digest = table
        self._stamp = stamp
        log.info("edge: loaded %d API key(s) from %s", len(table), self.path)

    def lookup(self, key: str | None) -> dict | None:
        """The entry for a presented key (None = unknown/missing).  Stats
        the file for changes at most every 0.5s."""
        now = time.monotonic()
        if now >= self._next_stat:
            with self._lock:
                if now >= self._next_stat:
                    self._next_stat = now + 0.5
                    self._load()
        if key is None:
            return None
        # the table is keyed by HMAC digest of the key, so the dict walk
        # never touches key bytes — timing can only leak the digest,
        # which is exactly what HMAC makes safe to leak
        return self._by_digest.get(_digest(key))

    def __len__(self) -> int:
        return len(self._by_digest)


# --- quota specs ------------------------------------------------------------


class QuotaSpecError(ValueError):
    """Malformed quota spec (grammar: "rps<100,vps<500000,cpu<0.5")."""


_QUOTA_FIELDS = ("rps", "vps", "cpu")


def parse_quota_spec(text: str | None) -> dict[str, float]:
    """`"rps<100,vps<500000,cpu<0.5"` -> {"rps": 100.0, ...}.  The `<`
    separator mirrors the MISAKA_SLO grammar (utils/slo.py); `=` is
    accepted as a synonym."""
    out: dict[str, float] = {}
    for raw in (text or "").split(","):
        entry = raw.strip()
        if not entry:
            continue
        for sep in ("<", "="):
            if sep in entry:
                name, _, val = entry.partition(sep)
                break
        else:
            raise QuotaSpecError(
                f"cannot parse quota term {entry!r} (want name<value)"
            )
        name = name.strip()
        if name not in _QUOTA_FIELDS:
            raise QuotaSpecError(
                f"unknown quota field {name!r} (known: {_QUOTA_FIELDS})"
            )
        try:
            limit = float(val.strip())
        except ValueError:
            raise QuotaSpecError(
                f"cannot parse quota value {val!r} in {entry!r}"
            ) from None
        if limit <= 0:
            raise QuotaSpecError(f"quota {name} must be > 0, got {limit}")
        out[name] = limit
    return out


class TokenBucket:
    """A classic token bucket: `rate` tokens/s, capacity `rate*burst_s`.
    take(n) either admits (True, 0.0) or rejects with the seconds until
    n tokens will exist (the Retry-After)."""

    __slots__ = ("rate", "capacity", "tokens", "stamp", "_lock")

    def __init__(self, rate: float, burst_s: float = 2.0):
        self.rate = float(rate)
        self.capacity = max(1.0, self.rate * burst_s)
        self.tokens = self.capacity
        self.stamp = time.monotonic()
        self._lock = threading.Lock()

    def take(self, n: float = 1.0) -> tuple[bool, float]:
        with self._lock:
            now = time.monotonic()
            self.tokens = min(
                self.capacity, self.tokens + (now - self.stamp) * self.rate
            )
            self.stamp = now
            if self.tokens >= n:
                self.tokens -= n
                return True, 0.0
            need = min(n, self.capacity) - self.tokens
            return False, need / self.rate if self.rate > 0 else 60.0

    def drain(self, n: float) -> None:
        """Remove `n` tokens WITHOUT admitting anything — the gossip
        reconciliation hook.  Unlike take(), the balance may go negative
        (down to -capacity): remote admissions already happened, and the
        debt makes this replica refuse local traffic until the aggregate
        rate is repaid.  The floor bounds recovery time — a long
        partition must not leave a tenant locked out for minutes after
        it heals."""
        if n <= 0:
            return
        with self._lock:
            now = time.monotonic()
            self.tokens = min(
                self.capacity, self.tokens + (now - self.stamp) * self.rate
            )
            self.stamp = now
            self.tokens = max(-self.capacity, self.tokens - n)


class CpuMeter:
    """Sliding-window cpu-seconds enforcement against the PR 7 usage
    ledger: `reader()` returns a program's cumulative cpu_seconds; the
    meter keeps (t, cpu) samples over `window_s` and rejects while the
    windowed consumption exceeds `limit_frac * window_s` core-seconds."""

    __slots__ = ("window_s", "_samples", "_lock")

    def __init__(self, window_s: float):
        self.window_s = float(window_s)
        self._samples: list[tuple[float, float]] = []
        self._lock = threading.Lock()

    def check(self, cpu_now: float, limit_frac: float) -> tuple[bool, float]:
        now = time.monotonic()
        with self._lock:
            s = self._samples
            if not s or now - s[-1][0] >= 0.05:
                s.append((now, cpu_now))
            while s and now - s[0][0] > self.window_s:
                s.pop(0)
            if not s:
                return True, 0.0
            consumed = cpu_now - s[0][1]
        budget = limit_frac * self.window_s
        if consumed <= budget:
            return True, 0.0
        # assume consumption stops: the window must slide far enough that
        # the overage ages out — proportional estimate, clamped sane
        frac_over = (consumed - budget) / max(consumed, 1e-9)
        return False, min(self.window_s, max(1.0, frac_over * self.window_s))


# --- admission governor -----------------------------------------------------


class AdmissionGovernor:
    """Queue-depth + fair-share load shedding at the door.

    `signals()` returns (waiting_values, slo_page): the LIVE ServeBatcher
    backlog (summed across per-program engines) and whether any SLO pages.
    Policy:

      * waiting < soft            -> admit everyone;
      * soft <= waiting < hard    -> shed tenants ABOVE their fair share
        of the recent (1s) admission window — the flooding tenant sheds
        first while an in-quota neighbor keeps flowing.  With a single
        active tenant there is no one to be fair to: admit until hard.
      * waiting >= hard (2x soft) -> shed everything (the plane keeps
        headroom; admitted work must never die of ComputeTimeout).

    A paging SLO halves the soft watermark: burn-rate pressure tightens
    admission before latency collapses.  Retry-After is derived from the
    observed drain rate of the recent window (clamped [0.05s, 5s]).
    """

    # fair-share slack: a tenant may hold up to 1.5x its equal share of
    # the admission window before the soft zone sheds it
    FAIR_SLACK = 1.5

    def __init__(self, signals, high_values: int):
        self._signals = signals
        self.high = max(1, int(high_values))
        self._lock = threading.Lock()
        # incremental window accounting: the deque holds the raw
        # admissions, the dict the RUNNING per-tenant sums — evicting
        # expired entries is amortized O(1) per admission, so the hot
        # path never rebuilds shares from the whole window (the first
        # implementation did, under this lock, and the conc64 A/B
        # measured 16% — serialized O(window) work per request)
        self._events: collections.deque = collections.deque()
        self._sums: dict[str, int] = {}
        self._total = 0
        self.window_s = 1.0

    def _evict(self, now: float) -> None:
        """Drop admissions older than the window (call under _lock)."""
        dq = self._events
        while dq and now - dq[0][0] > self.window_s:
            _, tenant, values = dq.popleft()
            self._total -= values
            s = self._sums.get(tenant, 0) - values
            if s <= 0:
                self._sums.pop(tenant, None)
            else:
                self._sums[tenant] = s

    def check(self, tenant: str, values: int) -> EdgeReject | None:
        waiting, page = self._signals()
        now = time.monotonic()
        # chaos (utils/faults.py): `overload` saturates the governor for
        # everyone, `overload:<tenant>` for one tenant — the shed drill
        # without needing 4x real load in a unit test
        if faults.armed():
            forced = faults.fire("overload")
            if forced is None:
                forced = faults.fire(f"overload:{tenant}")
            if forced is not None:
                return self._reject(waiting, values, 0, forced=True)
        soft = self.high // 2 if page else self.high
        hard = self.high * 2
        # one lock hold, never re-entered: the rejection itself is built
        # OUTSIDE (the ledger/SLO planes each once grew a recursive
        # resolve under a non-reentrant lock and self-deadlocked)
        with self._lock:
            self._evict(now)
            drained = self._total
            shed = waiting >= hard
            if not shed and waiting >= soft and len(self._sums) > 1:
                fair = self.FAIR_SLACK / len(self._sums)
                shed = (
                    self._sums.get(tenant, 0) / (drained or 1) > fair
                )
            if not shed:
                self._events.append((now, tenant, values))
                self._total += values
                self._sums[tenant] = self._sums.get(tenant, 0) + values
        if shed:
            return self._reject(waiting, values, drained)
        return None

    def _reject(self, waiting: int, values: int, drained: int,
                forced: bool = False) -> EdgeReject:
        """Build the typed 429 (lock-free: `drained` — admitted values in
        the recent window, the observed drain rate — comes from the
        caller's lock hold)."""
        rate = max(drained / self.window_s, 1.0)
        retry = min(5.0, max(0.05, (waiting + values) / rate)) \
            if not forced else 1.0
        return EdgeReject(
            429, "overload",
            f"admission control: {waiting} values already waiting "
            f"(watermark {self.high}); retry after backoff",
            retry_after=retry,
        )


# --- the chain --------------------------------------------------------------


class EdgeChain:
    """The ordered middleware chain + route table, evaluated by every
    serving surface via `check()`.  Build one per process with
    `from_env()` and install it (`install()`); the compute plane and the
    HTTP handlers read the installed chain."""

    def __init__(
        self,
        keyfile: KeyFile | None = None,
        quota_defaults: dict[str, float] | None = None,
        governor: AdmissionGovernor | None = None,
        cpu_reader=None,
        rate_scale: float = 1.0,
        auth_enabled: bool = True,
        quota_enabled: bool = True,
        admission_enabled: bool = True,
        burst_s: float = 2.0,
        cpu_window_s: float = 60.0,
        internal_token: str | None = None,
        token_secret: bytes | None = None,
    ):
        # MISAKA_EDGE_INTERNAL_TOKEN: a per-boot secret the fleet parent
        # mints and hands its replicas, presented as the key on the
        # fleet's OWN control-plane calls (drain, roll checkpoints,
        # aggregation fetches) — without it an authenticated fleet could
        # never roll, because no operator key lives in the parent.
        # Admin-scoped, never persisted, dies with the fleet process.
        self.internal_token = internal_token
        # signed short-lived tenant tokens (see mint_tenant_token): any
        # replica holding the secret verifies locally, zero coordination
        self.token_secret = token_secret if auth_enabled else None
        self.keyfile = keyfile if auth_enabled else None
        self.quota_defaults = dict(quota_defaults or {})
        self.governor = governor if admission_enabled else None
        self.cpu_reader = cpu_reader
        self.rate_scale = max(1e-9, float(rate_scale))
        # armed even with no env defaults: per-key and per-program specs
        # may arrive later (key-file reload, registry upload)
        self.quota_enabled = bool(quota_enabled)
        self.burst_s = burst_s
        self.cpu_window_s = cpu_window_s
        self._lock = threading.Lock()
        self._buckets: dict[tuple[str, str, float], TokenBucket] = {}
        self._cpu_meters: dict[str, CpuMeter] = {}
        self._program_quotas: dict[str, dict[str, float]] = {}
        # fleet-coherent quota state: cumulative admitted quota tokens
        # per (capped tenant label, field), exchanged as usage gossip
        # (usage_snapshot/apply_remote_usage) so sibling replicas drain
        # each other's buckets instead of each admitting the full quota
        self._gossip_lock = threading.Lock()
        self._usage: dict[tuple[str, str], float] = {}
        self._gossip_applied: dict[str, dict[str, float]] = {}

    # -- configuration hooks -------------------------------------------------

    @property
    def armed(self) -> bool:
        """True when ANY stage can reject (the fast-path gate).  A token
        secret arms the chain on its own: a presented-but-expired tenant
        token must answer its typed 401 even on a replica with no key
        table and every other stage disarmed."""
        return (
            self.keyfile is not None
            or self.token_secret is not None
            or self.quota_enabled
            or self.governor is not None
        )

    def set_program_quota(self, program: str, spec: str | None) -> None:
        """Install/clear a per-program quota override (the registry calls
        this when a version with a `quota` upload field becomes latest).
        Raises QuotaSpecError on a malformed spec — validate-first, like
        the registry's slo field."""
        with self._lock:
            if spec is None:
                self._program_quotas.pop(program, None)
            else:
                self._program_quotas[program] = parse_quota_spec(spec)

    def program_quota(self, program: str | None) -> dict[str, float] | None:
        # lock-free read: installs swap whole dict VALUES under the
        # lock, and a dict get is GIL-atomic
        if program is None or not self._program_quotas:
            return None
        return self._program_quotas.get(program)

    # -- evaluation ----------------------------------------------------------

    def resolve_tenant(self, key: str | None,
                       program: str | None) -> tuple[str, dict | None]:
        """The tenant a request bills to: its API key's tenant when auth
        is armed and the key resolves, else the program label (the
        pre-edge per-program tenancy).  The fleet's per-boot internal
        token resolves to an admin-scoped synthetic tenant."""
        if (
            self.internal_token is not None
            and key is not None
            and hmac.compare_digest(
                # compare BYTES: compare_digest raises TypeError on
                # non-ASCII str input, and a client-chosen header must
                # never turn into a 500 (or kill a plane connection)
                key.encode("utf-8", "surrogateescape"),
                self.internal_token.encode(),
            )
        ):
            return "_fleet", {"tenant": "_fleet", "admin": True,
                              "programs": None, "quota": None,
                              "quota_spec": None, "disabled": False}
        if (
            self.token_secret is not None
            and key is not None
            and key.startswith(TOKEN_PREFIX)
        ):
            # signed tenant token: verified locally, no key-table entry
            # needed — the zero-coordination multi-replica credential
            entry, why = verify_tenant_token(self.token_secret, key)
            _token_child(why).inc()
            if entry is not None:
                return entry["tenant"], entry
            # typed 401 downstream (never fall through to the key table:
            # an expired token must say so, not "unknown API key")
            return (program or "default"), {"_bad_token": why}
        entry = self.keyfile.lookup(key) if self.keyfile is not None else None
        if entry is not None:
            return entry["tenant"], entry
        return (program or "default"), None

    def _bucket(self, tenant: str, field: str, rate: float) -> TokenBucket:
        # the RATE is part of the key: a tenant alternating between
        # programs with different quota overrides must drain two
        # separate buckets (each bounded), never cause a keyed-by-tenant
        # bucket to be recreated at full burst on every flip — that
        # recreation was a complete rate-limit bypass.  Rates come from
        # validated operator config (env/key-file/program specs), never
        # from clients, so the per-tenant rate count is small; the cap
        # below only bounds drift across years of quota ROTATIONS.
        k = (tenant, field, rate)
        b = self._buckets.get(k)
        if b is None:
            with self._lock:
                b = self._buckets.get(k)
                if b is None:
                    same = [
                        k2 for k2 in self._buckets
                        if k2[0] == tenant and k2[1] == field
                    ]
                    if len(same) >= 8:
                        del self._buckets[same[0]]
                    b = TokenBucket(rate * self.rate_scale, self.burst_s)
                    self._buckets[k] = b
        return b

    def _effective_quota(self, entry: dict | None,
                         program: str | None) -> dict[str, float]:
        """Field-wise precedence: key entry > program metadata > env.
        The overwhelmingly common case (no overrides) returns the shared
        env-default dict without copying — callers only read it.  Key
        specs are parsed ONCE at key-file load (`quota_spec`)."""
        pq = self.program_quota(program.partition("@")[0] if program else None)
        kq = entry.get("quota_spec") if entry is not None else None
        if pq is None and not kq:
            return self.quota_defaults
        q = dict(self.quota_defaults)
        if pq:
            q.update(pq)
        if kq:
            q.update(kq)
        return q

    def check(
        self,
        route: str,
        method: str = "POST",
        key: str | None = None,
        program: str | None = None,
        values: int = 1,
        requests: int = 1,
    ) -> Decision:
        """Evaluate the chain for one request (or one compute-plane
        frame fusing `requests` client requests — frames pack per
        tenant, so a frame decision IS a tenant decision).  Never
        raises: the rejection (if any) rides the returned Decision.
        Metrics are recorded here — every surface gets the same
        accounting."""
        stages = route_policy(route, method)
        tenant, entry = self.resolve_tenant(key, program)
        if not stages or not self.armed:
            return Decision(tenant, None, entry)
        # ALL per-tenant state (buckets, cpu meters, the governor's
        # fair-share sums) keys on the CAPPED label, like the metric
        # series: tenant names are client-chosen (the program header
        # when auth is off), and unbounded dict growth on invented
        # names would be a memory DoS.  Past the budget, excess tenants
        # share the "other" state — the same collapse the whole health
        # plane applies.
        label = tenant_metric_label(tenant)
        reject = self._run_stages(stages, label, entry, key, program,
                                  values, requests)
        # count per fused client REQUEST, not per frame: a plane frame
        # coalesces `requests` of them, and the headline counters must
        # not under-report by exactly the coalescing factor under load
        if reject is None:
            _admitted_child(label).inc(max(1, requests))
        else:
            _rejected_child(reject.reason, label).inc(max(1, requests))
        return Decision(tenant, reject, entry)

    def _run_stages(self, stages, tenant_label, entry, key, program,
                    values, requests=1) -> EdgeReject | None:
        """`tenant_label` is the CAPPED tenant (check() resolves it) —
        every stateful stage keys on it."""
        tenant = tenant_label
        for stage in stages:
            if stage in ("auth", "auth_admin") and (
                entry is not None and entry.get("_bad_token")
            ):
                # a presented-but-unverifiable tenant token is always a
                # typed 401, even when no key table is armed
                why = entry["_bad_token"]
                return EdgeReject(
                    401, "unauthenticated",
                    "tenant token expired; mint a new one at /edge/token"
                    if why == "expired" else "tenant token invalid",
                )
            if stage in ("auth", "auth_admin") and self.keyfile is not None:
                if key is None:
                    return EdgeReject(
                        401, "unauthenticated",
                        "API key required (X-Misaka-Key header or "
                        "Authorization: Bearer <key>)",
                    )
                if entry is None:
                    return EdgeReject(
                        401, "unauthenticated", "unknown API key"
                    )
                if entry.get("disabled"):
                    return EdgeReject(403, "forbidden", "API key disabled")
                if stage == "auth_admin" and not entry.get("admin"):
                    return EdgeReject(
                        403, "forbidden",
                        "this route requires an admin-scoped API key",
                    )
                allow = entry.get("programs")
                if allow is not None and program is not None and (
                    program.partition("@")[0] not in allow
                ):
                    return EdgeReject(
                        403, "forbidden",
                        f"API key not authorized for program "
                        f"{program.partition('@')[0]!r}",
                    )
            elif stage == "quota" and self.quota_enabled:
                r = self._check_quota(tenant, entry, program, values,
                                      requests)
                if r is not None:
                    return r
            elif stage == "admission" and self.governor is not None:
                r = self.governor.check(tenant, values)
                if r is not None:
                    return r
        return None

    def _check_quota(self, tenant, entry, program,
                     values, requests=1) -> EdgeReject | None:
        if faults.armed() and faults.fire("quota_exhaust") is not None:
            return EdgeReject(
                429, "rate", "quota exhausted (injected fault)",
                retry_after=1.0,
            )
        q = self._effective_quota(entry, program)
        if not q:
            return None
        if "rps" in q:
            bucket = self._bucket(tenant, "rps", q["rps"])
            # a coalesced frame can fuse more requests than the burst
            # capacity holds tokens — the clients each sent ONE request,
            # so unlike the oversized-vps case there is nothing for them
            # to split; clamp the charge at capacity so the frame can
            # eventually be admitted (the vps/value quota remains the
            # precise limiter)
            charge = min(max(1.0, float(requests)), bucket.capacity)
            ok, retry = bucket.take(charge)
            if not ok:
                return EdgeReject(
                    429, "rate",
                    f"request rate quota exhausted "
                    f"({q['rps']:g} requests/s)",
                    retry_after=retry,
                )
            self._note_usage(tenant, "rps", charge)
        if "vps" in q:
            bucket = self._bucket(tenant, "vps", q["vps"])
            if values > bucket.capacity and requests <= 1:
                # a SINGLE request the bucket can never hold: a finite
                # Retry-After would send a compliant client into an
                # infinite retry loop — answer a terminal 413 instead.
                # A COALESCED frame (requests > 1) fuses individually
                # admittable requests, so like the rps stage the charge
                # clamps at capacity below — 'split the request' would
                # be unactionable for clients that each sent 50 values.
                return EdgeReject(
                    413, "values",
                    f"request of {values} values exceeds this tenant's "
                    f"burst capacity ({bucket.capacity:g} at "
                    f"{q['vps']:g} values/s); split the request",
                )
            charge = min(max(1.0, float(values)), bucket.capacity)
            ok, retry = bucket.take(charge)
            if not ok:
                return EdgeReject(
                    429, "values",
                    f"value rate quota exhausted ({q['vps']:g} values/s)",
                    retry_after=retry,
                )
            self._note_usage(tenant, "vps", charge)
        if "cpu" in q and self.cpu_reader is not None:
            # cpu budgets are PER PROGRAM by construction: the usage
            # ledger attributes cpu_seconds to programs, so a program's
            # budget (its own quota override, or the env default) is
            # evaluated against its own measured burn — key-level cpu
            # fields are rejected at key load (billing one tenant for a
            # program all tenants share would shed the innocent one).
            # The label rides its own capped set so client-chosen
            # program names cannot eat the tenant budget.
            label = _program_state_label(
                program.partition("@")[0] if program else "default"
            )
            with self._lock:
                meter = self._cpu_meters.get(label)
                if meter is None:
                    meter = self._cpu_meters[label] = CpuMeter(
                        self.cpu_window_s
                    )
            ok, retry = meter.check(float(self.cpu_reader(label)), q["cpu"])
            if not ok:
                return EdgeReject(
                    429, "cpu",
                    f"cpu quota exhausted ({q['cpu']:g} core-seconds/s "
                    f"over {self.cpu_window_s:g}s)",
                    retry_after=retry,
                )
        return None

    # -- fleet-coherent quota state (usage gossip) --------------------------

    def _note_usage(self, label: str, field: str, n: float) -> None:
        """Record `n` admitted quota tokens for a (capped) tenant label —
        the cumulative counter usage gossip ships to sibling replicas."""
        if n <= 0:
            return
        with self._gossip_lock:
            k = (label, field)
            self._usage[k] = self._usage.get(k, 0.0) + n

    def usage_snapshot(self) -> dict[str, float]:
        """Cumulative admitted quota tokens since boot, keyed
        "tenant|field".  MONOTONE counters, not deltas: receivers apply
        per-source deltas themselves (apply_remote_usage), so a snapshot
        is idempotent — a lost or duplicated gossip round delays
        reconciliation, never double-counts it."""
        with self._gossip_lock:
            return {
                f"{t}|{f}": round(v, 3) for (t, f), v in self._usage.items()
            }

    def apply_remote_usage(self, usage: dict, source: str = "peer") -> int:
        """Reconcile remote admissions into the local buckets: drain each
        matching bucket by the DELTA of `usage` (cumulative counters from
        usage_snapshot) since the last application from `source`.

        Only EXISTING buckets are drained — gossip must not mint
        per-tenant state for names this replica never admitted (the same
        cardinality discipline as the metric labels), and a tenant with
        no local traffic has nothing to over-admit.  A counter that went
        BACKWARDS re-anchors (the source restarted; treating the reset as
        a huge negative delta would hand the tenant free quota).  Returns
        the number of buckets drained."""
        if not isinstance(usage, dict):
            raise ValueError("usage must map 'tenant|field' -> total")
        deltas: list[tuple[str, str, float]] = []
        with self._gossip_lock:
            last = self._gossip_applied.setdefault(source, {})
            for key, total in usage.items():
                try:
                    tot = float(total)
                except (TypeError, ValueError):
                    continue
                prev = last.get(key, 0.0)
                if tot > prev:
                    tenant, _, field = str(key).rpartition("|")
                    if field in ("rps", "vps"):
                        deltas.append((tenant, field, tot - prev))
                last[key] = tot
        drained = 0
        for tenant, field, delta in deltas:
            with self._lock:
                buckets = [
                    b for (t, f, _r), b in self._buckets.items()
                    if t == tenant and f == field
                ]
            for b in buckets:
                b.drain(delta)
                drained += 1
            if buckets:
                M_EDGE_GOSSIP_DRAINED.labels(field=field).inc(
                    delta * len(buckets)
                )
        return drained

    def debug_payload(self) -> dict:
        """The /healthz `edge` block: which stages are armed."""
        return {
            "auth": self.keyfile is not None,
            "keys": len(self.keyfile) if self.keyfile is not None else 0,
            "tokens": self.token_secret is not None,
            "quota": self.quota_enabled,
            "admission": self.governor is not None,
            "admission_high": self.governor.high
            if self.governor is not None else None,
        }


# --- construction -----------------------------------------------------------

_DISARMED = EdgeChain(
    keyfile=None, quota_defaults=None, governor=None,
    auth_enabled=False, quota_enabled=False, admission_enabled=False,
)

_installed: EdgeChain = _DISARMED


def install(chain: EdgeChain) -> EdgeChain:
    """Make `chain` the process's edge (the compute plane and the HTTP
    handlers read it via current())."""
    global _installed
    _installed = chain
    return chain


def reset() -> None:
    """Restore the disarmed placeholder chain (tests: an installed chain
    closes over a specific master/registry and must not outlive its
    fixture)."""
    install(_DISARMED)


def current() -> EdgeChain:
    return _installed


def keyfile_path(environ=os.environ) -> str | None:
    """MISAKA_API_KEYS, or the conventional <MISAKA_PROGRAMS_DIR>/
    api_keys.json when that file exists."""
    p = environ.get("MISAKA_API_KEYS")
    if p:
        return p
    d = environ.get("MISAKA_PROGRAMS_DIR")
    if d:
        conv = os.path.join(d, "api_keys.json")
        if os.path.exists(conv):
            return conv
    return None


def from_env(
    signals=None,
    cpu_reader=None,
    default_admission_high: int = 65536,
    environ=os.environ,
) -> EdgeChain:
    """Build the process's chain from the env surface.

    Kill switches: MISAKA_EDGE=0 disarms everything; MISAKA_EDGE_AUTH /
    MISAKA_EDGE_QUOTA / MISAKA_EDGE_ADMISSION=0 disarm one stage — the
    per-layer switches the A/B overhead gate isolates stages with.
    MISAKA_ADMISSION_HIGH sets the soft watermark in waiting VALUES
    (`default_admission_high` otherwise — the engine passes a value that
    clears the largest legal request body, so the default NEVER sheds
    what the body cap admits; tune the env down to your latency
    budget); MISAKA_QUOTA the
    env-default per-tenant quota spec; MISAKA_QUOTA_BURST_S the bucket
    burst window (2s); MISAKA_QUOTA_CPU_WINDOW_S the cpu quota's sliding
    window (60s).  In a fleet, EACH replica enforces the full quota
    locally (see the in-body note on why 1/N scaling would starve
    hash-ring-sticky tenants); the fleet parent's usage gossip
    (apply_remote_usage) reconciles the buckets so aggregate
    over-admission stays bounded by the burst window, not Nx.

    MISAKA_TOKEN_SECRET[_FILE] (falling back to the plane secret) arms
    signed short-lived tenant tokens: /edge/token mints them, every
    replica holding the secret verifies them locally."""
    if environ.get("MISAKA_EDGE", "1") == "0":
        return _DISARMED
    auth_on = environ.get("MISAKA_EDGE_AUTH", "1") != "0"
    quota_on = environ.get("MISAKA_EDGE_QUOTA", "1") != "0"
    admission_on = environ.get("MISAKA_EDGE_ADMISSION", "1") != "0"
    kf_path = keyfile_path(environ)
    keyfile = KeyFile(kf_path) if (kf_path and auth_on) else None
    quota_defaults = parse_quota_spec(environ.get("MISAKA_QUOTA"))
    governor = None
    if admission_on and signals is not None:
        governor = AdmissionGovernor(
            signals,
            int(environ.get("MISAKA_ADMISSION_HIGH", "")
                or default_admission_high),
        )
    # In a fleet, every replica enforces the FULL quota locally.  The
    # tempting 1/N scaling is wrong for program-addressed traffic, which
    # the router hash-rings to ONE replica — that tenant would be shed
    # at quota/N while the other replicas' buckets sit idle.  Full-quota
    # per replica would over-admit stateless traffic by up to Nx; the
    # fleet hub's usage gossip (apply_remote_usage) reconciles the
    # buckets so the aggregate stays bounded by the burst window.
    rate_scale = 1.0
    return EdgeChain(
        keyfile=keyfile,
        quota_defaults=quota_defaults,
        governor=governor,
        cpu_reader=cpu_reader,
        rate_scale=rate_scale,
        auth_enabled=auth_on,
        quota_enabled=quota_on,
        admission_enabled=admission_on,
        burst_s=float(environ.get("MISAKA_QUOTA_BURST_S", "") or 2.0),
        cpu_window_s=float(
            environ.get("MISAKA_QUOTA_CPU_WINDOW_S", "") or 60.0
        ),
        internal_token=environ.get("MISAKA_EDGE_INTERNAL_TOKEN") or None,
        token_secret=token_secret(environ) if auth_on else None,
    )


# --- request-key extraction -------------------------------------------------


def key_from_headers(headers) -> str | None:
    """The presented API key: X-Misaka-Key, or Authorization: Bearer.
    `headers` is any mapping with .get (email.message.Message works)."""
    k = headers.get("X-Misaka-Key")
    if k:
        return k
    auth = headers.get("Authorization")
    if auth and auth.startswith("Bearer "):
        return auth[len("Bearer "):].strip() or None
    return None


# --- TLS on the HTTP edge ---------------------------------------------------


def tls_context_from_env(environ=os.environ) -> ssl.SSLContext | None:
    """A server-side SSLContext from MISAKA_TLS_CERT/MISAKA_TLS_KEY
    (None when unset — plain HTTP, exactly as before).  Raises on a
    cert/key that fails to load: a server that silently fell back to
    plaintext after a bad rotation would be worse than one that refused
    to boot."""
    cert = environ.get("MISAKA_TLS_CERT")
    key = environ.get("MISAKA_TLS_KEY")
    if not cert and not key:
        return None
    if not cert or not key:
        raise ValueError(
            "MISAKA_TLS_CERT and MISAKA_TLS_KEY must be set together"
        )
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.minimum_version = ssl.TLSVersion.TLSv1_2
    ctx.load_cert_chain(cert, key)
    return ctx


def wrap_server_tls(httpd, context: ssl.SSLContext | None):
    """Wrap an already-bound http.server socket for TLS.  No-op when
    `context` is None.  Returns httpd for chaining.

    do_handshake_on_connect=False is load-bearing: with it on, the
    handshake runs inside accept() — the server's SINGLE accept loop —
    so one client that connects and sends nothing (a slow-loris, or any
    plaintext prober) would park the listener and outage every other
    client.  Deferred, the handshake happens on the handler THREAD's
    first read, which is exactly where a plain-HTTP idle connection
    already sits."""
    if context is not None:
        httpd.socket = context.wrap_socket(
            httpd.socket, server_side=True, do_handshake_on_connect=False
        )
        httpd.misaka_tls = True
    return httpd


def drain_or_close(handler, max_drain: int = 65536) -> None:
    """The keep-alive discipline shared by every surface that rejects a
    POST before its route body runs: a small unread body is drained (the
    connection stays synchronized), a bulk or unparseable one closes the
    connection — rejecting at the door must not buffer the flood it is
    shedding."""
    try:
        length = int(handler.headers.get("Content-Length") or 0)
    except ValueError:
        length = -1
    if 0 <= length <= max_drain:
        handler.rfile.read(length)
    else:
        handler.close_connection = True


# --- compute-plane shared secret --------------------------------------------

_PLANE_TAG = b"misaka-plane-v1"
PLANE_HANDSHAKE_LEN = 32


def plane_secret(environ=os.environ) -> bytes | None:
    """MISAKA_PLANE_SECRET (the shared secret the fleet compute plane's
    handshake uses; unset = open plane, exactly as before).  Accepts
    MISAKA_PLANE_SECRET_FILE for file-based secret distribution."""
    s = environ.get("MISAKA_PLANE_SECRET")
    if s:
        return s.encode()
    p = environ.get("MISAKA_PLANE_SECRET_FILE")
    if p:
        try:
            with open(p, "rb") as f:
                return f.read().strip() or None
        except OSError:
            log.error("edge: plane secret file %s unreadable", p)
            return None
    return None


def plane_handshake(secret: bytes) -> bytes:
    """The 32 bytes a PlaneClient writes immediately after connect()."""
    return hmac.new(secret, _PLANE_TAG, hashlib.sha256).digest()


def verify_plane_handshake(secret: bytes, presented: bytes) -> bool:
    return hmac.compare_digest(plane_handshake(secret), presented)


# --- signed tenant tokens ---------------------------------------------------
#
# Static API keys are long-lived shared secrets: revocation means a key-
# file rotation shipped to every replica.  Tenant tokens are the fleet
# credential: short-lived, HMAC-signed under one fleet-wide secret,
# minted by the admin route POST /edge/token, and verified LOCALLY at
# every replica — no key-table distribution, no verification RPC, zero
# coordination.  Wire shape:
#
#     mst1.<base64url payload>.<base64url HMAC-SHA256 sig>
#
# payload JSON: {"t": tenant, "exp": epoch-seconds, "adm": bool?,
# "p": [programs]?}.  Expiry is wall-clock (epoch) on purpose — tokens
# cross hosts, and monotonic clocks don't.

_TOKEN_TAG = b"misaka-tenant-token-v1"
TOKEN_PREFIX = "mst1."

_token_children = {
    op: M_EDGE_TOKENS.labels(op=op)
    for op in ("mint", "ok", "expired", "invalid")
}


def _token_child(op: str):
    return _token_children[op]


def token_secret(environ=os.environ) -> bytes | None:
    """The tenant-token signing secret: MISAKA_TOKEN_SECRET, or
    MISAKA_TOKEN_SECRET_FILE, falling back to the plane secret (one
    fleet-wide secret already distributed to every replica).  None
    disarms minting AND verification — a bare `mst1.` string is then
    just an unknown API key."""
    s = environ.get("MISAKA_TOKEN_SECRET")
    if s:
        return s.encode()
    p = environ.get("MISAKA_TOKEN_SECRET_FILE")
    if p:
        try:
            with open(p, "rb") as f:
                return f.read().strip() or None
        except OSError:
            log.error("edge: token secret file %s unreadable", p)
            return None
    return plane_secret(environ)


def _token_sign(secret: bytes, payload_b64: bytes) -> bytes:
    return hmac.new(
        secret, _TOKEN_TAG + b"." + payload_b64, hashlib.sha256
    ).digest()


def mint_tenant_token(
    secret: bytes,
    tenant: str,
    ttl_s: float = 300.0,
    admin: bool = False,
    programs=None,
    now: float | None = None,
) -> tuple[str, float]:
    """Mint a signed tenant token -> (token, expires_at_epoch)."""
    exp = (time.time() if now is None else now) + max(1.0, float(ttl_s))
    payload: dict = {"t": tenant, "exp": round(exp, 3)}
    if admin:
        payload["adm"] = True
    if programs:
        payload["p"] = sorted(programs)
    pb = base64.urlsafe_b64encode(
        json.dumps(payload, separators=(",", ":")).encode()
    ).rstrip(b"=")
    sig = base64.urlsafe_b64encode(_token_sign(secret, pb)).rstrip(b"=")
    _token_child("mint").inc()
    return TOKEN_PREFIX + pb.decode() + "." + sig.decode(), float(
        payload["exp"]
    )


def verify_tenant_token(
    secret: bytes, token: str, now: float | None = None
) -> tuple[dict | None, str]:
    """-> (entry, why): a synthetic key-table entry and "ok" on success;
    (None, "invalid"|"expired") otherwise.  The SIGNATURE is checked
    before the payload is parsed — unsigned bytes never reach json."""
    body = token[len(TOKEN_PREFIX):] if token.startswith(TOKEN_PREFIX) \
        else token
    pb_s, _, sig_s = body.partition(".")
    if not pb_s or not sig_s:
        return None, "invalid"
    try:
        pb = pb_s.encode("ascii")
        sig = base64.urlsafe_b64decode(
            sig_s.encode("ascii") + b"=" * (-len(sig_s) % 4)
        )
        if not hmac.compare_digest(_token_sign(secret, pb), sig):
            return None, "invalid"
        payload = json.loads(base64.urlsafe_b64decode(pb + b"=" * (-len(pb_s) % 4)))
        tenant = payload["t"]
        exp = float(payload["exp"])
        programs = payload.get("p")
        if not isinstance(tenant, str) or (
            programs is not None and not isinstance(programs, list)
        ):
            return None, "invalid"
    except (ValueError, TypeError, KeyError, UnicodeDecodeError):
        return None, "invalid"
    if (time.time() if now is None else now) >= exp:
        return None, "expired"
    return {
        "tenant": tenant,
        "admin": bool(payload.get("adm")),
        "programs": frozenset(programs) if programs is not None else None,
        "quota": None,
        "quota_spec": None,
        "disabled": False,
        "token_exp": exp,
    }, "ok"


# --- plane mTLS (TCP transport) ---------------------------------------------


class PlaneTLSReloader:
    """Hot-reloadable mTLS contexts for the TCP compute plane.

    MISAKA_PLANE_TLS_CERT/KEY/CA name this process's certificate, its
    private key, and the pinned fleet CA.  BOTH sides authenticate: the
    plane server requires a client certificate signed by the CA
    (CERT_REQUIRED), and PlaneClient verifies the server's chain against
    the same CA.  Hostnames are NOT checked — identity in this trust
    model is CA membership (any cert the fleet CA signed is a fleet
    member), not DNS names, so certs work unchanged across rehoming.

    Rotation without restart: the three files' mtime+size are stat'd at
    most every 0.5s (the api-key table's discipline); a change rebuilds
    both contexts, and NEW connections pick them up while established
    sessions keep streaming — zero dropped frames.  A rebuild that fails
    (half-written files mid-rotation) KEEPS the previous contexts and
    counts misaka_plane_tls_reloads_total{status="error"}; the stamp is
    recorded so a broken rotation is not re-parsed hot, and the next
    file change retries.
    """

    def __init__(self, cert: str, key: str, ca: str):
        self.cert, self.key, self.ca = cert, key, ca
        self._lock = threading.Lock()
        self._next_stat = 0.0
        self._stamp = self._stat()  # raises on missing files: fail loud
        # first build raises too — a plane that silently ran plaintext
        # after a bad cert would be worse than one that refused to boot
        self._server, self._client = self._make()

    def _stat(self) -> tuple:
        out = []
        for p in (self.cert, self.key, self.ca):
            st = os.stat(p)
            out.append((st.st_mtime, st.st_size))
        return tuple(out)

    def _make(self) -> tuple[ssl.SSLContext, ssl.SSLContext]:
        server = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        server.minimum_version = ssl.TLSVersion.TLSv1_2
        server.load_cert_chain(self.cert, self.key)
        server.load_verify_locations(self.ca)
        server.verify_mode = ssl.CERT_REQUIRED
        client = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        client.minimum_version = ssl.TLSVersion.TLSv1_2
        client.load_cert_chain(self.cert, self.key)
        client.load_verify_locations(self.ca)
        client.check_hostname = False  # CA-pinned, not DNS identity
        client.verify_mode = ssl.CERT_REQUIRED
        return server, client

    def _maybe_reload(self) -> None:
        now = time.monotonic()
        if now < self._next_stat:
            return
        with self._lock:
            if now < self._next_stat:
                return
            self._next_stat = now + 0.5
            try:
                stamp = self._stat()
            except OSError:
                return  # mid-rotation rename window: keep serving
            if stamp == self._stamp:
                return
            self._stamp = stamp  # don't re-parse a broken rotation hot
            try:
                server, client = self._make()
            except (OSError, ssl.SSLError, ValueError) as e:
                M_PLANE_TLS_RELOADS.labels(status="error").inc()
                log.error("edge: plane TLS reload failed (%s); keeping "
                          "the previous certificates", e)
                return
            self._server, self._client = server, client
            M_PLANE_TLS_RELOADS.labels(status="ok").inc()
            log.info("edge: plane TLS certificates reloaded from %s",
                     self.cert)

    def server_context(self) -> ssl.SSLContext:
        self._maybe_reload()
        return self._server

    def client_context(self) -> ssl.SSLContext:
        self._maybe_reload()
        return self._client


def plane_tls_from_env(environ=os.environ) -> PlaneTLSReloader | None:
    """The plane's mTLS material from MISAKA_PLANE_TLS_CERT/KEY/CA (None
    when unset — TCP planes then run plaintext + HMAC handshake, the
    single-box/bench posture; never deploy that across hosts).  Raises
    when the triple is only partially set or fails to load."""
    cert = environ.get("MISAKA_PLANE_TLS_CERT")
    key = environ.get("MISAKA_PLANE_TLS_KEY")
    ca = environ.get("MISAKA_PLANE_TLS_CA")
    if not cert and not key and not ca:
        return None
    if not (cert and key and ca):
        raise ValueError(
            "MISAKA_PLANE_TLS_CERT, MISAKA_PLANE_TLS_KEY and "
            "MISAKA_PLANE_TLS_CA must be set together"
        )
    return PlaneTLSReloader(cert, key, ca)


def count_plane_tls_reject(reason: str) -> None:
    """One refused plane connection at the mTLS gate (typed, counted
    close — the acceptance criterion's observable)."""
    M_PLANE_TLS_REJECTED.labels(
        reason=reason if reason in ("plaintext", "bad_cert") else "handshake"
    ).inc()


# --- native-edge state push -------------------------------------------------


def native_edge_state(chain: EdgeChain | None = None) -> dict:
    """Snapshot the chain's auth/quota surface for the C++ frontend tier
    (runtime/frontends.NativeFrontendSupervisor pushes it via
    msk_edge_push_state, the way specialize.py pushes compiled
    programs).

    The contract keeps the native tier a CACHE, never an authority:

    * `digests` lists every known key digest (hex of the keyed HMAC —
      raw keys never cross the boundary), INCLUDING disabled keys: a
      disabled key must reach the engine chain so the client sees the
      canonical 403 "API key disabled", not a wrong local 401.
    * burst caps ride only on keys with their OWN `vps` spec (key specs
      field-wise override program/env defaults, so the cap is exact);
      requests under env-default quotas ship to the engine, whose
      answer is byte-identical anyway.  `burst_msg_mid` pre-renders the
      %g-formatted message tail so C++ never reimplements Python float
      formatting.
    * the 401 reject bodies are shipped verbatim — one source of truth
      for client-visible strings.
    """
    if chain is None:
        chain = current()
    state: dict = {
        # keyfile is already None when auth is disabled (__init__ guards).
        # With tenant TOKENS armed the native tier must NOT pre-reject:
        # a valid token is not in the digest table (it is verified, not
        # looked up), so local 401s would reject real credentials — the
        # tier forwards everything and the engine chain decides.
        "auth_armed": chain.keyfile is not None
        and chain.token_secret is None,
        "digests": {},
        "reject_missing": (
            "API key required (X-Misaka-Key header or "
            "Authorization: Bearer <key>)"
        ),
        "reject_unknown": "unknown API key",
    }
    kf = chain.keyfile
    if kf is not None:
        kf._load()
        for digest, entry in kf._by_digest.items():
            d: dict = {"tenant": entry["tenant"]}
            spec = entry.get("quota_spec")
            if (chain.quota_enabled and not entry.get("disabled")
                    and spec and "vps" in spec):
                vps = float(spec["vps"])
                cap = max(1.0, vps * chain.rate_scale * chain.burst_s)
                d["burst_cap"] = cap
                d["burst_msg_mid"] = (
                    f" values exceeds this tenant's burst capacity "
                    f"({cap:g} at {vps:g} values/s); split the request"
                )
            state["digests"][digest.hex()] = d
    if chain.internal_token is not None:
        # the fleet's canary/loopback token: known, never quota-shed
        state["digests"][_digest(chain.internal_token).hex()] = {
            "tenant": "_fleet",
        }
    return state
