"""Hand-rolled gRPC stubs for the messenger contract.

The image ships grpcio but not grpcio-tools, so instead of generated service
stubs this module declares the method table explicitly (the message classes
ARE generated, by protoc --python_out; see Makefile `grpc` target).  The
table mirrors the reference's three services exactly
(messenger_grpc.pb.go:20-588, generated from messenger.proto:9-28).

Transport policy vs the reference:
  * The reference dials a fresh blocking TLS connection per message
    (program.go:492-565 — SURVEY.md quirk #6, its dominant cost).  Clients
    here hold ONE channel per peer and reuse it; gRPC reconnects under the
    hood.  Semantics are identical, latency is not (strictly better).
  * TLS is optional: with cert/key files configured the server takes TLS
    creds (program.go:98-101) and clients verify against the same
    self-signed cert used as root CA (program.go:52-55); without them both
    sides run insecure — the reference has no insecure mode.
"""

from __future__ import annotations

import time

import grpc
from google.protobuf import empty_pb2

from misaka_tpu.transport import messenger_pb2 as pb
from misaka_tpu.utils import faults
from misaka_tpu.utils import tracespan

# The shared retry-delay policy, re-exported for the node retry loops:
# the pre-r9 loop slept a fixed 50ms forever — a dead peer got hammered
# at 20 req/s per node per instruction, and every retrying node woke in
# lockstep (bounded-exponential + jitter fixes both; utils/backoff.py).
from misaka_tpu.utils.backoff import Backoff  # noqa: F401  (re-export)

RpcError = grpc.RpcError


class InjectedRpcError(grpc.RpcError):
    """A transport failure injected by the fault harness (utils/faults.py
    `rpc_drop`): shaped like any other grpc.RpcError so every retry and
    health-accounting path treats it exactly like a real network fault."""

    def __init__(self, method: str):
        super().__init__(f"injected rpc_drop fault on {method}")
        self.method = method




class _FaultableCallable:
    """A unary-unary callable wrapped with the rpc_delay/rpc_drop fault
    points; passthrough-cheap (two dict lookups) when nothing is armed."""

    __slots__ = ("_inner", "_method")

    def __init__(self, inner, method: str):
        self._inner = inner
        self._method = method

    def _check(self) -> None:
        if not faults.armed():  # the production path: one dict truthiness
            return
        delay = faults.fire("rpc_delay")
        if delay:
            time.sleep(delay)
        if faults.fire("rpc_drop") is not None:
            raise InjectedRpcError(self._method)

    def __call__(self, request, timeout=None):
        self._check()
        trace = tracespan.current()
        if trace is None:  # the production hot path: one contextvar read
            return self._inner(request, timeout=timeout)
        # A request trace is in scope (an HTTP broadcast fan-out, a /load):
        # the ID crosses the wire as gRPC metadata — the peer's server
        # interceptor records the receipt — and the call itself lands in
        # the trace as an rpc.<Method> span.
        t0 = time.monotonic()
        try:
            return self._inner(
                request, timeout=timeout,
                metadata=((tracespan.RPC_METADATA_KEY, trace.trace_id),),
            )
        finally:
            tracespan.add_span(
                trace, "rpc." + self._method.rsplit("/", 1)[-1],
                t0, time.monotonic() - t0, {"path": self._method},
            )

    def future(self, request):
        self._check()
        trace = tracespan.current()
        if trace is not None:
            # propagate the ID; no span — the future's completion happens
            # on a caller-owned schedule this wrapper cannot see
            return self._inner.future(
                request,
                metadata=((tracespan.RPC_METADATA_KEY, trace.trace_id),),
            )
        return self._inner.future(request)

_EMPTY = empty_pb2.Empty
_VALUE = pb.ValueMessage
_SEND = pb.SendMessage
_LOAD = pb.LoadMessage

# service name -> method name -> (request class, response class).  Method
# paths become /grpc.<Service>/<Method>: proto package "grpc" per the
# reference IDL (messenger.proto:3).
SERVICES: dict[str, dict[str, tuple[type, type]]] = {
    "Master": {
        "GetInput": (_EMPTY, _VALUE),
        "SendOutput": (_VALUE, _EMPTY),
    },
    "Program": {
        "Run": (_EMPTY, _EMPTY),
        "Pause": (_EMPTY, _EMPTY),
        "Reset": (_EMPTY, _EMPTY),
        "Load": (_LOAD, _EMPTY),
        "Send": (_SEND, _EMPTY),
    },
    "Stack": {
        "Run": (_EMPTY, _EMPTY),
        "Pause": (_EMPTY, _EMPTY),
        "Reset": (_EMPTY, _EMPTY),
        "Push": (_VALUE, _EMPTY),
        "Pop": (_EMPTY, _VALUE),
    },
}

GRPC_PORT = 8001  # the reference's fixed node port (master.go:20)


def channel_credentials(cert_file: str) -> grpc.ChannelCredentials:
    """Client TLS verifying the server's self-signed cert as root CA
    (credentials.NewClientTLSFromFile(certFile, ""), program.go:52)."""
    with open(cert_file, "rb") as f:
        return grpc.ssl_channel_credentials(root_certificates=f.read())


def server_credentials(cert_file: str, key_file: str) -> grpc.ServerCredentials:
    """Server TLS from cert/key pair (NewServerTLSFromFile, program.go:98)."""
    with open(cert_file, "rb") as f:
        cert = f.read()
    with open(key_file, "rb") as f:
        key = f.read()
    return grpc.ssl_server_credentials([(key, cert)])


def open_channel(target: str, cert_file: str | None = None) -> grpc.Channel:
    if cert_file:
        return grpc.secure_channel(target, channel_credentials(cert_file))
    return grpc.insecure_channel(target)


class _Stub:
    """Typed callables for one service over one (reused) channel."""

    _service: str

    def __init__(
        self,
        target: str,
        cert_file: str | None = None,
        channel: grpc.Channel | None = None,
    ):
        self._owned = channel is None
        self._channel = channel or open_channel(target, cert_file)
        for method, (req_cls, resp_cls) in SERVICES[self._service].items():
            path = f"/grpc.{self._service}/{method}"
            setattr(
                self,
                "_" + method,
                _FaultableCallable(
                    self._channel.unary_unary(
                        path,
                        request_serializer=req_cls.SerializeToString,
                        response_deserializer=resp_cls.FromString,
                    ),
                    path,
                ),
            )

    def ready(self, timeout: float = 1.0) -> bool:
        """Probe peer reachability: wait up to `timeout` for the channel
        to reach READY (triggers a reconnect attempt on an idle or failed
        channel).  Pure transport-level — no RPC is invoked, so probing
        has no side effects on the peer.  This is the control plane's
        peer-health primitive (runtime/nodes.py)."""
        if faults.fire("rpc_drop") is not None:
            return False
        fut = grpc.channel_ready_future(self._channel)
        try:
            fut.result(timeout=timeout)
            return True
        except (grpc.FutureTimeoutError, grpc.RpcError, ValueError):
            # cancel unsubscribes the connectivity watcher: leaving it
            # armed makes grpc's poller thread crash when the channel
            # closes later (ValueError covers exactly that closed-channel
            # race when close() wins over a probe in flight)
            fut.cancel()
            return False

    def close(self) -> None:
        if self._owned:
            self._channel.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class MasterClient(_Stub):
    """Program-node-side view of the master (GetInput/SendOutput)."""

    _service = "Master"

    def get_input(self, timeout: float | None = None) -> int:
        return self._GetInput(_EMPTY(), timeout=timeout).value

    def get_input_future(self) -> grpc.Future:
        """Cancellable in-flight GetInput (result().value when done)."""
        return self._GetInput.future(_EMPTY())

    def send_output(self, value: int, timeout: float | None = None) -> None:
        self._SendOutput(_VALUE(value=_i32(value)), timeout=timeout)


class ProgramClient(_Stub):
    _service = "Program"

    def run(self, timeout: float | None = None) -> None:
        self._Run(_EMPTY(), timeout=timeout)

    def pause(self, timeout: float | None = None) -> None:
        self._Pause(_EMPTY(), timeout=timeout)

    def reset(self, timeout: float | None = None) -> None:
        self._Reset(_EMPTY(), timeout=timeout)

    def load(self, program: str, timeout: float | None = None) -> None:
        self._Load(_LOAD(program=program), timeout=timeout)

    def send(self, value: int, register: int, timeout: float | None = None) -> None:
        """Deliver into port R<register>; blocks while the port is full
        (the reference's channel send in the handler, program.go:160-175)."""
        self._Send(_SEND(value=_i32(value), register=register), timeout=timeout)

    def send_future(self, value: int, register: int) -> grpc.Future:
        return self._Send.future(_SEND(value=_i32(value), register=register))


class StackClient(_Stub):
    _service = "Stack"

    def run(self, timeout: float | None = None) -> None:
        self._Run(_EMPTY(), timeout=timeout)

    def pause(self, timeout: float | None = None) -> None:
        self._Pause(_EMPTY(), timeout=timeout)

    def reset(self, timeout: float | None = None) -> None:
        self._Reset(_EMPTY(), timeout=timeout)

    def push(self, value: int, timeout: float | None = None) -> None:
        self._Push(_VALUE(value=_i32(value)), timeout=timeout)

    def pop(self, timeout: float | None = None) -> int:
        """Blocks until the stack is non-empty (waitPop, stack.go:133-155)."""
        return self._Pop(_EMPTY(), timeout=timeout).value

    def pop_future(self) -> grpc.Future:
        return self._Pop.future(_EMPTY())


def _i32(v: int) -> int:
    """Wrap to sint32 range like the reference's int32(v) cast (program.go:498)."""
    v &= 0xFFFFFFFF
    return v - (1 << 32) if v >= (1 << 31) else v


def make_server(
    services: dict[str, object],
    port: int,
    cert_file: str | None = None,
    key_file: str | None = None,
    max_workers: int = 32,
    host: str = "0.0.0.0",
) -> tuple[grpc.Server, int]:
    """Serve `services` ({"Program": servicer, ...}); returns (server, port).

    Servicer objects expose one method per RPC, lowercase_snake, taking
    (request, context) and returning the response message.  Handlers run on
    a thread pool, so blocking inside one (port full, stack empty) blocks
    only its RPC — the reference gets the same from goroutines.
    Pass port=0 to bind an ephemeral port (tests).
    """
    from concurrent import futures

    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        interceptors=(_TraceMetadataInterceptor(),),
    )
    for service_name, servicer in services.items():
        handlers = {}
        for method, (req_cls, resp_cls) in SERVICES[service_name].items():
            fn = getattr(servicer, _snake(method))
            handlers[method] = grpc.unary_unary_rpc_method_handler(
                fn,
                request_deserializer=req_cls.FromString,
                response_serializer=resp_cls.SerializeToString,
            )
        server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(f"grpc.{service_name}", handlers),)
        )
    address = f"{host}:{port}"
    if cert_file and key_file:
        bound = server.add_secure_port(address, server_credentials(cert_file, key_file))
    else:
        bound = server.add_insecure_port(address)
    if bound == 0:
        raise RuntimeError(f"failed to bind gRPC server on {address}")
    return server, bound


class _TraceMetadataInterceptor(grpc.ServerInterceptor):
    """Record inbound trace IDs (x-misaka-trace metadata) as rpc.recv
    tier events — the peer-side proof a request trace crossed the wire,
    surfaced in this process's /debug/perfetto.  Passthrough-cheap: one
    metadata scan per RPC, and only RPCs that carry the key record."""

    def intercept_service(self, continuation, handler_call_details):
        for key, value in handler_call_details.invocation_metadata or ():
            if key == tracespan.RPC_METADATA_KEY:
                tracespan.note_tier(
                    "rpc.recv." + handler_call_details.method.rsplit(
                        "/", 1
                    )[-1],
                    0.0,
                    attrs={
                        "trace_id": value,
                        "path": handler_call_details.method,
                    },
                )
                break
        return continuation(handler_call_details)


def _snake(name: str) -> str:
    out = []
    for i, ch in enumerate(name):
        if ch.isupper() and i:
            out.append("_")
        out.append(ch.lower())
    return "".join(out)
