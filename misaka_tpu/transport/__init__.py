"""gRPC transport for the per-process compatibility mode.

Wire-compatible with the reference's internal/grpc/ package (same proto
package, services, and messages — see messenger.proto).  The fused TPU
engine does not use RPC at all; this package exists so a misaka_tpu
deployment can span OS processes/hosts exactly like the reference's
docker-compose topology, interoperating with original Go nodes.
"""

from misaka_tpu.transport.rpc import (
    MasterClient,
    ProgramClient,
    StackClient,
    RpcError,
    channel_credentials,
    server_credentials,
    make_server,
)

__all__ = [
    "MasterClient",
    "ProgramClient",
    "StackClient",
    "RpcError",
    "channel_credentials",
    "server_credentials",
    "make_server",
]
