"""`make observatory-smoke`: the ISSUE 11 observatory proven end-to-end
against a REAL subprocess server (~30s).

Boots `python -m misaka_tpu.runtime.app` with the registry + canary +
TSDB at test cadence, drives traffic, then asserts through the public
HTTP surface:

  1. the embedded TSDB collected >= 3 intervals and GET /debug/series
     answers well-formed shapes (index catalog; a counter-as-rate query
     with [t, avg, max] points; retention stages; the documented
     bytes-per-series bound);
  2. GET /debug/dashboard serves the self-contained HTML with populated
     sparklines (baked DATA panels carrying points; zero external
     assets);
  3. the synthetic canary's misaka_canary_success series is present and
     green (full-stack probes through edge -> batcher -> engine);
  4. the regression watchdog FIRES on an injected serve_delay fault
     (armed over the production POST /debug/faults route), surfaces on
     /debug/alerts with exemplar trace IDs and flips /healthz degraded
     — then CLEARS after the fault is removed.

Exit 0 on success, 1 with a reason.  The same assertions run inside
tier-1 (tests/test_observatory.py, tests/test_tsdb.py); this is the
standalone tripwire against the real process boundary.
"""

import json
import os
import re
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def post(base, path, data=None, raw=None, timeout=60):
    body = raw if raw is not None else urllib.parse.urlencode(data or {}).encode()
    req = urllib.request.Request(base + path, data=body, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def get(base, path, timeout=30):
    try:
        with urllib.request.urlopen(base + path, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def fail(msg):
    print(f"# observatory-smoke FAILED: {msg}", file=sys.stderr)
    sys.exit(1)


def main() -> int:
    import socket

    import numpy as np

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    tmp = tempfile.mkdtemp(prefix="misaka-obs-smoke-")
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "MISAKA_PORT": str(port),
        "MISAKA_BATCH": "8",
        "MISAKA_AUTORUN": "1",
        "MISAKA_IN_CAP": "32",
        "MISAKA_OUT_CAP": "32",
        "MISAKA_STACK_CAP": "16",
        "MISAKA_PROGRAMS_DIR": os.path.join(tmp, "programs"),
        # observatory at smoke cadence (production default: 5s / 1%)
        "MISAKA_TSDB_INTERVAL_S": "0.5",
        "MISAKA_TSDB_BUDGET": "0.5",
        "MISAKA_CANARY_INTERVAL_S": "0.5",
        "MISAKA_WATCHDOG_RECENT_S": "2",
        "MISAKA_WATCHDOG": (
            "p99hot=misaka_http_request_duration_seconds:p99"
            "{route=/compute_raw}>0.05 for 1s ->page"
        ),
        "NODE_INFO": json.dumps({"main": {"type": "program"}}),
        "MISAKA_PROGRAMS": json.dumps({"main": "IN ACC\nADD 2\nOUT ACC\n"}),
    }
    proc = subprocess.Popen([sys.executable, "-m", "misaka_tpu.runtime.app"],
                            env=env)
    base = f"http://127.0.0.1:{port}"
    stop = threading.Event()
    errors = []

    def pump():
        vals = np.arange(8, dtype=np.int32)
        try:
            while not stop.is_set():
                st, out = post(base, "/compute_raw?spread=1",
                               raw=vals.astype("<i4").tobytes())
                if st != 200 or not np.array_equal(
                    np.frombuffer(out, "<i4"), vals + 2
                ):
                    raise RuntimeError(f"traffic error: {st} {out[:80]!r}")
                time.sleep(0.02)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    try:
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            try:
                if get(base, "/healthz", timeout=2)[0] == 200:
                    break
            except OSError:
                pass
            time.sleep(0.25)
        else:
            fail("server did not come up")
        t = threading.Thread(target=pump, daemon=True)
        t.start()

        # --- 1. >= 3 collected intervals + /debug/series shapes ----------
        deadline = time.monotonic() + 60
        idx = None
        while time.monotonic() < deadline:
            st, body = get(base, "/debug/series")
            if st != 200:
                fail(f"/debug/series index: {st}")
            idx = json.loads(body)
            if idx.get("samples", 0) >= 3:
                break
            time.sleep(0.5)
        else:
            fail(f"TSDB never reached 3 samples: {idx}")
        if not idx["running"] or idx["series_count"] <= 0:
            fail(f"index unhealthy: {idx}")
        if idx["bytes_per_series"] != 28 * sum(
            s["slots"] for s in idx["stages"]
        ):
            fail(f"memory bound mismatch: {idx}")
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            st, body = get(
                base, "/debug/series?name=misaka_compute_values_total"
                      "&window=5m",
            )
            q = json.loads(body)
            if st == 200 and q["series"] and q["series"][0]["points"]:
                break
            time.sleep(0.5)
        else:
            fail(f"no rate points for misaka_compute_values_total: {q}")
        row = q["series"][0]
        if row["kind"] != "rate":
            fail(f"counter not stored as rate: {row['kind']}")
        for t_, avg, mx in row["points"]:
            if not (t_ > 0 and avg >= 0 and mx >= avg):
                fail(f"malformed point: {[t_, avg, mx]}")

        # --- 2. the dashboard with populated sparklines ------------------
        st, body = get(base, "/debug/dashboard?window=5m")
        if st != 200:
            fail(f"/debug/dashboard: {st}")
        page = body.decode()
        if "misaka observatory" not in page or "<script>" not in page:
            fail("dashboard page shape")
        if re.search(r'src\s*=\s*"http', page):
            fail("dashboard references external assets")
        m = re.search(r"const DATA = (.*);\n", page)
        if not m:
            fail("no baked DATA in the dashboard")
        data = json.loads(m.group(1))
        populated = [
            p["title"] for p in data["panels"]
            if any(r["points"] for r in p["series"])
        ]
        if not populated:
            fail("no dashboard panel has points")

        # --- 3. canary series present and green --------------------------
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            st, body = get(
                base, "/debug/series?name=misaka_canary_success&window=5m"
            )
            q = json.loads(body)
            full = [
                r for r in q["series"]
                if r["labels"].get("tier") == "full" and r["points"]
            ]
            if full:
                break
            time.sleep(0.5)
        else:
            fail(f"no canary full-stack series: {q}")
        if full[0]["points"][-1][1] < 1.0:
            fail(f"canary not green: {full[0]['points'][-3:]}")
        st, body = get(base, "/healthz")
        health = json.loads(body)
        if health.get("canary", {}).get("failing_tier") is not None:
            fail(f"canary failing at boot: {health['canary']}")

        # --- 4. watchdog fires on an injected fault, then clears ---------
        st, body = post(base, "/debug/faults",
                        {"spec": "serve_delay=0.15"})
        if st != 200:
            fail(f"arming the fault: {st} {body!r}")
        deadline = time.monotonic() + 90
        wd = None
        while time.monotonic() < deadline:
            wd = json.loads(get(base, "/debug/alerts")[1])["watchdog"]
            if wd["state"] == "page":
                break
            time.sleep(0.5)
        else:
            fail(f"watchdog never fired under serve_delay: {wd}")
        fired = [r for r in wd["rules"] if r["state"] == "page"]
        if not fired or not fired[0].get("exemplars"):
            fail(f"firing rule carries no exemplars: {fired}")
        ex = fired[0]["exemplars"][0]
        st, body = get(base, ex["href"])
        if st != 200:
            fail(f"exemplar {ex['href']} not resolvable: {st}")
        health = json.loads(get(base, "/healthz")[1])
        if health.get("degraded") is not True:
            fail(f"page did not flip /healthz degraded: {health}")
        st, body = post(base, "/debug/faults", {"spec": ""})
        if st != 200:
            fail(f"clearing the fault: {st} {body!r}")
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            wd = json.loads(get(base, "/debug/alerts")[1])["watchdog"]
            health = json.loads(get(base, "/healthz")[1])
            if wd["state"] == "ok" and health.get("degraded") is not True:
                break
            time.sleep(0.5)
        else:
            fail(f"watchdog never cleared: {wd} {health}")

        if errors:
            fail(f"traffic errors: {errors[0]}")
        print(json.dumps({
            "observatory_smoke": "ok",
            "tsdb_samples": idx["samples"],
            "series_count": idx["series_count"],
            "dashboard_populated_panels": len(populated),
            "canary_last": full[0]["points"][-1][1],
            "watchdog_fired_and_cleared": True,
        }))
        return 0
    finally:
        stop.set()
        proc.terminate()
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)


if __name__ == "__main__":
    sys.exit(main())
