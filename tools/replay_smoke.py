"""`make replay-smoke`: the capture -> export -> shadow-replay loop as an
out-of-pytest tripwire (~15s, CPU-forced).

Boots a registry-armed engine server, arms the wire recorder over HTTP,
serves mixed traffic to two programs, then asserts the whole record
plane end to end:

  1. POST /captures/export writes a manifest-verified segment + anchors
  2. `python tools/replay.py <segment>` replays every program green
     (byte-for-byte) and exits 0
  3. the same segment against an ADD20 mutant renders the loud
     per-request DIVERGENCE lines and exits 1
  4. POST /programs?verify=replay admits the unchanged program and 409s
     the mutant with structured diffs (nothing swapped)
  5. --emit-model fits a bench.py --model load model from the capture

The same assertions run inside tier-1 (tests/test_capture.py); this
target drives the real subprocess tool entry points.

Exit 0 on success, 1 with a diagnostic on any failure.
"""

import json
import os
import subprocess
import sys
import tempfile
import threading

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ.setdefault("MISAKA_CAPTURE_SAMPLE", "1.0")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ADD10 = "IN ACC\nADD 10\nOUT ACC\n"
ADD20 = "IN ACC\nADD 20\nOUT ACC\n"
SMALL = dict(stack_cap=16, in_cap=16, out_cap=16)


def main() -> int:
    from misaka_tpu import networks
    from misaka_tpu.client import MisakaClient, MisakaClientError
    from misaka_tpu.runtime import capture
    from misaka_tpu.runtime.master import MasterNode, make_http_server
    from misaka_tpu.runtime.registry import ProgramRegistry

    capture.configure()
    reg = ProgramRegistry(None, batch=2, engine="scan", chunk_steps=32,
                          caps=SMALL)
    top = networks.add2(**SMALL)
    master = MasterNode(top, chunk_steps=32, batch=2, engine="scan")
    reg.seed("default", master, top)
    master.run()
    httpd = make_http_server(master, port=0, registry=reg)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    port = httpd.server_address[1]
    base = f"http://127.0.0.1:{port}"
    tmp = tempfile.mkdtemp(prefix="replay_smoke_")

    try:
        c = MisakaClient(base)
        c.upload_program("p", program=ADD10)
        cp = MisakaClient(base, program="p")
        cp.compute_batch([0])  # lease the engine before anchoring

        st = c.capture_start()
        assert st["recording"] and "p" in st["anchors"], st
        for i in range(12):
            got = list(cp.compute_batch([i, i + 1]))
            assert got == [i + 10, i + 11], got
        for i in range(4):
            c.compute_batch([i])

        # --- 1. export: manifest-verified segment + anchor checkpoints
        exp = c.capture_export(os.path.join(tmp, "wire.mskcap"))
        assert exp["records"] >= 16 and "p" in exp["anchors"], exp
        capture.verify_segment(exp["path"])
        print(f"export OK: {exp['records']} records -> {exp['path']}")

        env = {**os.environ}
        tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "replay.py")

        # --- 2. offline replay of the unchanged programs: green, rc 0
        r = subprocess.run([sys.executable, tool, exp["path"]],
                           capture_output=True, text=True, timeout=300,
                           env=env)
        assert r.returncode == 0, r.stdout + r.stderr
        assert r.stdout.count("replay green") == 2, r.stdout
        print("baseline replay OK (both programs byte-for-byte green)")

        # --- 3. mutant candidate: loud per-request diff, rc 1
        cand = os.path.join(tmp, "cand.json")
        with open(cand, "w") as f:
            json.dump({"nodes": {"main": "program"},
                       "programs": {"main": ADD20}}, f)
        model = os.path.join(tmp, "model.json")
        r = subprocess.run(
            [sys.executable, tool, exp["path"], "--program", "p",
             "--candidate", cand, "--emit-model", model],
            capture_output=True, text=True, timeout=300, env=env,
        )
        assert r.returncode == 1, r.stdout + r.stderr
        assert "DIVERGENCE" in r.stdout and "trace=" in r.stdout, r.stdout
        assert "DIVERGED on 12/12" in r.stdout, r.stdout
        print("mutant replay OK (12/12 loud divergences, exit 1)")

        # --- 4. the ?verify=replay hot-swap gate
        res = c.replay("p", program=ADD10)
        assert res["name"] == "p", res
        try:
            c.replay("p", program=ADD20)
            raise AssertionError("mutant publish must refuse")
        except MisakaClientError as e:
            assert e.status == 409 and len(e.diffs) == 12, (
                e.status, len(e.diffs))
        got = list(cp.compute_batch([5]))
        assert got == [15], f"mutant swapped in: {got}"
        print("verify=replay OK (green admitted, mutant 409 with diffs)")

        # --- 5. the capture-fitted load model
        with open(model) as f:
            fitted = json.load(f)
        assert fitted["format"] == 1 and fitted["arrival"]["rate_rps"] > 0
        assert "p" in fitted["tenants"], fitted["tenants"]
        print(f"load model OK (rate={fitted['arrival']['rate_rps']} rps, "
              f"tenants={sorted(fitted['tenants'])})")
        print("replay smoke OK")
        return 0
    finally:
        try:
            if capture.recording():
                capture.stop()
            httpd.shutdown()
            reg.close()
            master.close()
        except Exception:
            pass


if __name__ == "__main__":
    sys.exit(main())
