"""Long-running differential soak: random networks vs the hand-written
oracle across every engine, far past the suite's 40 fixed seeds.

The CI fuzz lanes prove the engines bit-identical on a fixed seed set;
this soak spends otherwise-idle machine time widening that evidence.  Runs
until --seconds elapse (or Ctrl-C), cycling random seeds through the same
compare() harness tests/test_differential.py uses (XLA dense, compact, and
fused-interpret paths all checked against the oracle).  Any mismatch is
appended to --log with its seed, which then reproduces under pytest via
`compare(seed, ...)` directly.

Usage: python tools/soak_differential.py [--seconds 3600] [--log /tmp/soak.log]
"""

from __future__ import annotations

import argparse
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# FORCE cpu — setdefault is not enough: the axon sitecustomize runs at
# interpreter start and overwrites JAX_PLATFORMS whenever
# PALLAS_AXON_POOL_IPS is set, so an inherited env pointed the first soak
# at the wedged TPU (its only "mismatch" was the backend init failing).
# The config API works post-import as long as no computation has run.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seconds", type=float, default=3600.0)
    ap.add_argument("--log", default="/tmp/soak_differential.log")
    ap.add_argument("--start-seed", type=int, default=100_000)
    args = ap.parse_args()

    from tests.test_differential import compare

    try:  # the native serve twin soaks too, where a toolchain exists
        from misaka_tpu.core import native_serve
        from tests.test_native_engine import compare_serve

        has_native = native_serve.available()
    except Exception:
        has_native = False
    from tests.test_lifecycle_fuzz import lifecycle_fuzz

    deadline = time.monotonic() + args.seconds
    seed = args.start_seed
    ran = failures = reported = 0
    t0 = time.monotonic()
    lockf = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".tpu_capture_active",
    )

    def capture_running() -> bool:
        # honor only FRESH locks (same 2h rule as chip_probe_loop.sh): a
        # SIGKILLed capture leaves the file behind, and a stale lock must
        # not turn every future soak into a silent 0-comparison no-op
        try:
            stamp = float(open(lockf).read().strip() or 0)
        except OSError:
            return False
        except ValueError:
            stamp = 0.0
        return (time.time() - stamp) < 7200

    def bench_running() -> bool:
        # the DRIVER's end-of-round `python bench.py` takes no lockfile;
        # its served/latency sections are host-bound, so a soak stealing
        # the core would depress the official artifact's numbers.  Exact
        # argv match (== "bench.py" or .../bench.py), NOT substring: a
        # `pytest tests/test_bench.py` run must not read as a bench.
        me = os.getpid()
        for pid in os.listdir("/proc"):
            if not pid.isdigit() or int(pid) == me:
                continue
            try:
                with open(f"/proc/{pid}/comm") as f:
                    if not f.read().strip().startswith("python"):
                        continue
                with open(f"/proc/{pid}/cmdline", "rb") as f:
                    argv = f.read().split(b"\0")
            except OSError:
                continue
            for a in argv:
                s = a.decode(errors="replace")
                if s == "bench.py" or s.endswith("/bench.py"):
                    return True
        return False

    while time.monotonic() < deadline:
        if capture_running():
            # a TPU evidence capture started (2h-lock protocol): yield the
            # (single) CPU for good — depressed host-side capture numbers
            # cost more than soak time
            print("# soak: yielding to TPU capture (lockfile present)", flush=True)
            break
        if bench_running():
            # benches are short-lived (minutes, no lockfile): pause and
            # resume instead of forfeiting the remaining soak budget
            print("# soak: paused while a bench runs", flush=True)
            while bench_running() and time.monotonic() < deadline:
                time.sleep(30)
            continue
        # fused-interpret recompiles per network (~10s each on one core):
        # sample it every 5th seed so dense/compact/chained coverage
        # dominates
        modes = [
            ("dense", dict(engine="dense")),
            ("compact", dict(engine="compact")),
            ("chained", dict(engine="chained")),
        ]
        if seed % 5 == 0:
            modes.append(("fused", dict(fused=True)))
        if has_native and seed % 3 == 0:
            modes.append(("serve", "serve"))  # native serve_chunk vs device
        if seed % 7 == 0:
            # the runtime state machine under random lifecycle interleavings
            eng = "native" if has_native and seed % 2 else "scan"
            modes.append((f"lifecycle-{eng}", ("lifecycle", eng)))
        for label, kw in modes:
            try:
                if kw == "serve":
                    compare_serve(seed)
                elif isinstance(kw, tuple) and kw[0] == "lifecycle":
                    lifecycle_fuzz(seed, n_ops=12, engine=kw[1])
                else:
                    compare(seed, steps=48, **kw)
            except Exception:
                failures += 1
                with open(args.log, "a") as f:
                    f.write(f"=== seed={seed} engine={label}\n")
                    f.write(traceback.format_exc() + "\n")
                print(f"MISMATCH seed={seed} engine={label}", flush=True)
            ran += 1
        seed += 1
        # ran advances 3-5 per seed (fused every 5th, serve every 3rd), so
        # an exact `% 300 == 0` milestone is usually stepped over — report
        # each 300-block once as it's crossed
        if ran // 300 != reported:
            reported = ran // 300
            rate = ran / (time.monotonic() - t0)
            print(
                f"# soak: {ran} comparisons ({seed - args.start_seed} seeds), "
                f"{failures} failures, {rate:.1f} cmp/s",
                flush=True,
            )
    print(
        f"soak done: {ran} comparisons across {seed - args.start_seed} seeds, "
        f"{failures} failures (log: {args.log})",
        flush=True,
    )
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
