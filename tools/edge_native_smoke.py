"""`make edge-native-smoke`: the C++ native edge proven end-to-end
against a REAL subprocess server (~25s).

Boots `python -m misaka_tpu.runtime.app` with the worker tier armed
(MISAKA_HTTP_WORKERS=2), API-key auth and a per-tenant quota — plaintext,
so the native epoll frontend (native/frontend.cpp) takes the PUBLIC
port and the CPython workers become its loopback proxy target — then
asserts through the public surface:

  1. engagement: /healthz carries the `native_edge` block with up=true,
     and the hot /healthz route itself is answered BY the C++ tier
     (Server: misaka-native-edge/1);
  2. an authed client round-trips /compute_raw through the native tier
     (plane-shipped, values verified); a keyless client gets the typed
     401 WITH the WWW-Authenticate challenge; an over-quota tenant gets
     the typed 413 burst rejection — both answered locally at the edge
     from pushed auth/quota state, with the engine chain's exact bodies;
  3. one inbound X-Misaka-Trace ID renders ONE unified Perfetto
     timeline spanning >= 5 tiers (http/frontend/plane/serve/native) —
     the C++ edge's spans land in the same flight-recorder plane as
     everything below it;
  4. fallback: a second boot with the edge_native_build chaos point
     (MISAKA_FAULTS) must come up serving through the CPython worker
     tier alone — no native_edge block, same compute answers.

Exit 0 on success, 1 with a reason on any failed assertion.  The same
assertions run inside tier-1 (tests/test_native_edge.py); this is the
standalone tripwire against the real process boundary.
"""

import http.client
import json
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import time
import urllib.error
import uuid

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def fail(msg):
    print(f"# edge-native-smoke FAILED: {msg}", file=sys.stderr)
    sys.exit(1)


def _req(port, method, path, body=None, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=15)
    conn.request(method, path, body=body, headers=headers or {})
    r = conn.getresponse()
    data = r.read()
    hdrs = {k.lower(): v for k, v in r.getheaders()}
    conn.close()
    return r.status, hdrs, data


def _boot_env(port, keyfile, extra=None):
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "MISAKA_PORT": str(port),
        "MISAKA_BATCH": "4",
        "MISAKA_AUTORUN": "1",
        "MISAKA_IN_CAP": "32",
        "MISAKA_OUT_CAP": "32",
        "MISAKA_STACK_CAP": "16",
        "MISAKA_HTTP_WORKERS": "2",  # plaintext workers -> the native
        "MISAKA_API_KEYS": keyfile,  # edge owns the public port
        "MISAKA_TRACE": "1",
        "NODE_INFO": json.dumps({"main": {"type": "program"}}),
        "MISAKA_PROGRAMS": json.dumps({"main": "IN ACC\nADD 2\nOUT ACC\n"}),
    }
    env.update(extra or {})
    return env


def _wait_up(client, seconds=120):
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline:
        try:
            hz = client.healthz()
            if hz.get("ok"):
                return hz
        except (urllib.error.URLError, OSError, ValueError):
            pass
        time.sleep(0.25)
    return None


def main() -> int:
    import numpy as np

    from misaka_tpu.client import MisakaClient

    tmp = tempfile.mkdtemp(prefix="misaka-edge-native-smoke-")
    keyfile = os.path.join(tmp, "api_keys.json")
    with open(keyfile, "w") as f:
        json.dump({"keys": [
            {"key": "smoke-admin", "tenant": "ops", "admin": True},
            # burst cap = 8 values: a 16-value body is a deterministic
            # locally-answered 413 regardless of bucket fill
            {"key": "smoke-tenant", "tenant": "tenant-a", "quota": "vps<4"},
        ]}, f)

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = subprocess.Popen(
        [sys.executable, "-m", "misaka_tpu.runtime.app"],
        env=_boot_env(port, keyfile),
    )
    base = f"http://127.0.0.1:{port}"
    try:
        # --- 1. the native tier engaged on the public port ---------------
        admin = MisakaClient(base, api_key="smoke-admin", timeout=10)
        hz = _wait_up(admin)
        if hz is None:
            fail("server did not come up")
        # the C++ tier answers /healthz from a pushed snapshot of the
        # engine's payload, refreshed every watcher tick — poll briefly
        # for the native_edge block to ride in
        ne = hz.get("native_edge")
        deadline = time.monotonic() + 15
        while not ne and time.monotonic() < deadline:
            time.sleep(0.3)
            ne = admin.healthz().get("native_edge")
        if not ne or not ne.get("up"):
            fail(f"native edge not engaged: healthz native_edge={ne!r}")
        s_, h_, b_ = _req(port, "GET", "/healthz")
        if s_ != 200 or h_.get("server") != "misaka-native-edge/1":
            fail(f"/healthz not answered by the C++ tier "
                 f"(Server={h_.get('server')!r})")
        print(f"# edge-native-smoke: native edge up on :{port} "
              f"({ne.get('threads')} threads)")

        # --- 2. authed / keyless / over-quota through the native tier ----
        tid = uuid.uuid4().hex
        vals = np.arange(8, dtype=np.int32)
        s_, h_, b_ = _req(port, "POST", "/compute_raw",
                          body=vals.astype("<i4").tobytes(),
                          headers={"X-Misaka-Key": "smoke-admin",
                                   "X-Misaka-Trace": tid})
        if s_ != 200:
            fail(f"authed compute_raw answered {s_}: {b_!r}")
        out = np.frombuffer(b_, dtype="<i4")
        if not np.array_equal(out, vals + 2):
            fail(f"authed compute served wrong values: {out!r}")
        s_, h_, b_ = _req(port, "POST", "/compute_raw",
                          body=vals.astype("<i4").tobytes())
        if s_ != 401 or "www-authenticate" not in h_:
            fail(f"keyless compute answered {s_} "
                 f"(WWW-Authenticate={h_.get('www-authenticate')!r})")
        if b"API key required" not in b_:
            fail(f"401 body diverged from the engine chain: {b_!r}")
        s_, h_, b_ = _req(port, "POST", "/compute_raw",
                          body=np.arange(16, dtype="<i4").tobytes(),
                          headers={"X-Misaka-Key": "smoke-tenant"})
        if s_ != 413 or b"split the request" not in b_:
            fail(f"over-quota compute answered {s_}: {b_!r}")
        print("# edge-native-smoke: authed 200 (values verified), "
              "keyless -> typed 401, over-quota -> typed 413")

        # --- 3. one trace ID, >= 5 tiers in one Perfetto timeline --------
        from misaka_tpu.utils import tracespan

        tiers = set()
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            s_, h_, b_ = _req(port, "GET", "/debug/perfetto",
                              headers={"X-Misaka-Key": "smoke-admin"})
            if s_ == 200:
                tiers = {
                    tracespan.tier_of(ev["name"])
                    for ev in json.loads(b_).get("traceEvents", ())
                    if ev.get("ph") == "X"
                    and ev.get("args", {}).get("trace_id") == tid
                }
                if len(tiers) >= 5:
                    break
            time.sleep(0.3)
        if len(tiers) < 5 or not {"frontend", "native"} <= tiers:
            fail(f"expected ONE timeline spanning >= 5 tiers incl. the "
                 f"C++ frontend under trace {tid}, got {sorted(tiers)}")
        print(f"# edge-native-smoke: one trace ID -> {len(tiers)} tiers "
              f"{sorted(tiers)}")

        # stats ride the pushed healthz snapshot (~1s refresh): poll
        ne = {}
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            ne = admin.healthz().get("native_edge") or {}
            if ne.get("plane") and ne.get("local_401") \
                    and ne.get("local_413"):
                break
            time.sleep(0.3)
        if not ne.get("plane") or not ne.get("local_401") \
                or not ne.get("local_413"):
            fail(f"native edge stats did not count the traffic: {ne!r}")
        admin.close()
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=20)
        except subprocess.TimeoutExpired:
            proc.kill()

    # --- 4. build-failure chaos point -> total worker-tier fallback ------
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port2 = s.getsockname()[1]
    proc = subprocess.Popen(
        [sys.executable, "-m", "misaka_tpu.runtime.app"],
        env=_boot_env(port2, keyfile,
                      {"MISAKA_FAULTS": "edge_native_build=1"}),
    )
    try:
        admin = MisakaClient(f"http://127.0.0.1:{port2}",
                             api_key="smoke-admin", timeout=10)
        hz = _wait_up(admin)
        if hz is None:
            fail("fallback server did not come up")
        if hz.get("native_edge") is not None:
            fail("native_edge block present despite injected build failure")
        s_, h_, b_ = _req(port2, "GET", "/healthz")
        if h_.get("server") == "misaka-native-edge/1":
            fail("C++ tier answered despite injected build failure")
        vals = np.arange(8, dtype=np.int32)
        s_, h_, b_ = _req(port2, "POST", "/compute_raw",
                          body=vals.astype("<i4").tobytes(),
                          headers={"X-Misaka-Key": "smoke-admin"})
        out = np.frombuffer(b_, dtype="<i4")
        if s_ != 200 or not np.array_equal(out, vals + 2):
            fail(f"worker-tier fallback compute answered {s_}: {b_!r}")
        admin.close()
        print("# edge-native-smoke: injected build failure -> CPython "
              "worker tier served alone (no native_edge block)")
        print("# edge-native-smoke OK")
        return 0
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=20)
        except subprocess.TimeoutExpired:
            proc.kill()
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
