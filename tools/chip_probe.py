"""One TPU-health probe: exit 0 + print "OK tpu ..." iff the relayed chip
answers a tiny jit.  Run under `timeout`; the script path carries the misaka
repo marker so a live probe holding the chip is greppable (pgrep -f).
"""
import jax

d = jax.devices()
import jax.numpy as jnp

v = jax.jit(lambda x: x * 2)(jnp.ones((8,))).sum()
print("OK", d[0].platform, float(v))
