"""Shadow-replay a captured traffic segment against a (candidate) program.

The offline half of the capture plane (runtime/capture.py): given a
``.mskcap`` segment exported by POST /captures/export, rebuild each
program's engine from its anchor checkpoint, drive the recorded request
stream through it in recorded order, and compare every response
byte-for-byte.  Unchanged semantics MUST replay green; any divergence
renders one loud line per request (trace ID, stream offset, expected vs
actual head) and the process exits non-zero — the same verdict the
in-process ``POST /programs?verify=replay`` gate computes, runnable
against any segment on any machine.

  python tools/replay.py capture.mskcap
      replay every anchored program against its own recorded topology
      (the determinism self-check: green or the engine is broken)

  python tools/replay.py capture.mskcap --candidate new.json --program default
      replay program "default"'s stream against a CANDIDATE topology
      restored from the old anchor state — the pre-deploy verdict

  python tools/replay.py capture.mskcap --emit-model load.json
      additionally fit the capture into a bench.py --model load model

Also exposed as ``python -m misaka_tpu replay`` (misaka_tpu/__main__.py).
"""

from __future__ import annotations

import json
import os
import sys

if __name__ == "__main__":  # `python tools/replay.py` — find the repo; the
    # `python -m misaka_tpu replay` path imports this module and keeps the
    # caller's platform choice
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def _load_anchor(path: str):
    from misaka_tpu.runtime.capture import load_anchor_checkpoint

    return load_anchor_checkpoint(path)


def _topology_from_meta(meta: dict):
    from misaka_tpu.runtime.topology import Topology

    return Topology(
        node_info=meta["nodes"],
        programs=meta["programs"],
        stack_cap=int(meta["stack_cap"]),
        in_cap=int(meta["in_cap"]),
        out_cap=int(meta["out_cap"]),
    )


def _engine_arg(recorded: str | None) -> str:
    """Anchors record the RESOLVED engine name (e.g. "scan-compact");
    map it back to a MasterNode constructor value."""
    if not recorded:
        return "scan"
    for base in ("fused-interpret", "fused", "scan", "gather", "native"):
        if recorded == base or recorded.startswith(base + "-"):
            return base
    return "scan"


def _resolve_anchor_path(segment: str, info: dict, label: str) -> str:
    fname = info.get("file") or f"{os.path.basename(segment)}.anchor.{label}.npz"
    return os.path.join(os.path.dirname(os.path.abspath(segment)), fname)


def replay_segment(
    segment: str,
    candidate: str | None = None,
    program: str | None = None,
    engine: str | None = None,
    limit: int | None = None,
    emit_model: str | None = None,
    out=sys.stdout,
) -> int:
    """Drive a segment; returns a process exit code (0 green, 1 diverged,
    2 unusable segment/anchor)."""
    from misaka_tpu.runtime import capture
    from misaka_tpu.runtime.master import MasterNode

    try:
        header, recs = capture.read_segment(segment, verify=True)
    except capture.CaptureError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    anchors = header.get("anchors") or {}
    labels = [program] if program else sorted(anchors)
    if program and program not in anchors:
        print(f"error: segment has no anchor for program {program!r} "
              f"(anchored: {', '.join(sorted(anchors)) or 'none'})",
              file=sys.stderr)
        return 2
    if candidate and len(labels) != 1:
        print("error: --candidate needs exactly one program "
              "(pass --program)", file=sys.stderr)
        return 2
    if not labels:
        print("error: segment carries no anchors (was the capture "
              "started while serving?)", file=sys.stderr)
        return 2

    candidate_topo = None
    if candidate:
        from misaka_tpu.__main__ import _load_topology

        candidate_topo = _load_topology(candidate)

    rc = 0
    for label in labels:
        info = anchors[label]
        lost = int(info.get("dropped_since_anchor") or 0)
        if lost:
            print(f"{label}: UNSOUND — the ring evicted {lost} records "
                  "since the anchor; a replay of this segment cannot "
                  "prove anything (raise MISAKA_CAPTURE_MB)",
                  file=sys.stderr)
            rc = max(rc, 2)
            continue
        apath = _resolve_anchor_path(segment, info, label)
        try:
            meta, state = _load_anchor(apath)
        except Exception as e:
            print(f"{label}: error: anchor {apath}: {e}", file=sys.stderr)
            rc = max(rc, 2)
            continue
        if candidate_topo is not None:
            # the candidate inherits the anchor's capacities, exactly as a
            # registry hot-swap inherits the running registry's — caps
            # shape the state arrays, so a cap change can never restore
            from misaka_tpu.runtime.topology import Topology

            topo = Topology(
                node_info=dict(candidate_topo.node_info),
                programs=dict(candidate_topo.programs),
                stack_cap=int(meta["stack_cap"]),
                in_cap=int(meta["in_cap"]),
                out_cap=int(meta["out_cap"]),
            )
        else:
            topo = _topology_from_meta(meta)
        sel = capture.replayable([r for r in recs if r["program"] == label])
        if limit is not None:
            sel = sel[-limit:]
        if not sel:
            print(f"{label}: no replayable records in segment", file=out)
            continue
        master = MasterNode(
            topo,
            batch=meta.get("batch"),
            engine=engine or _engine_arg(info.get("engine")),
        )
        try:
            try:
                master.restore(state)
            except ValueError as e:
                print(f"{label}: DIVERGENCE — candidate cannot restore "
                      f"the capture anchor: {e}", file=out)
                rc = max(rc, 1)
                continue
            master.run()
            diffs = capture.replay_records(master, sel)
        finally:
            master.close()
        if diffs:
            for d in diffs:
                print(capture.format_diff(d), file=out)
            print(f"{label}: DIVERGED on {len(diffs)}/{len(sel)} "
                  "captured requests", file=out)
            rc = max(rc, 1)
        else:
            print(f"{label}: replay green — {len(sel)} requests "
                  "byte-for-byte identical", file=out)

    if emit_model:
        from misaka_tpu.runtime import capture as _c

        try:
            model = _c.fit_load_model(recs)
        except _c.CaptureError as e:
            print(f"error: {e}", file=sys.stderr)
            return max(rc, 2)
        with open(emit_model, "w") as f:
            json.dump(model, f, indent=2)
            f.write("\n")
        print(f"load model written to {emit_model} "
              f"(rate={model['arrival']['rate_rps']} rps, "
              f"p50 n={model['values']['p50']})", file=out)
    return rc


def replay_directory(
    directory: str,
    candidate: str | None = None,
    program: str | None = None,
    engine: str | None = None,
    limit: int | None = None,
    emit_model: str | None = None,
    out=sys.stdout,
) -> int:
    """Sweep every .mskcap segment in a directory oldest-first (the
    capture spool's on-disk history) — worst per-segment verdict wins.
    ``--emit-model`` fits ONE model from the union of all swept records,
    which is the point of retained history: more of the day in the fit."""
    try:
        names = sorted(os.listdir(directory))
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    segs = [
        os.path.join(directory, n) for n in names if n.endswith(".mskcap")
    ]
    if not segs:
        print(f"error: no .mskcap segments under {directory}",
              file=sys.stderr)
        return 2
    rc = 0
    all_recs: list = []
    for seg in segs:
        print(f"== {seg}", file=out)
        rc = max(rc, replay_segment(
            seg, candidate=candidate, program=program, engine=engine,
            limit=limit, emit_model=None, out=out,
        ))
        if emit_model:
            from misaka_tpu.runtime import capture

            try:
                _, recs = capture.read_segment(seg, verify=True)
                all_recs.extend(recs)
            except capture.CaptureError:
                pass
    print(f"swept {len(segs)} segment(s): "
          f"{'green' if rc == 0 else 'NOT green'}", file=out)
    if emit_model:
        from misaka_tpu.runtime import capture

        try:
            model = capture.fit_load_model(all_recs)
        except capture.CaptureError as e:
            print(f"error: {e}", file=sys.stderr)
            return max(rc, 2)
        with open(emit_model, "w") as f:
            json.dump(model, f, indent=2)
            f.write("\n")
        print(f"load model written to {emit_model} from {len(segs)} "
              f"segment(s) (rate={model['arrival']['rate_rps']} rps)",
              file=out)
    return rc


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("segment", help=".mskcap segment from /captures/export, "
                   "or a directory of segments (the capture spool dir) to "
                   "sweep oldest-first")
    p.add_argument("--candidate", help="candidate topology (baseline name, "
                   ".json, or compose .yml) to replay against")
    p.add_argument("--program", help="replay only this program label")
    p.add_argument("--engine", help="engine override (scan/native/...)")
    p.add_argument("--limit", type=int, help="replay only the last N records")
    p.add_argument("--emit-model", metavar="OUT.json",
                   help="also fit a bench.py --model load model")
    args = p.parse_args(argv)
    fn = replay_directory if os.path.isdir(args.segment) else replay_segment
    return fn(
        args.segment, candidate=args.candidate, program=args.program,
        engine=args.engine, limit=args.limit, emit_model=args.emit_model,
    )


if __name__ == "__main__":
    sys.exit(main())
