"""`make native-trace-smoke`: boot a server WITH frontend workers, fire
traced traffic, and assert GET /debug/perfetto renders ONE unified
timeline per inbound X-Misaka-Trace ID spanning >= 5 tiers — http,
frontend, plane, serve, AND native worker-thread spans from the in-C++
flight recorder (~10s, CPU-forced).

This is the out-of-pytest tripwire for the r18 native flight recorder's
whole correlation chain: client header -> frontend worker -> plane frame
metadata -> ServeBatcher pass-trace registry -> NativeServePool call
window -> C++ per-thread event rings -> Perfetto export.  It also
asserts the raw dump (GET /debug/native_trace) carries rung-tagged unit
events with the same trace IDs attached.  The same assertions run inside
tier-1 (tests/test_native_trace.py); this target drives the real
subprocess worker boot path.

Exit 0 on success, 1 with a diagnostic on any failure.
"""

import http.client
import json
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import numpy as np

    from misaka_tpu import networks
    from misaka_tpu.runtime import frontends
    from misaka_tpu.runtime.master import MasterNode, make_http_server

    # batch >= 8 so the pool runs real SIMD group units (rung-tagged)
    master = MasterNode(
        networks.add2(), chunk_steps=64, batch=16, engine="native"
    )
    engine_httpd = make_http_server(master, port=0)
    threading.Thread(target=engine_httpd.serve_forever, daemon=True).start()
    engine_port = engine_httpd.server_address[1]
    plane_path = f"/tmp/misaka-ntrace-smoke-{os.getpid()}.sock"
    plane = frontends.start_compute_plane(master, plane_path)
    public_port = frontends.pick_free_port()
    workers = frontends.spawn_frontends(
        2, public_port, f"http://127.0.0.1:{engine_port}", plane_path
    )
    try:
        if not frontends.wait_ready(public_port):
            raise AssertionError("frontend workers did not come up")
        master.run()

        ids = [f"7718aa{i:02d}7718aa{i:02d}" for i in range(8)]
        errors = []

        def client(tid, seed):
            try:
                conn = http.client.HTTPConnection(
                    "127.0.0.1", public_port, timeout=30
                )
                rng = np.random.default_rng(seed)
                for _ in range(6):
                    vals = rng.integers(-99, 99, size=64).astype(np.int32)
                    conn.request(
                        "POST", "/compute_raw?spread=1",
                        vals.astype("<i4").tobytes(),
                        {"X-Misaka-Trace": tid},
                    )
                    resp = conn.getresponse()
                    body = resp.read()
                    assert resp.status == 200, (resp.status, body)
                    out = np.frombuffer(body, dtype="<i4")
                    assert (out == vals + 2).all()
                conn.close()
            except Exception as e:  # pragma: no cover — surfaced below
                errors.append(e)

        threads = [
            threading.Thread(target=client, args=(tid, i))
            for i, tid in enumerate(ids)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]

        def fetch(path):
            conn = http.client.HTTPConnection(
                "127.0.0.1", engine_port, timeout=15
            )
            conn.request("GET", path)
            resp = conn.getresponse()
            body = resp.read()
            conn.close()
            assert resp.status == 200, (path, resp.status)
            return json.loads(body)

        from misaka_tpu.utils import tracespan

        # the engine's recorder needs a beat: plane traces complete after
        # the response bytes are already on their way back
        tiers_by_id, native_by_id = {}, {}
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            doc = fetch("/debug/perfetto")
            events = doc["traceEvents"]
            assert isinstance(events, list) and events
            tiers_by_id, native_by_id = {}, {}
            for ev in events:
                if ev.get("ph") != "X":
                    continue
                tid = ev.get("args", {}).get("trace_id")
                if tid in ids:
                    tiers_by_id.setdefault(tid, set()).add(
                        tracespan.tier_of(ev["name"])
                    )
                    if ev["name"].startswith("native."):
                        native_by_id.setdefault(tid, set()).add(ev["name"])
            good = [
                t for t, tiers in tiers_by_id.items()
                if len(tiers) >= 5 and "native" in tiers
            ]
            if good:
                break
            time.sleep(0.2)

        best_id, best = max(
            tiers_by_id.items(), key=lambda kv: len(kv[1]),
            default=(None, set()),
        )
        assert len(best) >= 5 and "native" in best, (
            f"expected ONE unified timeline spanning >= 5 tiers incl. "
            f"native under one trace ID, best was {best_id}: {sorted(best)}"
        )
        native_spans = native_by_id.get(best_id, set())
        assert native_spans, f"no native spans under {best_id}"

        # the raw dump: rung-tagged unit events carrying trace IDs
        nt = fetch("/debug/native_trace")
        assert nt["enabled"] and nt["pools"], nt.get("pools")
        rungs, dump_ids = set(), set()
        for pool in nt["pools"]:
            assert pool["capacity"] > 0
            for ring in pool["rings"]:
                assert len(ring["events"]) <= pool["capacity"]
                for ev in ring["events"]:
                    if ev["kind"] == "unit":
                        rungs.add(ev["rung"])
                    dump_ids.update(ev.get("trace_ids", ()))
        assert rungs, "no rung-tagged unit events in /debug/native_trace"
        assert dump_ids & set(ids), (
            f"no inbound trace IDs on native events: {sorted(dump_ids)[:5]}"
        )

        print(json.dumps({
            "native_trace_smoke": "ok",
            "trace_id": best_id,
            "tiers": sorted(best),
            "native_spans": sorted(native_spans),
            "unit_rungs": sorted(rungs),
            "events_total": len(events),
        }))
        return 0
    except AssertionError as e:
        print(f"# native-trace-smoke FAILED: {e}", file=sys.stderr)
        return 1
    finally:
        for p in workers:
            p.terminate()
        master.pause()
        plane.close()
        engine_httpd.shutdown()


if __name__ == "__main__":
    sys.exit(main())
