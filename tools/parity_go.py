"""Replay the parity corpus against the ACTUAL Go reference binary.

The check SURVEY.md §4 promises but this environment cannot run (no Go
toolchain, no Docker — re-verified every round): build the reference via
its own Dockerfile (/root/reference/Dockerfile, golang:1.14-alpine) and
drive each corpus case through its real deployment — one container per
node, Docker DNS for name resolution (the reference dials peers by bare
node name on fixed ports :8000/:8001, master.go:19-20,178), values fed
through serialized POST /compute exactly like its README.

Skips cleanly (exit 0, "SKIP") when Docker or the reference checkout is
absent, so `make parity-go` is safe everywhere; the corpus itself is
committed (tests/corpus/parity/) and its engine side is re-verified in CI
by tests/test_parity_corpus.py.

Env:
  MISAKA_REFERENCE   reference checkout (default /root/reference)
  MISAKA_PARITY_TIMEOUT  per-case seconds (default 120)

Usage: python tools/parity_go.py [--local] [case ...]
  default   replay against the Go binary via Docker (SKIP if unavailable)
  --local   replay against THIS build's wire-compatible per-process gRPC
            cluster (runtime/nodes.py) over the same serialized /compute
            protocol — proves the harness end to end without Docker
  case ...  restrict to named corpus cases (default: all)
"""

from __future__ import annotations

import glob
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CORPUS = os.path.join(REPO, "tests", "corpus", "parity")
REFERENCE = os.environ.get("MISAKA_REFERENCE", "/root/reference")
TIMEOUT = float(os.environ.get("MISAKA_PARITY_TIMEOUT", "120"))


def _compose_cmd() -> list[str] | None:
    if shutil.which("docker"):
        probe = subprocess.run(
            ["docker", "compose", "version"], capture_output=True
        )
        if probe.returncode == 0:
            return ["docker", "compose"]
    if shutil.which("docker-compose"):
        return ["docker-compose"]
    return None


def _compose_file(case: dict, master_port: int) -> str:
    """One service per node, reference-style env config (docker-compose.yml)."""
    def indent(text: str, pad: str) -> str:
        return "\n".join(pad + line for line in text.splitlines())

    lines = ["services:"]
    node_info_json = json.dumps(
        {n: {"type": k} for n, k in case["node_info"].items()}
    )
    lines += [
        "  last_order:",
        "    build: " + REFERENCE,
        "    image: misaka_net_parity",
        f'    ports: ["{master_port}:8000"]',
        "    environment:",
        "      NODE_TYPE: master",
        f"      NODE_INFO: '{node_info_json}'",
        "      CERT_FILE: ./openssl/service.pem",
        "      KEY_FILE: ./openssl/service.key",
        "    command: ./app",
    ]
    for name, kind in case["node_info"].items():
        lines += [
            f"  {name}:",
            "    image: misaka_net_parity",
            "    environment:",
            f"      NODE_TYPE: {kind}",
            "      CERT_FILE: ./openssl/service.pem",
            "      KEY_FILE: ./openssl/service.key",
        ]
        if kind == "program":
            lines += [
                "      MASTER_URI: last_order",
                "      PROGRAM: |",
                indent(case["programs"][name], "        ") or "        NOP",
            ]
        lines += ["    command: ./app"]
    return "\n".join(lines) + "\n"


def _post(url: str, data: bytes, timeout: float) -> bytes:
    req = urllib.request.Request(url, data=data, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.read()


def _replay_http(base: str, case: dict, timeout: float) -> list[int]:
    """Feed the case through serialized POST /compute — the same protocol
    for the Go binary and the local wire-compatible cluster."""
    deadline = time.monotonic() + timeout
    while True:  # wait for the master's HTTP surface
        try:
            _post(base + "/run", b"", 2)
            break
        except Exception:
            if time.monotonic() > deadline:
                raise RuntimeError(f"{case['name']}: master never came up")
            time.sleep(0.5)
    outs = []
    for v in case["inputs"]:  # serialized: pairing unambiguous
        raw = _post(base + "/compute", f"value={v}".encode(), timeout)
        outs.append(int(json.loads(raw)["value"]))
    return outs


def _check(case: dict, outs: list[int], source: str) -> bool:
    want = case["engine_outputs"]
    ok = (outs == want) if case["compare"] == "stream" else (sorted(outs) == sorted(want))
    marker = "OK " if ok else "FAIL"
    print(f"{marker} {case['name']} [{case['compare']}]: {source}={outs} engine={want}")
    return ok


def run_case_local(case: dict) -> bool:
    """Replay one corpus case against OUR per-process gRPC cluster through
    its real HTTP surface — the replayer's feed/compare half exercised end
    to end in environments without Docker (the cluster speaks the
    reference's exact wire protocol, runtime/nodes.py)."""
    import threading

    from misaka_tpu.runtime.master import make_http_server
    from misaka_tpu.runtime.nodes import build_loopback_cluster

    master, close = build_loopback_cluster(case["node_info"], case["programs"])
    httpd = None
    try:
        httpd = make_http_server(master, port=0)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        outs = _replay_http(base, case, TIMEOUT)
    finally:
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        close()
    return _check(case, outs, "cluster")


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_case(compose, case: dict, master_port: int = 0) -> bool:
    # a fixed host port turns concurrent invocations (or a stale container)
    # into spurious FAILs; bind an ephemeral one per case instead
    if not master_port:
        master_port = _free_port()
    name = case["name"]
    with tempfile.TemporaryDirectory(prefix=f"parity_{name}_") as tmp:
        cf = os.path.join(tmp, "docker-compose.yml")
        with open(cf, "w") as f:
            f.write(_compose_file(case, master_port))
        up = compose + ["-f", cf, "up", "--build", "-d"]
        try:
            subprocess.run(up, check=True, capture_output=True, timeout=600)
            outs = _replay_http(f"http://127.0.0.1:{master_port}", case, TIMEOUT)
        finally:
            subprocess.run(
                compose + ["-f", cf, "down", "-t", "2"],
                capture_output=True, timeout=120,
            )
    return _check(case, outs, "go")


def main() -> int:
    args = sys.argv[1:]
    local = "--local" in args
    unknown = [a for a in args if a.startswith("--") and a != "--local"]
    if unknown:  # a typo'd flag must not silently become a green no-op run
        print(f"unknown flag(s): {unknown}\n\n{__doc__.split('Usage:')[1]}")
        return 2
    wanted = {a for a in args if not a.startswith("--")}
    if local:
        sys.path.insert(0, REPO)
        compose = None
    else:
        if not os.path.isdir(os.path.join(REFERENCE, "cmd")):
            print(f"SKIP: reference checkout not found at {REFERENCE}")
            return 0
        compose = _compose_cmd()
        if compose is None:
            print(
                "SKIP: docker / docker-compose not available in this "
                "environment (tools/parity_go.py --local replays the corpus "
                "against the wire-compatible per-process cluster instead)"
            )
            return 0
    files = sorted(glob.glob(os.path.join(CORPUS, "*.json")))
    if not files:
        print(f"no corpus at {CORPUS}; run tools/gen_parity_corpus.py first")
        return 2
    known = {os.path.splitext(os.path.basename(p))[0] for p in files}
    if wanted - known:  # a typo'd case must not become a green 0-case run
        print(f"unknown case(s): {sorted(wanted - known)}; corpus has {sorted(known)}")
        return 2
    failures = 0
    for path in files:
        with open(path) as f:
            case = json.load(f)
        if wanted and case["name"] not in wanted:
            continue
        try:
            ok = run_case_local(case) if local else run_case(compose, case)
        except Exception as e:  # infra failure: count, keep replaying
            print(f"FAIL {case['name']}: {type(e).__name__}: {e}")
            ok = False
        if not ok:
            failures += 1
    print(f"parity-go{' --local' if local else ''}: {failures} failure(s)")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
