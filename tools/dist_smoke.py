"""dist-smoke: the multi-host fleet acceptance drill, on loopback.

Two REAL `misaka_tpu.runtime.app` processes talk over TCP + mTLS —
no in-process stubs, no mocked planes:

  peer   — a standalone engine replica serving its compute plane on a
           loopback TCP address (MISAKA_PLANE_SERVE=1,
           MISAKA_PLANE_SOCKET=127.0.0.1:<port>), plane TLS armed.
  parent — a 1-local-replica fleet (MISAKA_FLEET=1) that registers the
           peer via MISAKA_FLEET_PEERS, probes it on the shared state
           machine, and fans compute frames across BOTH planes.

The drill (each step fatal on failure):

  1. both processes boot; the parent's /fleet shows the remote row up
     (peers_up == 1) and the fleet undegraded;
  2. 64 pooled clients hammer the parent's compute lane; once every
     client has served at least one request, the peer is kill -9'd
     MID-LOAD — the load loop must finish with ZERO client-visible
     errors (hedged reroute + replay-chain failover absorb the crash)
     while /fleet walks the peer to "down";
  3. the peer restarts on the same ports and is readmitted (peers_up
     back to 1) with the load still running;
  4. an authenticated remote /fleet/roll drives BOTH rows — the local
     replica (drain -> checkpoint -> replace -> restore) and the remote
     peer (drain -> checkpoint -> readmit; restored=False, the peer's
     own supervisor owns process replacement);
  5. the admin mints a short-lived tenant token at /edge/token and a
     fresh client computes with it (local HMAC verification — no
     coordination with a token service);
  6. /metrics shows the fleet series: misaka_fleet_peers_up == 1,
     gossip rounds counted ok, zero plane-TLS rejects (nothing
     plaintext ever dialed the plane).

Runs under `make dist-smoke` (wired into `make ci`).  Skips (exit 0)
when openssl is unavailable.  Every assertion failure exits 1 with a
`dist-smoke FAILED:` line on stderr.
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def fail(msg: str) -> None:
    print(f"dist-smoke FAILED: {msg}", file=sys.stderr)
    sys.exit(1)


def _pick_ports(n: int) -> list[int]:
    import socket

    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def main() -> int:  # noqa: C901 - a linear drill script
    from misaka_tpu.client import MisakaClient, MisakaClientError

    if shutil.which("openssl") is None:
        print("# dist-smoke: openssl unavailable; skipping")
        return 0

    tmp = tempfile.mkdtemp(prefix="misaka-dist-smoke-")
    cert = os.path.join(tmp, "plane.pem")
    key = os.path.join(tmp, "plane.key")
    subprocess.run(
        [
            "openssl", "req", "-x509", "-newkey", "ec",
            "-pkeyopt", "ec_paramgen_curve:prime256v1", "-nodes",
            "-keyout", key, "-out", cert, "-days", "1",
            "-subj", "/CN=misaka-fleet",
            "-addext", "subjectAltName=IP:127.0.0.1",
        ],
        check=True, capture_output=True,
    )
    keyfile = os.path.join(tmp, "api_keys.json")
    with open(keyfile, "w") as f:
        json.dump({"keys": [
            {"key": "smoke-admin", "tenant": "ops", "admin": True},
            {"key": "smoke-tenant", "tenant": "tenant-a"},
        ]}, f)

    a_port, b_port, b_plane = _pick_ports(3)
    peer_key = "dist-smoke-peer-key"
    plane_secret = "dist-smoke-plane-secret"

    common = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "MISAKA_AUTORUN": "1",
        "MISAKA_BATCH": "4",
        "MISAKA_IN_CAP": "32",
        "MISAKA_OUT_CAP": "32",
        "MISAKA_STACK_CAP": "16",
        "MISAKA_TTL_S": "600",
        "NODE_INFO": json.dumps({"main": {"type": "program"}}),
        "MISAKA_PROGRAMS": json.dumps({"main": "IN ACC\nADD 2\nOUT ACC\n"}),
        # the plane trust plane: CA-pinned mTLS around the PR 9 HMAC
        # handshake (both required; plaintext dials are refused)
        "MISAKA_PLANE_TLS_CERT": cert,
        "MISAKA_PLANE_TLS_KEY": key,
        "MISAKA_PLANE_TLS_CA": cert,
        "MISAKA_PLANE_SECRET": plane_secret,
        "MISAKA_API_KEYS": keyfile,
        "MISAKA_TOKEN_SECRET": "dist-smoke-token-secret",
    }
    common.pop("MISAKA_TLS_CERT", None)
    common.pop("MISAKA_TLS_KEY", None)
    peer_env = {
        **common,
        # the same shape FleetManager._replica_env spawns, but on a
        # loopback TCP plane — a stand-in for a replica on another host
        "MISAKA_FLEET": "0",
        "MISAKA_HTTP_WORKERS": "0",
        "MISAKA_PORT": str(b_port),
        "MISAKA_PLANE_SOCKET": f"127.0.0.1:{b_plane}",
        "MISAKA_PLANE_SERVE": "1",
        "MISAKA_FLEET_REPLICA": "1",
        "MISAKA_CHECKPOINT_DIR": os.path.join(tmp, "peer-ckpt"),
        "MISAKA_EDGE_INTERNAL_TOKEN": peer_key,
    }
    parent_env = {
        **common,
        "MISAKA_FLEET": "1",
        "MISAKA_HTTP_WORKERS": "2",
        "MISAKA_PORT": str(a_port),
        "MISAKA_FLEET_DIR": os.path.join(tmp, "fleet"),
        "MISAKA_FLEET_PEERS": f"127.0.0.1:{b_port}:{b_plane}",
        "MISAKA_FLEET_PEER_KEY": peer_key,
        "MISAKA_GOSSIP_S": "0.25",
    }

    def spawn_peer() -> subprocess.Popen:
        return subprocess.Popen(
            [sys.executable, "-m", "misaka_tpu.runtime.app"], env=peer_env
        )

    procs: list[subprocess.Popen] = []
    base = f"http://127.0.0.1:{a_port}"
    try:
        print("# dist-smoke: booting remote peer "
              f"(plane tcp 127.0.0.1:{b_plane}, mTLS)")
        peer = spawn_peer()
        procs.append(peer)
        print("# dist-smoke: booting fleet parent "
              f"(MISAKA_FLEET_PEERS=127.0.0.1:{b_port}:{b_plane})")
        parent = subprocess.Popen(
            [sys.executable, "-m", "misaka_tpu.runtime.app"], env=parent_env
        )
        procs.append(parent)

        admin = MisakaClient(base, api_key="smoke-admin", timeout=30)

        def wait_fleet(pred, what: str, timeout_s: float = 180.0) -> dict:
            deadline = time.monotonic() + timeout_s
            last: dict = {}
            while time.monotonic() < deadline:
                if parent.poll() is not None:
                    fail(f"fleet parent died while waiting for {what}")
                try:
                    last = admin.fleet_status()
                    if pred(last):
                        return last
                except (MisakaClientError, urllib.error.URLError, OSError):
                    pass
                time.sleep(0.25)
            fail(f"timed out waiting for {what}; last /fleet: {last}")
            raise AssertionError  # unreachable

        st = wait_fleet(
            lambda s: s.get("peers_up") == 1 and not s.get("degraded"),
            "remote peer up + fleet undegraded",
        )
        remote_rows = [r for r in st["replicas"] if r.get("remote")]
        if len(remote_rows) != 1 or remote_rows[0]["state"] != "up":
            fail(f"expected one up remote row, got {remote_rows}")
        print("# dist-smoke: fleet healthy — 1 local replica + 1 remote "
              "peer over TCP+mTLS")

        # --- pooled load: 64 clients through a kill -9 ------------------
        stop = threading.Event()
        counts = [0] * 64
        errors: list[str] = []

        def hammer(i: int) -> None:
            cl = MisakaClient(base, api_key="smoke-tenant", timeout=60)
            vals = [i, i + 1, i + 2]
            want = [v + 2 for v in vals]
            while not stop.is_set():
                try:
                    out = cl.compute_raw(vals)
                    if list(out) != want:
                        errors.append(f"client {i}: wrong result {out}")
                        return
                    counts[i] += 1
                except Exception as exc:  # noqa: BLE001 - the assertion
                    errors.append(f"client {i}: {type(exc).__name__}: {exc}")
                    return

        threads = [
            threading.Thread(target=hammer, args=(i,), daemon=True)
            for i in range(64)
        ]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and (
            min(counts) < 1 or errors
        ):
            time.sleep(0.1)
        if errors:
            fail(f"client errors before the kill: {errors[:3]}")
        if min(counts) < 1:
            fail("load never warmed: some client served zero requests")
        print(f"# dist-smoke: 64 clients warm ({sum(counts)} requests); "
              "kill -9 the remote peer mid-load")

        os.kill(peer.pid, signal.SIGKILL)
        peer.wait(timeout=30)
        wait_fleet(
            lambda s: any(
                r.get("remote") and r["state"] == "down"
                for r in s["replicas"]
            ),
            "remote peer marked down",
        )
        # keep hammering through the failover window, then check errors
        settle = time.monotonic() + 5
        while time.monotonic() < settle:
            if errors:
                break
            time.sleep(0.1)
        if errors:
            fail(f"client-visible errors across the kill -9: {errors[:3]}")
        print("# dist-smoke: peer down, zero client errors — failover "
              "held (hedge + replay chain)")

        # --- restart the peer on the same ports: readmission ------------
        peer = spawn_peer()
        procs.append(peer)
        wait_fleet(
            lambda s: s.get("peers_up") == 1 and not s.get("degraded"),
            "restarted peer readmitted",
        )
        if errors:
            fail(f"client errors during readmission: {errors[:3]}")
        print("# dist-smoke: restarted peer readmitted (peers_up=1)")

        # --- authenticated remote /fleet/roll ---------------------------
        report = admin.fleet_roll(timeout=600)
        if not report.get("ok"):
            fail(f"/fleet/roll not ok: {report}")
        remote_entries = [
            e for e in report.get("replicas", []) if e.get("remote")
        ]
        if len(remote_entries) != 1:
            fail(f"roll report missing the remote entry: {report}")
        ent = remote_entries[0]
        if ent.get("restored") is not False or not str(
            ent.get("checkpoint", "")
        ).startswith("fleet-roll-"):
            fail(f"remote roll entry wrong shape: {ent}")
        print("# dist-smoke: remote /fleet/roll ok — drain -> checkpoint "
              f"{ent['checkpoint']!r} -> readmit")

        stop.set()
        for t in threads:
            t.join(timeout=30)
        if errors:
            fail(f"client errors at drain: {errors[:3]}")
        total = sum(counts)
        if total < 64:
            fail(f"implausibly little load served: {total}")
        print(f"# dist-smoke: load done — {total} requests, zero errors")

        # --- fleet tokens: mint at the edge, verify locally -------------
        minted = json.loads(admin._post_form(
            "/edge/token", tenant="roaming", ttl="120"
        ))
        token = minted.get("token", "")
        if not token.startswith("mst1."):
            fail(f"/edge/token minted no token: {minted}")
        roamer = MisakaClient(base, api_key=token, timeout=30)
        out = roamer.compute_raw([40])
        if list(out) != [42]:
            fail(f"token-authenticated compute wrong: {out}")
        print("# dist-smoke: minted tenant token accepted on the "
              "compute lane (local HMAC verification)")

        # --- the metric surface -----------------------------------------
        text = admin.metrics()
        if "misaka_fleet_peers_up 1" not in text.replace(".0", ""):
            fail("misaka_fleet_peers_up != 1 in /metrics")
        if 'misaka_fleet_gossip_total{status="ok"}' not in text:
            fail("no ok gossip rounds counted in /metrics")
        for line in text.splitlines():
            if line.startswith("misaka_plane_tls_rejected_total") and \
                    not line.rstrip().endswith(" 0"):
                fail(f"unexpected plane TLS reject: {line}")
        print("# dist-smoke: metrics surface ok (peers_up=1, gossip "
              "counted, zero plane-TLS rejects)")
        print("# dist-smoke OK")
        return 0
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=20)
            except subprocess.TimeoutExpired:
                p.kill()
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
