"""`make edge-smoke`: the production edge proven end-to-end against a
REAL subprocess server (~15s).

Boots `python -m misaka_tpu.runtime.app` with TLS (a throwaway
self-signed cert), API-key auth (reloadable key file), a per-tenant
quota, and the SO_REUSEPORT frontend tier — the full production-edge
topology — then asserts through the PUBLIC https:// surface:

  1. the TLS handshake: a CA-pinned client round-trips; a client that
     does not trust the cert is refused; plain HTTP against the TLS
     port fails;
  2. bad key -> typed 401 (with the WWW-Authenticate challenge) and a
     non-admin key on a lifecycle route -> 403;
  3. quota exhaustion -> typed 429 WITH Retry-After, on the hot
     compute-plane path (the frame-level edge decision made engine-side
     and restored by the worker);
  4. recovery: after backing off for the advertised Retry-After, the
     same tenant serves again — and an admin-keyed /metrics scrape shows
     the tenant-labeled misaka_edge_{admitted,rejected}_total series.

Exit 0 on success, 1 with a reason on any failed assertion.  The same
assertions run inside tier-1 (tests/test_edge.py); this is the
standalone tripwire against the real process + TLS boundary.
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
import urllib.error

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def fail(msg):
    print(f"# edge-smoke FAILED: {msg}", file=sys.stderr)
    sys.exit(1)


def main() -> int:
    import socket

    import numpy as np

    from misaka_tpu.client import MisakaClient, MisakaClientError

    if shutil.which("openssl") is None:
        print("# edge-smoke: openssl unavailable; skipping")
        return 0

    tmp = tempfile.mkdtemp(prefix="misaka-edge-smoke-")
    cert = os.path.join(tmp, "service.pem")
    key = os.path.join(tmp, "service.key")
    subprocess.run(
        [
            "openssl", "req", "-x509", "-newkey", "ec",
            "-pkeyopt", "ec_paramgen_curve:prime256v1", "-nodes",
            "-keyout", key, "-out", cert, "-days", "1",
            "-subj", "/CN=localhost",
            "-addext", "subjectAltName=DNS:localhost,IP:127.0.0.1",
        ],
        check=True, capture_output=True,
    )
    keyfile = os.path.join(tmp, "api_keys.json")
    with open(keyfile, "w") as f:
        json.dump({"keys": [
            {"key": "smoke-admin", "tenant": "ops", "admin": True},
            {"key": "smoke-tenant", "tenant": "tenant-a",
             "quota": "rps<3"},
        ]}, f)

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "MISAKA_PORT": str(port),
        "MISAKA_BATCH": "4",
        "MISAKA_AUTORUN": "1",
        "MISAKA_IN_CAP": "32",
        "MISAKA_OUT_CAP": "32",
        "MISAKA_STACK_CAP": "16",
        "MISAKA_HTTP_WORKERS": "2",  # workers terminate TLS; the edge
        "MISAKA_TLS_CERT": cert,     # decision rides the compute plane
        "MISAKA_TLS_KEY": key,
        "MISAKA_API_KEYS": keyfile,
        "NODE_INFO": json.dumps({"main": {"type": "program"}}),
        "MISAKA_PROGRAMS": json.dumps({"main": "IN ACC\nADD 2\nOUT ACC\n"}),
    }
    proc = subprocess.Popen(
        [sys.executable, "-m", "misaka_tpu.runtime.app"], env=env
    )
    base = f"https://127.0.0.1:{port}"
    try:
        # --- 1. TLS handshake --------------------------------------------
        admin = MisakaClient(base, ca=cert, api_key="smoke-admin",
                             timeout=10)
        deadline = time.monotonic() + 120
        up = False
        while time.monotonic() < deadline:
            try:
                if admin.healthz().get("ok"):
                    up = True
                    break
            except (urllib.error.URLError, OSError):
                pass
            time.sleep(0.25)
        if not up:
            fail("server did not come up over TLS")
        print("# edge-smoke: TLS handshake ok (CA-pinned client)")
        untrusted = MisakaClient(base, timeout=5)
        try:
            untrusted.healthz()
            fail("untrusted client was not refused")
        except urllib.error.URLError:
            pass
        untrusted.close()
        plain = MisakaClient(f"http://127.0.0.1:{port}", timeout=5,
                             connect_retries=0, retry_stale=False)
        try:
            plain.healthz()
            fail("plain HTTP against the TLS port succeeded")
        except urllib.error.URLError:
            pass
        plain.close()
        print("# edge-smoke: untrusted + plaintext clients refused")

        # --- 2. auth typing ----------------------------------------------
        bad = MisakaClient(base, ca=cert, api_key="wrong-key", timeout=10)
        try:
            bad.compute(1)
            fail("bad key was admitted")
        except MisakaClientError as e:
            if e.status != 401:
                fail(f"bad key answered {e.status}, wanted 401")
        bad.close()
        tenant = MisakaClient(base, ca=cert, api_key="smoke-tenant",
                              timeout=10)
        try:
            tenant.pause()
            fail("non-admin key drove a lifecycle route")
        except MisakaClientError as e:
            if e.status != 403:
                fail(f"non-admin pause answered {e.status}, wanted 403")
        print("# edge-smoke: bad key -> 401, non-admin lifecycle -> 403")

        # --- 3. quota exhaustion -> 429 + Retry-After --------------------
        vals = np.arange(16, dtype=np.int32)
        retry_after = None
        served = 0
        for _ in range(12):
            try:
                out = tenant.compute_raw(vals)
                if not np.array_equal(np.asarray(out), vals + 2):
                    fail("served values wrong")
                served += 1
            except MisakaClientError as e:
                if e.status != 429:
                    fail(f"quota rejection was {e.status}, wanted 429")
                if e.retry_after is None:
                    fail("429 carried no Retry-After")
                retry_after = e.retry_after
                break
        if retry_after is None:
            fail(f"no 429 after {served} requests against rps<3")
        print(f"# edge-smoke: quota exhausted after {served} requests -> "
              f"429 Retry-After={retry_after:g}s")

        # --- 4. recovery after the advertised backoff --------------------
        time.sleep(min(retry_after, 10.0) + 0.5)
        out = tenant.compute_raw(vals)
        if not np.array_equal(np.asarray(out), vals + 2):
            fail("post-backoff request served wrong values")
        tenant.close()
        text = admin.metrics()
        for needle in (
            'misaka_edge_rejected_total{reason="rate",tenant="tenant-a"}',
            "misaka_edge_admitted_total",
        ):
            if needle not in text:
                fail(f"metrics missing {needle!r}")
        admin.close()
        print("# edge-smoke: tenant recovered after backoff; edge metrics "
              "labeled")
        print("# edge-smoke OK")
        return 0
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=20)
        except subprocess.TimeoutExpired:
            proc.kill()
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
