"""`make registry-smoke`: the program registry proven end-to-end (~15s).

Boots the REAL server as a subprocess (python -m misaka_tpu.runtime.app)
with MISAKA_PROGRAMS_DIR armed, then drives the whole multi-tenant story
through the public HTTP surface:

  1. upload two programs (POST /programs) and serve BOTH concurrently
     from per-program engines, parity-checked;
  2. hot-swap one of them by publishing a new version under concurrent
     traffic — zero client-visible errors, responses flip old -> new;
  3. assert GET /metrics carries `program`-labeled registry series for
     both tenants, and GET /debug/requests/<id> shows the serve.pass
     span carrying the program attr — the observability contract.

Exit 0 on success, 1 with a reason on any failed assertion.  The same
assertions run inside tier-1 (tests/test_registry.py); this is the
standalone tripwire against the real process boundary.
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

ADD5 = "IN ACC\nADD 5\nOUT ACC\n"
ADD7 = "IN ACC\nADD 7\nOUT ACC\n"
ADD9 = "IN ACC\nADD 9\nOUT ACC\n"


def post(base, path, data=None, headers=None, raw=None, timeout=60):
    body = raw if raw is not None else urllib.parse.urlencode(data or {}).encode()
    req = urllib.request.Request(
        base + path, data=body, method="POST", headers=headers or {}
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def get(base, path, timeout=30):
    try:
        with urllib.request.urlopen(base + path, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def wait_ready(base, deadline_s=120):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        try:
            status, _ = get(base, "/healthz", timeout=2)
            if status == 200:
                return True
        except OSError:
            pass
        time.sleep(0.25)
    return False


def fail(msg):
    print(f"# registry-smoke FAILED: {msg}", file=sys.stderr)
    sys.exit(1)


def main() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    tmp = tempfile.mkdtemp(prefix="misaka-registry-smoke-")
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "MISAKA_PORT": str(port),
        "MISAKA_BATCH": "4",
        "MISAKA_ENGINE": "scan",
        "MISAKA_AUTORUN": "1",
        "MISAKA_IN_CAP": "16",
        "MISAKA_OUT_CAP": "16",
        "MISAKA_STACK_CAP": "16",
        "MISAKA_PROGRAMS_DIR": os.path.join(tmp, "programs"),
        "NODE_INFO": json.dumps({
            "misaka1": {"type": "program"}, "misaka2": {"type": "program"},
            "misaka3": {"type": "stack"},
        }),
        "MISAKA_PROGRAMS": json.dumps({
            "misaka1": "IN ACC\nADD 1\nMOV ACC, misaka2:R0\n"
                       "MOV R0, ACC\nOUT ACC\n",
            "misaka2": "MOV R0, ACC\nADD 1\nPUSH ACC, misaka3\n"
                       "POP misaka3, ACC\nMOV ACC, misaka1:R0\n",
        }),
    }
    proc = subprocess.Popen([sys.executable, "-m", "misaka_tpu.runtime.app"],
                            env=env)
    base = f"http://127.0.0.1:{port}"
    try:
        if not wait_ready(base):
            fail("server did not come up")

        # --- 1. upload two programs, serve both concurrently ------------
        status, body = post(base, "/programs", {"name": "alpha",
                                                "program": ADD5})
        if status != 200:
            fail(f"upload alpha: {status} {body!r}")
        status, body = post(base, "/programs", {"name": "beta",
                                                "program": ADD7})
        if status != 200:
            fail(f"upload beta: {status} {body!r}")

        errors = []

        def hammer(name, delta, n=30, trace_prefix=None):
            for k in range(n):
                headers = {}
                if trace_prefix:
                    headers["X-Misaka-Trace"] = f"{trace_prefix}{k:04d}"
                st, out = post(base, f"/programs/{name}/compute",
                               {"value": str(k)}, headers=headers)
                if st != 200 or json.loads(out)["value"] != k + delta:
                    errors.append((name, k, st, out))
                    return

        ts = [
            threading.Thread(target=hammer, args=("alpha", 5, 30, "regsmka")),
            threading.Thread(target=hammer, args=("beta", 7, 30, "regsmkb")),
            # legacy routes keep serving the seeded default (+2) alongside
            threading.Thread(target=lambda: [
                errors.append(("default", v, st, out))
                for v in range(10)
                for st, out in [post(base, "/compute", {"value": str(v)})]
                if st != 200 or json.loads(out)["value"] != v + 2
            ]),
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        if errors:
            fail(f"concurrent serving errors: {errors[:3]}")
        print("# registry-smoke: two programs + the default served "
              "concurrently, parity-checked", file=sys.stderr)

        # --- 2. hot-swap beta under concurrent traffic ------------------
        swap_errors = []
        seen = {"old": 0, "new": 0}
        stop = threading.Event()

        def swap_traffic():
            k = 0
            while not stop.is_set():
                st, out = post(base, "/programs/beta/compute",
                               {"value": str(k)})
                if st != 200:
                    swap_errors.append((k, st, out))
                    return
                got = json.loads(out)["value"]
                if got == k + 7:
                    seen["old"] += 1
                elif got == k + 9:
                    seen["new"] += 1
                else:
                    swap_errors.append((k, st, out))
                    return
                k += 1

        hammers = [threading.Thread(target=swap_traffic) for _ in range(4)]
        for t in hammers:
            t.start()
        time.sleep(0.3)
        status, body = post(base, "/programs", {"name": "beta",
                                                "program": ADD9})
        if status != 200 or not json.loads(body)["swapped"]:
            stop.set()
            fail(f"hot-swap publish: {status} {body!r}")
        time.sleep(0.5)
        stop.set()
        for t in hammers:
            t.join()
        if swap_errors:
            fail(f"hot-swap client-visible errors: {swap_errors[:3]}")
        if not seen["new"]:
            fail("no post-swap responses observed")
        print(f"# registry-smoke: hot-swap under traffic, zero errors "
              f"(old={seen['old']} new={seen['new']} responses)",
              file=sys.stderr)

        # --- 3. observability: program labels + trace attr --------------
        status, body = get(base, "/metrics")
        text = body.decode()
        for want in (
            'misaka_program_requests_total{program="alpha"}',
            'misaka_program_requests_total{program="beta"}',
            'misaka_program_values_total{program="alpha"}',
            "misaka_program_swaps_total",
            "misaka_program_active_engines",
        ):
            if want not in text:
                fail(f"/metrics missing {want}")
        # a FRESH traced request (the earlier hammer traces may have been
        # evicted from the bounded flight-recorder ring by swap traffic)
        status, body = post(base, "/programs/alpha/compute",
                            {"value": "1"},
                            headers={"X-Misaka-Trace": "regsmk-final-1"})
        if status != 200:
            fail(f"traced request: {status} {body!r}")
        status, body = get(base, "/debug/requests/regsmk-final-1")
        if status != 200:
            fail(f"trace lookup: {status} {body!r}")
        tree = json.loads(body)
        passes = [s for s in tree["spans"] if s["name"] == "serve.pass"]
        if not passes or passes[0].get("attrs", {}).get("program") != "alpha":
            fail(f"serve.pass span lacks the program attr: {passes}")
        status, body = get(base, "/programs")
        listing = json.loads(body)
        if not {"alpha", "beta", "default"} <= set(listing["programs"]):
            fail(f"listing incomplete: {sorted(listing['programs'])}")
        print("# registry-smoke: /metrics program labels + serve.pass "
              "program attr + /programs listing all present",
              file=sys.stderr)
        print(json.dumps({
            "metric": "registry_smoke", "ok": True,
            "programs": sorted(listing["programs"]),
            "swap_responses": seen,
        }))
        return 0
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
