"""`make trace-smoke`: boot a server WITH frontend workers, fire
concurrent traced traffic, fetch GET /debug/perfetto from the engine,
and assert spans from >= 3 tiers appear under one trace ID (~10s,
CPU-forced).

This is the out-of-pytest tripwire for the whole propagation chain:
client header -> SO_REUSEPORT frontend worker process (http.parse,
frontend.coalesce) -> unix-socket plane frame metadata -> engine
(plane.recv, serve.queue, serve.pass) -> flight recorder -> Perfetto
export.  The same assertions run inside tier-1
(tests/test_request_trace.py); this target drives the real subprocess
worker boot path.

Exit 0 on success, 1 with a diagnostic on any failure.
"""

import http.client
import json
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import numpy as np

    from misaka_tpu import networks
    from misaka_tpu.runtime import frontends
    from misaka_tpu.runtime.master import MasterNode, make_http_server

    master = MasterNode(networks.add2(), chunk_steps=64, batch=8)
    engine_httpd = make_http_server(master, port=0)
    threading.Thread(target=engine_httpd.serve_forever, daemon=True).start()
    engine_port = engine_httpd.server_address[1]
    plane_path = f"/tmp/misaka-trace-smoke-{os.getpid()}.sock"
    plane = frontends.start_compute_plane(master, plane_path)
    public_port = frontends.pick_free_port()
    workers = frontends.spawn_frontends(
        2, public_port, f"http://127.0.0.1:{engine_port}", plane_path
    )
    try:
        if not frontends.wait_ready(public_port):
            raise AssertionError("frontend workers did not come up")
        master.run()

        ids = [f"5110ce{i:02d}5110ce{i:02d}" for i in range(8)]
        errors = []

        def client(tid, seed):
            try:
                conn = http.client.HTTPConnection(
                    "127.0.0.1", public_port, timeout=30
                )
                rng = np.random.default_rng(seed)
                for _ in range(4):
                    vals = rng.integers(-99, 99, size=64).astype(np.int32)
                    conn.request(
                        "POST", "/compute_raw?spread=1",
                        vals.astype("<i4").tobytes(),
                        {"X-Misaka-Trace": tid},
                    )
                    resp = conn.getresponse()
                    body = resp.read()
                    assert resp.status == 200, (resp.status, body)
                    assert resp.getheader("X-Misaka-Trace") == tid
                    out = np.frombuffer(body, dtype="<i4")
                    assert (out == vals + 2).all()
                conn.close()
            except Exception as e:  # pragma: no cover — surfaced below
                errors.append(e)

        threads = [
            threading.Thread(target=client, args=(tid, i))
            for i, tid in enumerate(ids)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]

        # the engine's recorder needs a beat: plane traces complete after
        # the response bytes are already on their way back
        def fetch_perfetto():
            conn = http.client.HTTPConnection(
                "127.0.0.1", engine_port, timeout=15
            )
            conn.request("GET", "/debug/perfetto")
            resp = conn.getresponse()
            body = resp.read()
            conn.close()
            assert resp.status == 200, resp.status
            return json.loads(body)  # must parse as trace-event JSON

        from misaka_tpu.utils import tracespan

        tiers_by_id = {}
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            doc = fetch_perfetto()
            events = doc["traceEvents"]
            assert isinstance(events, list) and events
            tiers_by_id = {}
            for ev in events:
                if ev.get("ph") != "X":
                    continue
                tid = ev.get("args", {}).get("trace_id")
                if tid in ids:
                    tiers_by_id.setdefault(tid, set()).add(
                        tracespan.tier_of(ev["name"])
                    )
            if tiers_by_id and max(len(v) for v in tiers_by_id.values()) >= 3:
                break
            time.sleep(0.2)

        best_id, best = max(
            tiers_by_id.items(), key=lambda kv: len(kv[1]),
            default=(None, set()),
        )
        assert len(best) >= 3, (
            f"expected spans from >= 3 tiers under one trace ID, best was "
            f"{best_id}: {sorted(best)}"
        )
        span_names = {
            ev["name"] for ev in events
            if ev.get("ph") == "X"
            and ev.get("args", {}).get("trace_id") == best_id
        }
        assert {"serve.queue", "serve.pass"} <= span_names, span_names

        print(json.dumps({
            "trace_smoke": "ok",
            "trace_id": best_id,
            "tiers": sorted(best),
            "spans": sorted(span_names),
            "events_total": len(events),
        }))
        return 0
    except AssertionError as e:
        print(f"# trace-smoke FAILED: {e}", file=sys.stderr)
        return 1
    finally:
        for p in workers:
            p.terminate()
        master.pause()
        plane.close()
        engine_httpd.shutdown()


if __name__ == "__main__":
    sys.exit(main())
