"""`make metrics-smoke`: boot a server, fire traffic, assert the metrics
plane works end to end (~10s, CPU-forced).

Checks, in order:
  1. GET /healthz answers without the network even running (cheap liveness).
  2. GET /metrics parses as Prometheus text exposition v0.0.4 — EVERY line,
     through utils/metrics.parse_text (the strict parser the tests use).
  3. After concurrent /compute + /compute_batch + /compute_raw traffic, the
     key series MOVED: http route counters, route latency histogram counts,
     compute values, device-loop ticks and chunk observations.
  4. Histogram invariants on the live exposition: cumulative buckets
     monotone, +Inf bucket == _count.

Exit 0 on success, 1 with a diagnostic on any failure.  The same
assertions run inside tier-1 (tests/test_metrics.py); this target is the
out-of-pytest tripwire an operator or CI step can run against the real
boot path.
"""

import json
import os
import sys
import threading
import urllib.parse
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import numpy as np

    from misaka_tpu import networks
    from misaka_tpu.runtime.master import MasterNode, make_http_server
    from misaka_tpu.utils import metrics

    master = MasterNode(networks.add2(), chunk_steps=64, batch=8)
    httpd = make_http_server(master, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"

    def get(path):
        with urllib.request.urlopen(base + path, timeout=15) as resp:
            return resp.read()

    def post(path, data=None, raw=None):
        body = raw if raw is not None else urllib.parse.urlencode(data or {}).encode()
        req = urllib.request.Request(base + path, data=body, method="POST")
        with urllib.request.urlopen(req, timeout=60) as resp:
            return resp.read()

    try:
        health = json.loads(get("/healthz"))
        assert health["ok"] and "engine" in health and "uptime_seconds" in health, health

        before = metrics.parse_text(get("/metrics").decode())

        post("/run")
        errors = []

        def client(seed):
            try:
                rng = np.random.default_rng(seed)
                v = int(rng.integers(-99, 99))
                assert json.loads(post("/compute", {"value": str(v)}))["value"] == v + 2
                vals = rng.integers(-99, 99, size=64).astype(np.int32)
                got = json.loads(post("/compute_batch", {
                    "values": " ".join(map(str, vals.tolist())), "spread": "1",
                }))["values"]
                assert got == (vals + 2).tolist()
                out = np.frombuffer(
                    post("/compute_raw?spread=1", raw=vals.astype("<i4").tobytes()),
                    dtype="<i4",
                )
                assert (out == vals + 2).all()
            except Exception as e:  # pragma: no cover — surfaced below
                errors.append(e)

        threads = [threading.Thread(target=client, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]

        after = metrics.parse_text(get("/metrics").decode())
        moved = metrics.delta(before, after)

        must_move = [
            'misaka_http_requests_total{route="/compute",method="POST"}',
            'misaka_http_requests_total{route="/compute_batch",method="POST"}',
            'misaka_http_requests_total{route="/compute_raw",method="POST"}',
            'misaka_http_request_duration_seconds_count{route="/compute"}',
            "misaka_compute_requests_total",
            "misaka_compute_values_total",
            "misaka_device_loop_ticks_total",
            "misaka_device_loop_chunk_seconds_count",
        ]
        missing = [k for k in must_move if moved.get(k, 0) <= 0]
        assert not missing, f"series did not move: {missing}"

        # histogram invariants on the live exposition
        hist_counts = 0
        for series, value in after.items():
            name, labels = metrics.parse_series(series)
            if not name.endswith("_count"):
                continue
            stem = name[: -len("_count")]
            inf_key = metrics._series(  # the canonical series string
                stem + "_bucket",
                tuple(labels) + ("le",),
                tuple(labels.values()) + ("+Inf",),
            )
            if inf_key in after:
                hist_counts += 1
                assert after[inf_key] == value, (series, after[inf_key], value)
        assert hist_counts > 0, "no histograms found in the exposition"

        print(json.dumps({
            "metrics_smoke": "ok",
            "series_total": len(after),
            "series_moved": len(moved),
            "histograms_checked": hist_counts,
            "compute_values": moved.get("misaka_compute_values_total"),
            "ticks": moved.get("misaka_device_loop_ticks_total"),
        }))
        return 0
    except AssertionError as e:
        print(f"# metrics-smoke FAILED: {e}", file=sys.stderr)
        return 1
    finally:
        master.pause()
        httpd.shutdown()


if __name__ == "__main__":
    sys.exit(main())
