"""Sanitizer stress lane: hammer the C++ serving pool under ASan/TSan/UBSan.

The PR 7 review found a TOCTOU use-after-free in exactly this shape: the
debug surfaces (/metrics, /debug/usage, /debug/flamegraph) read the
pool's busy/idle counters from scrape threads while a registry eviction
or hot-swap close()d the pool — the `_h is None` check alone left a
window where a reader dereferenced a freed C++ Pool.  The fix
(cinterp.NativePool._ctr_lock) is a Python-side discipline around native
memory, which is precisely what only a sanitizer build can re-verify:
this driver runs the concurrent serve / close / counter-read scenario
against an INSTRUMENTED libmisaka_interp and lets ASan (heap UAF), TSan
(data races between pool workers and readers), or UBSan (the int64
wrap / JRO-saturation arithmetic, fed INT32_MIN/MAX) veto the build.

Two-stage: invoked plain, it builds the sanitized .so (make native-asan
and friends produce the same artifact), locates the sanitizer runtime,
and re-execs itself under LD_PRELOAD with MISAKA_INTERP_SO pointing at
the instrumented build (utils/nativelib.py honors the override and
skips the staleness rebuild that would otherwise clobber it).  The
child then runs the scenario through the SHIPPED wrappers — the point
is to sanitize the production discipline, not a lookalike.

The r19 `--lane edge` variant points the same two-stage machinery at the
native serving edge (native/frontend.cpp via MISAKA_FRONTEND_SO): an
instrumented C++ epoll frontend in front of a real master + compute
plane, hammered by concurrent keep-alive clients, mid-flight connection
kills (torn request lines, half-shipped bodies, oversized 413s), and
supervisor close/recreate cycles — the connection-teardown and
engine-restart races only a sanitizer build can veto.

The r20 `--lane capture` variant targets the edge's wire-capture ring:
epoll workers appending locally-terminated rejects (401/413) under
cap_mu race a CPython drainer swap-draining through msk_edge_captures,
while the engine-side recorder toggles on/off (push-state swaps
re-parsing capture_enabled/capture_sample mid-traffic) and the
supervisor restart-cycles with rows still queued.

The r21 `--lane jit` variant targets the copy-and-patch tier
(core/jit.py): splicer threads race mmap → patch → W^X flip → munmap
buffer churn against an instrumented pool serving THROUGH armed
fragment tables, with arm/disarm/eviction cycles (including refused
bad-ABI arms that must leave the pool untouched) and scrape readers on
the counter/trace/simd_info surfaces throughout.  The stencil
fragments themselves stay UNinstrumented by design — sanitizer
instrumentation would add runtime-library relocations the splicer's
self-containment check rejects — so the lane polices the instrumented
pool code AROUND the fragments plus the Python-side mapping lifecycle.

Usage (or `make sanitize-smoke` / `make sanitize-all`):
    python tools/sanitize_stress.py --sanitizer address [--seconds 6]
    python tools/sanitize_stress.py --sanitizer address --lane edge
    python tools/sanitize_stress.py --sanitizer address --lane capture
    python tools/sanitize_stress.py --sanitizer address --lane jit
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # `python tools/...` puts tools/ first, not the repo
    sys.path.insert(0, REPO)

_SAN = {
    # sanitizer -> (cc flag, runtime lib, .so suffix, env var, env value)
    "address": ("-fsanitize=address", "libasan.so", "asan",
                # python itself "leaks" interned objects by design; the
                # lane polices the interpreter library, not CPython
                "ASAN_OPTIONS", "detect_leaks=0:abort_on_error=1"),
    "thread": ("-fsanitize=thread", "libtsan.so", "tsan",
               "TSAN_OPTIONS", "halt_on_error=1:second_deadlock_stack=1"),
    "undefined": ("-fsanitize=undefined -fno-sanitize-recover=all",
                  "libubsan.so", "ubsan",
                  "UBSAN_OPTIONS", "halt_on_error=1:print_stacktrace=1"),
}


def build_sanitized_so(kind: str) -> str:
    """Build native/libmisaka_interp.<kind>.so when missing or older
    than the source (mtime is fine for a local lane artifact — these
    are never shipped, unlike the hash-tagged default build).

    The Makefile's native-<kind> rule is the ONE flag definition (so
    `make native-asan` and this script cannot drift apart and test
    different binaries); the inline compile below is only the fallback
    for environments without make, mirroring SAN_CXXFLAGS."""
    flag, _, suffix, _, _ = _SAN[kind]
    src = os.path.join(REPO, "native", "interpreter.cpp")
    so = os.path.join(REPO, "native", f"libmisaka_interp.{suffix}.so")
    if os.path.exists(so) and os.path.getmtime(so) >= os.path.getmtime(src):
        return so
    print(f"# building {os.path.relpath(so, REPO)}", file=sys.stderr)
    made = subprocess.run(["make", "-C", REPO, f"native-{suffix}"],
                          capture_output=True)
    if made.returncode == 0 and os.path.exists(so):
        return so
    cxx = os.environ.get("CXX", "g++")
    cmd = [cxx, "-O1", "-g", "-fno-omit-frame-pointer", "-std=c++17",
           "-shared", "-fPIC", "-pthread", *flag.split(),
           "-Wall", "-Wextra", "-Werror", src, "-o", so]
    subprocess.run(cmd, check=True)
    return so


_FRONTEND_UNITS = ("msk_http.hpp", "msk_frame.hpp", "frontend.cpp")


def build_sanitized_frontend_so(kind: str) -> str:
    """Instrumented native edge (native/libmisaka_frontend.<kind>.so) —
    same make-first/inline-fallback shape as build_sanitized_so; the
    headers are real units (the Makefile's FRONTEND_UNITS), so staleness
    compares against the newest of the three."""
    flag, _, suffix, _, _ = _SAN[kind]
    srcs = [os.path.join(REPO, "native", u) for u in _FRONTEND_UNITS]
    so = os.path.join(REPO, "native", f"libmisaka_frontend.{suffix}.so")
    if (os.path.exists(so)
            and os.path.getmtime(so) >= max(map(os.path.getmtime, srcs))):
        return so
    print(f"# building {os.path.relpath(so, REPO)}", file=sys.stderr)
    made = subprocess.run(["make", "-C", REPO, f"native-{suffix}"],
                          capture_output=True)
    if made.returncode == 0 and os.path.exists(so):
        return so
    cxx = os.environ.get("CXX", "g++")
    cmd = [cxx, "-O1", "-g", "-fno-omit-frame-pointer", "-std=c++17",
           "-shared", "-fPIC", "-pthread", *flag.split(),
           "-Wall", "-Wextra", "-Werror", srcs[-1], "-o", so]
    subprocess.run(cmd, check=True)
    return so


def build_sanitized_spec_so(kind: str) -> str | None:
    """An INSTRUMENTED per-program specialized build of the scenario's
    network (core/specialize.py with the sanitizer's flags via
    MISAKA_SPEC_CXXFLAGS): the specialized tick paths get the same
    sanitizer coverage as the generic ones.  Built in the parent so the
    child never runs g++ under the sanitizer's LD_PRELOAD."""
    import types

    flag, _, suffix, _, _ = _SAN[kind]
    code, prog_len = _tables()
    net = types.SimpleNamespace(
        code=code, prog_len=prog_len, num_stacks=1, stack_cap=16,
        in_cap=16, out_cap=16,
    )
    from misaka_tpu.core import specialize

    prev = os.environ.get("MISAKA_SPEC_CXXFLAGS")
    os.environ["MISAKA_SPEC_CXXFLAGS"] = (
        f"{flag} -O1 -g -fno-omit-frame-pointer"
    )
    try:
        so = specialize.build(
            net,
            cache_dir=os.path.join(REPO, "native", f".spec-{suffix}-cache"),
        )
    finally:
        if prev is None:
            os.environ.pop("MISAKA_SPEC_CXXFLAGS", None)
        else:
            os.environ["MISAKA_SPEC_CXXFLAGS"] = prev
    if so is None:
        print("sanitize: WARNING — instrumented specialized build failed; "
              "the lane runs without the specialized path", file=sys.stderr)
    return so


def build_jit_stencil_cache(kind: str) -> str | None:
    """Pre-build the copy-and-patch stencil library in the PARENT (the
    child must never run g++ under the sanitizer's LD_PRELOAD).  The
    stencils are compiled with the production flags, NOT the sanitizer's:
    instrumented fragments would carry sanitizer-runtime relocations the
    self-containment check rejects — the jit lane polices the
    instrumented pool around the fragments, not the fragments."""
    from misaka_tpu.core import jit as jit_mod

    _, _, suffix, _, _ = _SAN[kind]
    cache = os.path.join(REPO, "native", f".jit-{suffix}-cache")
    path = jit_mod.build_stencils(cache)
    if path is None:
        print("sanitize: WARNING — stencil build failed; the jit lane "
              "cannot run", file=sys.stderr)
        return None
    return cache


def reexec_under_sanitizer(kind: str, args) -> int:
    so = build_sanitized_so(kind)
    # The edge lane instruments BOTH native tiers: the frontend under
    # test and the interpreter behind it (the lane's master runs
    # engine="native", so no un-instrumented hot code sits in the path).
    # The specialized build stays pool-lane-only — the edge never loads
    # a per-program .so.
    frontend_so = (build_sanitized_frontend_so(kind)
                   if args.lane in ("edge", "capture") else None)
    spec_so = build_sanitized_spec_so(kind) if args.lane == "pool" else None
    jit_cache = None
    if args.lane == "jit":
        jit_cache = build_jit_stencil_cache(kind)
        if jit_cache is None:
            return 1
    _, runtime, _, env_var, env_val = _SAN[kind]
    cxx = os.environ.get("CXX", "g++")
    lib = subprocess.run(
        [cxx, f"-print-file-name={runtime}"],
        check=True, capture_output=True, text=True,
    ).stdout.strip()
    if lib == runtime or not os.path.exists(lib):
        print(f"sanitize: {runtime} not found next to {cxx}; cannot run "
              f"the {kind} lane here", file=sys.stderr)
        return 0  # missing toolchain degrades like the native tier does
    env = dict(os.environ)
    env.update({
        "LD_PRELOAD": lib,
        env_var: env_val + ":" + env.get(env_var, ""),
        "MISAKA_INTERP_SO": so,
        "MISAKA_SANITIZE_CHILD": kind,
        **({"MISAKA_SANITIZE_SPEC_SO": spec_so} if spec_so else {}),
        **({"MISAKA_FRONTEND_SO": frontend_so} if frontend_so else {}),
        **({"MISAKA_SANITIZE_JIT_CACHE": jit_cache} if jit_cache else {}),
        # never touch (or wedge on) a TPU relay from a sanitizer lane
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
    })
    cmd = [sys.executable, os.path.abspath(__file__),
           "--sanitizer", kind, "--lane", args.lane,
           "--seconds", str(args.seconds),
           "--replicas", str(args.replicas),
           "--pool-threads", str(args.pool_threads),
           "--readers", str(args.readers)]
    print(f"# re-exec under {os.path.basename(lib)} "
          f"(MISAKA_INTERP_SO={os.path.relpath(so, REPO)})", file=sys.stderr)
    return subprocess.run(cmd, env=env).returncode


# --- the child scenario -----------------------------------------------------


def _tables():
    """One-lane IN; ADD 2; OUT — the minimal always-progressing serve
    program, built straight from the ISA tables (no parser dependency)."""
    import numpy as np

    from misaka_tpu.tis import isa

    code = np.zeros((1, 3, isa.NFIELDS), np.int32)
    code[0, 0, isa.F_OP] = isa.OP_IN          # IN  ACC
    code[0, 1, isa.F_OP] = isa.OP_ADD         # ADD 2
    code[0, 1, isa.F_SRC] = isa.SRC_IMM
    code[0, 1, isa.F_IMM] = 2
    code[0, 2, isa.F_OP] = isa.OP_OUT         # OUT ACC
    code[0, 2, isa.F_SRC] = isa.SRC_ACC
    return code, np.array([3], np.int32)


def _init_state(B: int, n: int, s: int, stack_cap: int, in_cap: int,
                out_cap: int):
    import numpy as np

    from misaka_tpu.tis import isa

    d = {
        "acc": np.zeros((B, n), np.int32),
        "bak": np.zeros((B, n), np.int32),
        "acc_hi": np.zeros((B, n), np.int32),
        "bak_hi": np.zeros((B, n), np.int32),
        "pc": np.zeros((B, n), np.int32),
        "port_val": np.zeros((B, n, isa.NUM_PORTS), np.int32),
        "port_full": np.zeros((B, n, isa.NUM_PORTS), np.uint8),
        "hold_val": np.zeros((B, n), np.int32),
        "holding": np.zeros((B, n), np.uint8),
        "stack_mem": np.zeros((B, s, stack_cap), np.int32),
        "stack_top": np.zeros((B, s), np.int32),
        "in_buf": np.zeros((B, in_cap), np.int32),
        "out_buf": np.zeros((B, out_cap), np.int32),
        "retired": np.zeros((B, n), np.int32),
    }
    for k in ("in_rd", "in_wr", "out_rd", "out_wr", "tick"):
        d[k] = np.zeros((B,), np.int32)
    return d


def run_scenario(args) -> int:
    import numpy as np

    from misaka_tpu.core import cinterp

    assert os.environ.get("MISAKA_INTERP_SO"), "child needs the override"
    if not cinterp.available():
        print("sanitize: instrumented interpreter failed to load",
              file=sys.stderr)
        return 1

    B, in_cap = args.replicas, 16
    code, prog_len = _tables()
    stop = threading.Event()
    serve_gate = threading.Event()   # set = serve thread may run
    serve_idle = threading.Event()   # set = serve thread parked at the gate
    serve_gate.set()
    errors: list[BaseException] = []
    stats = {"passes": 0, "values": 0, "reads": 0, "closed_reads": 0,
             "cycles": 0, "resident_passes": 0, "trace_reads": 0,
             "trace_records": 0}

    # Pool variants rotated across close/recreate cycles so every ladder
    # rung runs the concurrent serve/close/counter-read race under the
    # sanitizer: the AVX2 group path, the generic group fallback, the
    # scalar per-replica path (MISAKA_SIMD=0), and — when the parent
    # built one — the instrumented SPECIALIZED build's baked tick paths.
    spec_lib = None
    spec_path = os.environ.get("MISAKA_SANITIZE_SPEC_SO")
    if spec_path:
        spec_lib = cinterp.load_specialized(spec_path)
    variants = [(None, None), ("generic", None), ("0", None)]
    # the group/specialized paths only arm with at least one full SIMD
    # group of replicas (kGroupW = 8); below that every variant runs the
    # scalar engine and expecting `specialized` to engage would abort a
    # lane that is correctly degrading
    group_capable = B >= 8
    if spec_lib is not None and group_capable:
        variants.append((None, spec_lib))
    stats["spec_pools"] = 0

    def new_pool(variant: int):
        mode, lib = variants[variant % len(variants)]
        prev = os.environ.pop("MISAKA_SIMD", None)
        if mode is not None:
            os.environ["MISAKA_SIMD"] = mode
        try:
            pool = cinterp.NativePool(
                code, prog_len, 1, 16, in_cap, in_cap,
                replicas=B, threads=args.pool_threads, lib=lib,
            )
        finally:
            os.environ.pop("MISAKA_SIMD", None)
            if prev is not None:
                os.environ["MISAKA_SIMD"] = prev
        if lib is not None:
            assert pool.simd_info()["specialized"], \
                "specialized build did not engage"
            stats["spec_pools"] += 1
        return pool

    box = {"pool": new_pool(0)}
    rng = np.random.default_rng(7)

    def serve_loop():
        # The single serve caller (the device-loop contract); pauses at
        # the gate so close/recreate happens against a quiescent pool —
        # exactly the drain-to-quiescence discipline the engine uses.
        d = _init_state(B, 1, 1, 16, in_cap, in_cap)
        try:
            while not stop.is_set():
                if not serve_gate.is_set():
                    serve_idle.set()
                    serve_gate.wait(timeout=1.0)
                    d = _init_state(B, 1, 1, 16, in_cap, in_cap)
                    continue
                serve_idle.clear()
                pool = box["pool"]
                counts = rng.integers(0, 5, size=B).astype(np.int32)
                # extreme magnitudes drive the 64-bit wrap arithmetic
                # (UBSan's half of the lane); int32 wrap on the wire is
                # the spec, so expectations wrap with i32 semantics
                vals = np.zeros((B, in_cap), np.int32)
                for b in range(B):
                    vals[b, :counts[b]] = rng.choice(
                        [-2**31, -7, 0, 5, 2**31 - 1, 2**31 - 2],
                        size=counts[b],
                    ).astype(np.int32)
                # Alternate the r17 RESIDENT path with the stateless one:
                # import/serve_resident/export race the same scrape
                # readers (and drive the futex dispenser + masked group
                # ticks), and the export-under-load is exactly the
                # lifecycle path a checkpoint takes against a hot pool.
                resident = stats["passes"] % 2 == 1
                active = np.arange(min(2, B), dtype=np.int32)
                if resident:
                    if not pool.is_resident() and not pool.import_state(d):
                        raise AssertionError("resident import refused")
                    packed, progress = pool.serve_resident(vals, counts, 64)
                    assert progress.shape == (B,)
                    # masked partial-fill resident pass (group-mask path)
                    pool.serve_resident(
                        np.zeros((B, in_cap), np.int32),
                        np.zeros((B,), np.int32), 8, active=active,
                    )
                    d = pool.export_state()  # the lifecycle export
                    assert d is not None
                    stats["resident_passes"] += 1
                else:
                    if pool.is_resident():
                        pool.discard_resident()  # d carries the export
                    d, packed = pool.serve(d, vals, counts, ticks=64)
                    # partial-fill serial fast path (n<=4 runs on THIS
                    # thread): a second shape through the same superstep
                    d, _ = pool.serve(
                        d, np.zeros((B, in_cap), np.int32),
                        np.zeros((B,), np.int32), ticks=8, active=active,
                    )
                for b in range(B):
                    rd, wr = int(packed[b, 2]), int(packed[b, 3])
                    got = packed[b, 4:][(rd + np.arange(wr - rd)) % in_cap]
                    want = (vals[b, :counts[b]].astype(np.int64) + 2)
                    want = want.astype(np.uint64).astype(np.uint32)
                    # plain compare, NOT np.testing: numpy.testing's lazy
                    # first import spawns a subprocess (check_support_sve),
                    # and fork() under the TSan runtime deadlocks
                    if not np.array_equal(got.astype(np.uint32), want):
                        raise AssertionError(
                            f"replica {b} served wrong values: "
                            f"{got!r} != {want!r}"
                        )
                    stats["values"] += wr - rd
                stats["passes"] += 1
        except BaseException as e:  # noqa: BLE001 — surfaced at exit
            errors.append(e)
            stop.set()
        finally:
            serve_idle.set()

    def reader_loop():
        # Scrape-thread twin: hammers the counter read AND the r18
        # flight-recorder read API (ring snapshots, aggregate stats)
        # CONCURRENTLY with serve and with close/recreate — TSan over the
        # lock-free ring handshake (relaxed record stores + release
        # cursor / acquire reader) is the point of this lane, and the
        # torn-row discipline must hold while workers lap the reader.
        # "pool is closed" is the typed, expected outcome of losing the
        # close race; a UAF is what ASan/TSan are here to veto.
        try:
            ring = 0
            while not stop.is_set():
                pool = box["pool"]
                try:
                    c = pool.counters()
                    assert c["busy_ns"] >= 0 and c["idle_ns"] >= 0
                    pool.thread_counters()
                    info = pool.trace_info()
                    if info["rings"]:
                        recs, cursor, dropped = pool.trace_read(
                            ring % info["rings"]
                        )
                        # bounded rings: a snapshot never exceeds capacity
                        assert len(recs) <= info["capacity"], \
                            (len(recs), info["capacity"])
                        assert cursor >= len(recs) and dropped >= 0
                        s = pool.trace_stats()
                        assert s["serve_calls"] >= 0 and s["dropped"] >= 0
                        stats["trace_reads"] += 1
                        stats["trace_records"] += len(recs)
                    ring += 1
                    stats["reads"] += 1
                except RuntimeError:
                    stats["closed_reads"] += 1
                except ValueError:
                    stats["closed_reads"] += 1  # ring raced a recreate
        except BaseException as e:  # noqa: BLE001
            errors.append(e)
            stop.set()

    threads = [threading.Thread(target=serve_loop)]
    threads += [threading.Thread(target=reader_loop)
                for _ in range(args.readers)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + args.seconds
    try:
        while time.monotonic() < deadline and not stop.is_set():
            time.sleep(0.2)
            # the PR 7 shape: close while scrape threads are mid-hammer
            serve_gate.clear()
            if not serve_idle.wait(timeout=10):
                errors.append(RuntimeError("serve thread never quiesced"))
                break
            old = box["pool"]
            box["pool"] = new_pool(stats["cycles"] + 1)
            old.close()  # readers may hold `old` RIGHT NOW — the race
            stats["cycles"] += 1
            serve_gate.set()
    finally:
        stop.set()
        serve_gate.set()
        for t in threads:
            t.join(timeout=30)
        box["pool"].close()
    if errors:
        print(f"sanitize: scenario error: {errors[0]!r}", file=sys.stderr)
        return 1
    if not (stats["passes"] and stats["reads"] and stats["cycles"]
            and stats["resident_passes"] and stats["trace_reads"]):
        print(f"sanitize: scenario did not exercise the race: {stats}",
              file=sys.stderr)
        return 1
    print(f"# sanitize[{os.environ.get('MISAKA_SANITIZE_CHILD')}] green: "
          f"{stats['passes']} serve passes / {stats['values']} values "
          f"({stats['resident_passes']} resident), "
          f"{stats['reads']} counter reads "
          f"({stats['closed_reads']} typed closed-pool losses), "
          f"{stats['trace_reads']} ring snapshots / "
          f"{stats['trace_records']} records, "
          f"{stats['cycles']} close/recreate cycles "
          f"({stats['spec_pools']} specialized pools)", file=sys.stderr)
    return 0


def run_edge_scenario(args) -> int:
    """The r19 edge lane: an INSTRUMENTED native/frontend.cpp serving a
    real master + compute plane while three hostile actors race it —
    keep-alive clients (200s interleaved with locally-answered 401/413
    rejections), a killer shipping torn request lines / half bodies /
    oversized 413s and slamming connections shut mid-flight, and the
    main thread close()/recreate-ing the supervisor (full C++ engine
    stop/start) under fire.  Every shape the sanitizer must bless:
    connection teardown with responses in flight, the plane-ship path,
    the span-ring drain racing the scrape thread, and restart cycles."""
    import http.client
    import json as _json
    import random
    import socket
    import struct
    import tempfile
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    import numpy as np

    assert os.environ.get("MISAKA_FRONTEND_SO"), "child needs the override"

    tmp = tempfile.mkdtemp(prefix="msk-san-edge-")
    keyfile = os.path.join(tmp, "keys.json")
    with open(keyfile, "w") as f:
        _json.dump({"keys": [
            {"key": "adm-secret", "tenant": "ops", "admin": True},
            # burst cap 8.0 values: a 12-value body is a deterministic
            # locally-answered 413 regardless of bucket fill
            {"key": "tiny-secret", "tenant": "tiny", "quota": "vps<4"},
        ]}, f)
    os.environ["MISAKA_API_KEYS"] = keyfile
    os.environ["MISAKA_MAX_BODY"] = "65536"
    os.environ["MISAKA_TRACE"] = "1"  # arm the C++ span ring + drain path

    from misaka_tpu.runtime import edge
    from misaka_tpu.runtime import frontends

    if not frontends._FRONTEND_LIB.available():
        print("sanitize: instrumented frontend failed to load",
              file=sys.stderr)
        return 1
    # normally make_http_server's job at engine boot — this lane has no
    # CPython engine server, so arm the edge chain from env directly
    edge.install(edge.from_env())

    class _StubMaster:
        """numpy twin of the scenario's add2 network.  The plane calls
        exactly is_running + compute_coalesced, and a jax-free stub
        keeps jit lowering out of the child: MLIR uses C++ exceptions
        as control flow, and the LD_PRELOADed sanitizer runtime aborts
        on a throw it never got to intercept.  The lane polices the
        C++ FRONTEND, not the engine behind it."""
        is_running = True

        def compute_coalesced(self, values, timeout=None,
                              return_array=True, traces=()):
            return np.asarray(values, np.int32) + 2

    class _ProxyStub(BaseHTTPRequestHandler):
        """Minimal proxy target for non-hot routes — exercises the
        native proxy path without a CPython engine server."""
        protocol_version = "HTTP/1.1"

        def do_GET(self):
            body = b"proxied-ok"
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # noqa: ARG002 — quiet lane
            pass

    class _QuietHTTPServer(ThreadingHTTPServer):
        def handle_error(self, request, client_address):
            pass  # killer-slammed proxy connections are the scenario

    httpd = _QuietHTTPServer(("127.0.0.1", 0), _ProxyStub)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    engine_port = httpd.server_address[1]
    plane_path = os.path.join(tmp, "plane.sock")
    plane = frontends.start_compute_plane(_StubMaster(), plane_path)

    def new_sup():
        return frontends.NativeFrontendSupervisor(
            port=0, proxy_port=engine_port, plane_path=plane_path,
            threads=2, plane_conns=1,
        )

    box = {"sup": new_sup()}
    stop = threading.Event()
    errors: list[BaseException] = []
    lock = threading.Lock()
    stats = {"requests": 0, "values": 0, "local_401": 0, "local_413": 0,
             "proxied": 0, "kills": 0, "cycles": 0, "scrapes": 0,
             "span_rows": 0, "conn_losses": 0}

    def bump(k, n=1):
        with lock:
            stats[k] += n

    def client_loop(seed: int):
        # Keep-alive hammer through the SHIPPED http.client path: every
        # burst mixes plane-shipped 200s (values verified end to end)
        # with the edge's locally-answered 401/413 (connection must
        # survive both) and the native /healthz.  A connection refused /
        # reset is the typed outcome of losing a restart-cycle race.
        rng = random.Random(seed)
        try:
            while not stop.is_set():
                port = box["sup"].port
                try:
                    conn = http.client.HTTPConnection(
                        "127.0.0.1", port, timeout=10)
                    for _ in range(8):
                        if stop.is_set():
                            break
                        n = rng.randrange(1, 5)
                        vals = [rng.randrange(-1000, 1000) for _ in range(n)]
                        body = struct.pack(f"<{n}i", *vals)
                        conn.request("POST", "/compute_raw", body=body,
                                     headers={"X-Misaka-Key": "adm-secret"})
                        r = conn.getresponse()
                        data = r.read()
                        if r.status != 200:
                            raise AssertionError(
                                f"compute_raw {r.status}: {data!r}")
                        got = struct.unpack(f"<{n}i", data)
                        if got != tuple(v + 2 for v in vals):
                            raise AssertionError(
                                f"edge served wrong values: {got} != "
                                f"{tuple(v + 2 for v in vals)}")
                        bump("requests")
                        bump("values", n)
                        conn.request("POST", "/compute_raw", body=body)
                        r = conn.getresponse()
                        r.read()
                        if r.status != 401:
                            raise AssertionError(f"keyless got {r.status}")
                        bump("local_401")
                        big = struct.pack("<12i", *range(12))
                        conn.request("POST", "/compute_raw", body=big,
                                     headers={"X-Misaka-Key": "tiny-secret"})
                        r = conn.getresponse()
                        r.read()
                        if r.status != 413:
                            raise AssertionError(f"burst got {r.status}")
                        bump("local_413")
                        conn.request("GET", "/healthz")
                        r = conn.getresponse()
                        r.read()
                        if r.status != 200:
                            raise AssertionError(f"healthz {r.status}")
                        # non-hot route → the native proxy path
                        conn.request("GET", "/status")
                        r = conn.getresponse()
                        if (r.status, r.read()) != (200, b"proxied-ok"):
                            raise AssertionError(f"proxy got {r.status}")
                        bump("proxied")
                    conn.close()
                except (OSError, http.client.HTTPException):
                    bump("conn_losses")
                    time.sleep(0.02)
        except BaseException as e:  # noqa: BLE001 — surfaced at exit
            errors.append(e)
            stop.set()

    def killer_loop(seed: int):
        # Mid-flight kills: the teardown shapes a public listener eats
        # all day — torn request line, half-shipped body, a connect/slam,
        # and the oversized 413 whose contract is reply-then-TCP-close.
        rng = random.Random(seed)
        try:
            while not stop.is_set():
                port = box["sup"].port
                try:
                    s = socket.create_connection(("127.0.0.1", port),
                                                 timeout=5)
                    mode = rng.randrange(4)
                    if mode == 0:
                        s.sendall(b"POST /compute_raw HTT")
                    elif mode == 1:
                        s.sendall(b"POST /compute_raw HTTP/1.1\r\n"
                                  b"Content-Length: 4096\r\n\r\n"
                                  + b"x" * rng.randrange(0, 512))
                    elif mode == 2:
                        s.sendall(b"POST /compute_raw HTTP/1.1\r\n"
                                  b"X-Misaka-Key: adm-secret\r\n"
                                  b"Content-Length: 999999\r\n\r\n")
                        try:
                            s.recv(4096)  # the 413; server closes after
                        except OSError:
                            pass
                    s.close()  # mode 3: connect and slam shut
                    bump("kills")
                except OSError:
                    bump("conn_losses")
                time.sleep(0.002)
        except BaseException as e:  # noqa: BLE001
            errors.append(e)
            stop.set()

    def scrape_loop():
        # The supervisor's read surfaces (stats buffer, span-ring drain)
        # racing traffic AND restart cycles — a stale supervisor losing
        # the swap race must degrade typed, never crash.
        try:
            while not stop.is_set():
                sup = box["sup"]
                try:
                    st = sup.state()
                    assert st.get("requests", 0) >= 0
                    bump("span_rows", len(sup.recent_spans()))
                    bump("scrapes")
                except Exception:
                    bump("conn_losses")
                time.sleep(0.01)
        except BaseException as e:  # noqa: BLE001
            errors.append(e)
            stop.set()

    threads = [threading.Thread(target=client_loop, args=(i,))
               for i in range(3)]
    threads += [threading.Thread(target=killer_loop, args=(100 + i,))
                for i in range(2)]
    threads.append(threading.Thread(target=scrape_loop))
    for t in threads:
        t.start()
    deadline = time.monotonic() + args.seconds
    try:
        while time.monotonic() < deadline and not stop.is_set():
            time.sleep(0.9)
            # supervisor restart cycle under fire: the C++ engine is
            # one-per-process, so close FIRST — clients mid-request lose
            # the race (typed conn_losses), the recreate must come up
            # clean on a fresh port with state re-pushed
            box["sup"].close()
            box["sup"] = new_sup()
            bump("cycles")
    except BaseException as e:  # noqa: BLE001 — recreate failed
        errors.append(e)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
        box["sup"].close()
        plane.close()
        httpd.shutdown()
    if errors:
        print(f"sanitize[edge]: scenario error: {errors[0]!r}",
              file=sys.stderr)
        return 1
    if not (stats["requests"] and stats["local_401"] and stats["local_413"]
            and stats["proxied"] and stats["kills"] and stats["cycles"]
            and stats["scrapes"]):
        print(f"sanitize[edge]: scenario did not exercise the races: "
              f"{stats}", file=sys.stderr)
        return 1
    print(f"# sanitize[{os.environ.get('MISAKA_SANITIZE_CHILD')}/edge] "
          f"green: {stats['requests']} plane 200s / {stats['values']} "
          f"values, {stats['local_401']}+{stats['local_413']} local "
          f"401/413 rejections, {stats['proxied']} proxied, "
          f"{stats['kills']} mid-flight kills, "
          f"{stats['cycles']} supervisor restart cycles, "
          f"{stats['scrapes']} scrapes / {stats['span_rows']} span rows "
          f"({stats['conn_losses']} typed connection losses)",
          file=sys.stderr)
    return 0


def run_capture_scenario(args) -> int:
    """The r20 capture lane: the C++ edge's wire-capture ring under
    sanitizer fire.  Every reject the edge terminates locally
    (401/413/shed) appends a CaptureRec under cap_mu from an epoll
    worker thread while a CPython drainer swap-drains the deque through
    msk_edge_captures — this lane races those writers against an
    aggressive drain loop, the engine-side recorder toggling on/off
    (push-state swaps re-parsing capture_enabled/capture_sample
    mid-traffic), and full supervisor restart cycles with rows still
    queued in the ring.  Inbound X-Misaka-Trace requests pin the
    sampling-bypass path; a 0.5 sample rate keeps the xorshift sampling
    branch hot too."""
    import http.client
    import json as _json
    import random
    import struct
    import tempfile

    import numpy as np

    assert os.environ.get("MISAKA_FRONTEND_SO"), "child needs the override"

    tmp = tempfile.mkdtemp(prefix="msk-san-capture-")
    keyfile = os.path.join(tmp, "keys.json")
    with open(keyfile, "w") as f:
        _json.dump({"keys": [
            {"key": "adm-secret", "tenant": "ops", "admin": True},
            {"key": "tiny-secret", "tenant": "tiny", "quota": "vps<4"},
        ]}, f)
    os.environ["MISAKA_API_KEYS"] = keyfile
    os.environ["MISAKA_MAX_BODY"] = "65536"
    os.environ["MISAKA_CAPTURE_SAMPLE"] = "0.5"

    from misaka_tpu.runtime import capture as capture_mod
    from misaka_tpu.runtime import edge
    from misaka_tpu.runtime import frontends

    if not frontends._FRONTEND_LIB.available():
        print("sanitize: instrumented frontend failed to load",
              file=sys.stderr)
        return 1
    edge.install(edge.from_env())
    capture_mod.configure()
    capture_mod.start()

    class _StubMaster:
        """Same jax-free numpy twin as the edge lane (see there for why
        the real engine stays out of a sanitizer child)."""
        is_running = True

        def compute_coalesced(self, values, timeout=None,
                              return_array=True, traces=()):
            return np.asarray(values, np.int32) + 2

    plane_path = os.path.join(tmp, "plane.sock")
    plane = frontends.start_compute_plane(_StubMaster(), plane_path)

    def new_sup():
        return frontends.NativeFrontendSupervisor(
            port=0, proxy_port=1, plane_path=plane_path,
            threads=2, plane_conns=1,
        )

    box = {"sup": new_sup()}
    stop = threading.Event()
    errors: list[BaseException] = []
    lock = threading.Lock()
    stats = {"requests": 0, "local_401": 0, "local_413": 0, "inbound": 0,
             "drains": 0, "ring_rows": 0, "toggles": 0, "cycles": 0,
             "conn_losses": 0}

    def bump(k, n=1):
        with lock:
            stats[k] += n

    def reject_loop(seed: int):
        # Every burst lands three locally-terminated rejects in the C++
        # capture ring — a sampled keyless 401, a sampled over-quota 413,
        # and a traced 401 that MUST bypass sampling — plus a plane 200
        # to keep the serving path interleaved with the recording path.
        rng = random.Random(seed)
        try:
            while not stop.is_set():
                port = box["sup"].port
                try:
                    conn = http.client.HTTPConnection(
                        "127.0.0.1", port, timeout=10)
                    for _ in range(8):
                        if stop.is_set():
                            break
                        n = rng.randrange(1, 4)
                        body = struct.pack(
                            f"<{n}i", *(rng.randrange(1000) for _ in range(n)))
                        conn.request("POST", "/compute_raw", body=body,
                                     headers={"X-Misaka-Key": "adm-secret"})
                        r = conn.getresponse()
                        r.read()
                        if r.status != 200:
                            raise AssertionError(f"compute_raw {r.status}")
                        bump("requests")
                        conn.request("POST", "/compute_raw", body=body)
                        r = conn.getresponse()
                        r.read()
                        if r.status != 401:
                            raise AssertionError(f"keyless got {r.status}")
                        bump("local_401")
                        big = struct.pack("<12i", *range(12))
                        conn.request("POST", "/compute_raw", body=big,
                                     headers={"X-Misaka-Key": "tiny-secret"})
                        r = conn.getresponse()
                        r.read()
                        if r.status != 413:
                            raise AssertionError(f"burst got {r.status}")
                        bump("local_413")
                        trace = f"{rng.getrandbits(64):016x}"
                        conn.request("POST", "/compute_raw", body=body,
                                     headers={"X-Misaka-Trace": trace})
                        r = conn.getresponse()
                        r.read()
                        if r.status != 401:
                            raise AssertionError(f"traced got {r.status}")
                        bump("inbound")
                    conn.close()
                except (OSError, http.client.HTTPException):
                    bump("conn_losses")
                    time.sleep(0.02)
        except BaseException as e:  # noqa: BLE001 — surfaced at exit
            errors.append(e)
            stop.set()

    def drain_loop():
        # The read half of the race: swap-drain the C++ deque through
        # msk_edge_captures into the engine-side ring, against both the
        # epoll writers and the watcher thread's own periodic drain.  A
        # stale supervisor losing the restart race degrades typed.
        last = 0
        nonlocal_last = [last]
        try:
            while not stop.is_set():
                sup = box["sup"]
                try:
                    sup._drain_captures()
                    bump("drains")
                except Exception:
                    bump("conn_losses")
                cur = capture_mod.status()["records"]
                if cur > nonlocal_last[0]:
                    bump("ring_rows", cur - nonlocal_last[0])
                nonlocal_last[0] = cur
                time.sleep(0.005)
        except BaseException as e:  # noqa: BLE001
            errors.append(e)
            stop.set()

    threads = [threading.Thread(target=reject_loop, args=(i,))
               for i in range(3)]
    threads.append(threading.Thread(target=drain_loop))
    for t in threads:
        t.start()
    deadline = time.monotonic() + args.seconds
    try:
        flip = 0
        while time.monotonic() < deadline and not stop.is_set():
            time.sleep(0.9)
            flip += 1
            # recorder toggle under fire: the push-state swap re-parses
            # capture_enabled/capture_sample while workers are mid-
            # record_capture on the previous state generation
            if capture_mod.recording():
                capture_mod.stop()
            else:
                capture_mod.start()
            bump("toggles")
            try:
                box["sup"]._push(force=True)
            except Exception:
                bump("conn_losses")
            if flip % 2 == 0:
                # restart cycle with rows still queued in the C++ ring
                box["sup"].close()
                box["sup"] = new_sup()
                bump("cycles")
    except BaseException as e:  # noqa: BLE001 — recreate failed
        errors.append(e)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
        box["sup"].close()
        plane.close()
        if capture_mod.recording():
            capture_mod.stop()
    if errors:
        print(f"sanitize[capture]: scenario error: {errors[0]!r}",
              file=sys.stderr)
        return 1
    if not (stats["requests"] and stats["local_401"] and stats["local_413"]
            and stats["inbound"] and stats["drains"] and stats["ring_rows"]
            and stats["toggles"] and stats["cycles"]):
        print(f"sanitize[capture]: scenario did not exercise the races: "
              f"{stats}", file=sys.stderr)
        return 1
    print(f"# sanitize[{os.environ.get('MISAKA_SANITIZE_CHILD')}/capture] "
          f"green: {stats['requests']} plane 200s, "
          f"{stats['local_401']}+{stats['local_413']} sampled rejects / "
          f"{stats['inbound']} sampling-bypass traced rejects, "
          f"{stats['drains']} ring drains -> {stats['ring_rows']} rows "
          f"ingested, {stats['toggles']} recorder toggles, "
          f"{stats['cycles']} supervisor restart cycles "
          f"({stats['conn_losses']} typed connection losses)",
          file=sys.stderr)
    return 0


def run_jit_scenario(args) -> int:
    """The r21 jit lane: copy-and-patch buffer churn under sanitizer
    fire.  Splicer threads loop prepare() — mmap, fragment patch, W^X
    mprotect flip — and munmap retired buffers while the instrumented
    pool serves THROUGH the armed fragment tables and scrape readers
    hammer counters/trace_stats/simd_info.  Arm/disarm/eviction honors
    the production contract (between serve calls, quiesced like
    import/discard), but everything around the contract races: shared
    in-process stencil cache under _lib_lock, refused bad-ABI arms
    against a hot pool's metadata, full pool close/recreate cycles with
    readers mid-hammer, and the disarm → munmap edge where a stale
    reader must lose typed, never dereference freed executable pages."""
    import types

    import numpy as np

    from misaka_tpu.core import cinterp
    from misaka_tpu.core import jit as jit_mod

    assert os.environ.get("MISAKA_INTERP_SO"), "child needs the override"
    cache = os.environ.get("MISAKA_SANITIZE_JIT_CACHE")
    assert cache, "parent pre-builds the stencil cache"
    if not cinterp.available():
        print("sanitize: instrumented interpreter failed to load",
              file=sys.stderr)
        return 1

    B, in_cap = args.replicas, 16
    code, prog_len = _tables()
    net = types.SimpleNamespace(
        code=code, prog_len=prog_len, num_stacks=1, stack_cap=16,
        in_cap=in_cap, out_cap=in_cap,
    )
    first = jit_mod.prepare(net, cache_dir=cache)
    if first is None:
        print("sanitize[jit]: stencil library unavailable", file=sys.stderr)
        return 1

    stop = threading.Event()
    serve_gate = threading.Event()
    serve_idle = threading.Event()
    serve_gate.set()
    errors: list[BaseException] = []
    lock = threading.Lock()
    stats = {"passes": 0, "values": 0, "resident_passes": 0, "splices": 0,
             "evictions": 0, "arm_cycles": 0, "refused": 0, "cycles": 0,
             "reads": 0, "closed_reads": 0}
    spare: list = []  # splicer-produced programs awaiting arm/eviction

    def bump(k, n=1):
        with lock:
            stats[k] += n

    def new_pool():
        return cinterp.NativePool(
            code, prog_len, 1, 16, in_cap, in_cap,
            replicas=B, threads=args.pool_threads,
        )

    box = {"pool": new_pool(), "prog": first}
    if box["pool"].jit_arm(first) != 0:
        print("sanitize[jit]: initial arm refused", file=sys.stderr)
        return 1
    rng = np.random.default_rng(11)

    def serve_loop():
        # The single serve caller, through the ARMED fragment tables;
        # values verified end to end so a mispatched hole can never pass
        # as "no sanitizer report".  Same gate discipline as the pool
        # lane: arm/evict/recreate happens against a quiescent pool.
        d = _init_state(B, 1, 1, 16, in_cap, in_cap)
        try:
            while not stop.is_set():
                if not serve_gate.is_set():
                    serve_idle.set()
                    serve_gate.wait(timeout=1.0)
                    d = _init_state(B, 1, 1, 16, in_cap, in_cap)
                    continue
                serve_idle.clear()
                pool = box["pool"]
                counts = rng.integers(0, 5, size=B).astype(np.int32)
                vals = np.zeros((B, in_cap), np.int32)
                for b in range(B):
                    vals[b, :counts[b]] = rng.choice(
                        [-2**31, -7, 0, 5, 2**31 - 1, 2**31 - 2],
                        size=counts[b],
                    ).astype(np.int32)
                resident = stats["passes"] % 2 == 1
                active = np.arange(min(2, B), dtype=np.int32)
                if resident:
                    if not pool.is_resident() and not pool.import_state(d):
                        raise AssertionError("resident import refused")
                    packed, progress = pool.serve_resident(vals, counts, 64)
                    assert progress.shape == (B,)
                    # masked partial-fill pass + packed-buffer reuse: the
                    # r21 elision ledger path under the sanitizer
                    pool.serve_resident(
                        np.zeros((B, in_cap), np.int32),
                        np.zeros((B,), np.int32), 8, active=active,
                        reuse_out=True,
                    )
                    d = pool.export_state()
                    assert d is not None
                    bump("resident_passes")
                else:
                    if pool.is_resident():
                        pool.discard_resident()
                    d, packed = pool.serve(d, vals, counts, ticks=64)
                for b in range(B):
                    rd, wr = int(packed[b, 2]), int(packed[b, 3])
                    got = packed[b, 4:][(rd + np.arange(wr - rd)) % in_cap]
                    want = (vals[b, :counts[b]].astype(np.int64) + 2)
                    want = want.astype(np.uint64).astype(np.uint32)
                    if not np.array_equal(got.astype(np.uint32), want):
                        raise AssertionError(
                            f"replica {b} served wrong values through the "
                            f"jit tables: {got!r} != {want!r}")
                    bump("values", wr - rd)
                bump("passes")
        except BaseException as e:  # noqa: BLE001 — surfaced at exit
            errors.append(e)
            stop.set()
        finally:
            serve_idle.set()

    def splicer_loop(seed: int):
        # mmap → patch → mprotect(RX) churn concurrent with serving and
        # with the other splicer; retired buffers munmap while unrelated
        # mappings are executing on pool worker threads.
        lrng = np.random.default_rng(seed)
        try:
            while not stop.is_set():
                prog = jit_mod.prepare(net, cache_dir=cache)
                if prog is None:
                    raise AssertionError("prepare failed mid-lane")
                bump("splices")
                with lock:
                    spare.append(prog)
                    retire = spare[:-3] if len(spare) > 3 else []
                    del spare[:-3]
                for p in retire:
                    p.close()  # W^X unmap while the pool executes OTHERS
                    bump("evictions")
                time.sleep(float(lrng.uniform(0.001, 0.01)))
        except BaseException as e:  # noqa: BLE001
            errors.append(e)
            stop.set()

    def reader_loop():
        # Scrape twin: counters + trace aggregates + simd_info (which
        # reads jit_armed under the same _ctr_lock arm/disarm takes).
        try:
            while not stop.is_set():
                pool = box["pool"]
                try:
                    c = pool.counters()
                    assert c["elided_rows"] >= 0
                    assert c["skip_packed_rows"] >= 0
                    s = pool.trace_stats()
                    assert s["serve_calls"] >= 0
                    pool.simd_info()
                    bump("reads")
                except RuntimeError:
                    bump("closed_reads")
                except ValueError:
                    bump("closed_reads")
        except BaseException as e:  # noqa: BLE001
            errors.append(e)
            stop.set()

    threads = [threading.Thread(target=serve_loop)]
    threads += [threading.Thread(target=splicer_loop, args=(50 + i,))
                for i in range(2)]
    threads += [threading.Thread(target=reader_loop)
                for _ in range(args.readers)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + args.seconds
    try:
        while time.monotonic() < deadline and not stop.is_set():
            time.sleep(0.2)
            serve_gate.clear()
            if not serve_idle.wait(timeout=10):
                errors.append(RuntimeError("serve thread never quiesced"))
                break
            pool = box["pool"]
            # refused arm first: ABI drift must leave the pool serving
            # exactly as armed (rc -1, tables untouched)
            bad = spare and stats["cycles"] % 3 == 0
            if bad:
                with lock:
                    probe = spare[-1]
                probe.abi = 999
                if pool.jit_arm(probe) != -1:
                    errors.append(RuntimeError("bad-ABI arm not refused"))
                    break
                probe.abi = jit_mod.MISAKA_JIT_ABI
                bump("refused")
            if stats["cycles"] % 4 == 3:
                # full eviction: recreate the pool with readers mid-hammer,
                # then re-arm the live program so serving stays on the rung
                old = box["pool"]
                box["pool"] = new_pool()
                old.close()
                if box["pool"].jit_arm(box["prog"]) != 0:
                    errors.append(RuntimeError("arm after recreate refused"))
                    break
            nxt = None
            with lock:
                if spare:
                    nxt = spare.pop()
            if nxt is not None:
                pool = box["pool"]
                pool.jit_disarm()
                old_prog, box["prog"] = box["prog"], nxt
                old_prog.close()  # disarm → munmap edge
                if pool.jit_arm(nxt) != 0:
                    errors.append(RuntimeError("re-arm refused"))
                    break
                bump("arm_cycles")
            bump("cycles")
            serve_gate.set()
    finally:
        stop.set()
        serve_gate.set()
        for t in threads:
            t.join(timeout=30)
        box["pool"].close()
        box["prog"].close()
        with lock:
            retire = list(spare)
            spare.clear()
        for p in retire:
            p.close()
    if errors:
        print(f"sanitize[jit]: scenario error: {errors[0]!r}",
              file=sys.stderr)
        return 1
    if not (stats["passes"] and stats["values"] and stats["splices"]
            and stats["arm_cycles"] and stats["refused"]
            and stats["evictions"] and stats["reads"]
            and stats["resident_passes"]):
        print(f"sanitize[jit]: scenario did not exercise the races: "
              f"{stats}", file=sys.stderr)
        return 1
    print(f"# sanitize[{os.environ.get('MISAKA_SANITIZE_CHILD')}/jit] "
          f"green: {stats['passes']} serve passes / {stats['values']} "
          f"values through jit tables ({stats['resident_passes']} "
          f"resident), {stats['splices']} splices / "
          f"{stats['evictions']} buffer evictions, "
          f"{stats['arm_cycles']} arm cycles + {stats['refused']} refused "
          f"bad-ABI arms, {stats['cycles']} quiesce cycles, "
          f"{stats['reads']} scrape reads ({stats['closed_reads']} typed "
          f"closed-pool losses)", file=sys.stderr)
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--sanitizer", default="address",
                    choices=sorted(_SAN))
    ap.add_argument("--lane", default="pool",
                    choices=("pool", "edge", "capture", "jit"))
    ap.add_argument("--seconds", type=float, default=6.0)
    ap.add_argument("--replicas", type=int, default=64)
    ap.add_argument("--pool-threads", type=int, default=8)
    ap.add_argument("--readers", type=int, default=4)
    args = ap.parse_args()
    if os.environ.get("MISAKA_SANITIZE_CHILD"):
        if args.lane == "edge":
            return run_edge_scenario(args)
        if args.lane == "capture":
            return run_capture_scenario(args)
        if args.lane == "jit":
            return run_jit_scenario(args)
        return run_scenario(args)
    return reexec_under_sanitizer(args.sanitizer, args)


if __name__ == "__main__":
    sys.exit(main())
