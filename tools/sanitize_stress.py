"""Sanitizer stress lane: hammer the C++ serving pool under ASan/TSan/UBSan.

The PR 7 review found a TOCTOU use-after-free in exactly this shape: the
debug surfaces (/metrics, /debug/usage, /debug/flamegraph) read the
pool's busy/idle counters from scrape threads while a registry eviction
or hot-swap close()d the pool — the `_h is None` check alone left a
window where a reader dereferenced a freed C++ Pool.  The fix
(cinterp.NativePool._ctr_lock) is a Python-side discipline around native
memory, which is precisely what only a sanitizer build can re-verify:
this driver runs the concurrent serve / close / counter-read scenario
against an INSTRUMENTED libmisaka_interp and lets ASan (heap UAF), TSan
(data races between pool workers and readers), or UBSan (the int64
wrap / JRO-saturation arithmetic, fed INT32_MIN/MAX) veto the build.

Two-stage: invoked plain, it builds the sanitized .so (make native-asan
and friends produce the same artifact), locates the sanitizer runtime,
and re-execs itself under LD_PRELOAD with MISAKA_INTERP_SO pointing at
the instrumented build (utils/nativelib.py honors the override and
skips the staleness rebuild that would otherwise clobber it).  The
child then runs the scenario through the SHIPPED wrappers — the point
is to sanitize the production discipline, not a lookalike.

Usage (or `make sanitize-smoke` / `make sanitize-all`):
    python tools/sanitize_stress.py --sanitizer address [--seconds 6]
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # `python tools/...` puts tools/ first, not the repo
    sys.path.insert(0, REPO)

_SAN = {
    # sanitizer -> (cc flag, runtime lib, .so suffix, env var, env value)
    "address": ("-fsanitize=address", "libasan.so", "asan",
                # python itself "leaks" interned objects by design; the
                # lane polices the interpreter library, not CPython
                "ASAN_OPTIONS", "detect_leaks=0:abort_on_error=1"),
    "thread": ("-fsanitize=thread", "libtsan.so", "tsan",
               "TSAN_OPTIONS", "halt_on_error=1:second_deadlock_stack=1"),
    "undefined": ("-fsanitize=undefined -fno-sanitize-recover=all",
                  "libubsan.so", "ubsan",
                  "UBSAN_OPTIONS", "halt_on_error=1:print_stacktrace=1"),
}


def build_sanitized_so(kind: str) -> str:
    """Build native/libmisaka_interp.<kind>.so when missing or older
    than the source (mtime is fine for a local lane artifact — these
    are never shipped, unlike the hash-tagged default build).

    The Makefile's native-<kind> rule is the ONE flag definition (so
    `make native-asan` and this script cannot drift apart and test
    different binaries); the inline compile below is only the fallback
    for environments without make, mirroring SAN_CXXFLAGS."""
    flag, _, suffix, _, _ = _SAN[kind]
    src = os.path.join(REPO, "native", "interpreter.cpp")
    so = os.path.join(REPO, "native", f"libmisaka_interp.{suffix}.so")
    if os.path.exists(so) and os.path.getmtime(so) >= os.path.getmtime(src):
        return so
    print(f"# building {os.path.relpath(so, REPO)}", file=sys.stderr)
    made = subprocess.run(["make", "-C", REPO, f"native-{suffix}"],
                          capture_output=True)
    if made.returncode == 0 and os.path.exists(so):
        return so
    cxx = os.environ.get("CXX", "g++")
    cmd = [cxx, "-O1", "-g", "-fno-omit-frame-pointer", "-std=c++17",
           "-shared", "-fPIC", "-pthread", *flag.split(),
           "-Wall", "-Wextra", "-Werror", src, "-o", so]
    subprocess.run(cmd, check=True)
    return so


def build_sanitized_spec_so(kind: str) -> str | None:
    """An INSTRUMENTED per-program specialized build of the scenario's
    network (core/specialize.py with the sanitizer's flags via
    MISAKA_SPEC_CXXFLAGS): the specialized tick paths get the same
    sanitizer coverage as the generic ones.  Built in the parent so the
    child never runs g++ under the sanitizer's LD_PRELOAD."""
    import types

    flag, _, suffix, _, _ = _SAN[kind]
    code, prog_len = _tables()
    net = types.SimpleNamespace(
        code=code, prog_len=prog_len, num_stacks=1, stack_cap=16,
        in_cap=16, out_cap=16,
    )
    from misaka_tpu.core import specialize

    prev = os.environ.get("MISAKA_SPEC_CXXFLAGS")
    os.environ["MISAKA_SPEC_CXXFLAGS"] = (
        f"{flag} -O1 -g -fno-omit-frame-pointer"
    )
    try:
        so = specialize.build(
            net,
            cache_dir=os.path.join(REPO, "native", f".spec-{suffix}-cache"),
        )
    finally:
        if prev is None:
            os.environ.pop("MISAKA_SPEC_CXXFLAGS", None)
        else:
            os.environ["MISAKA_SPEC_CXXFLAGS"] = prev
    if so is None:
        print("sanitize: WARNING — instrumented specialized build failed; "
              "the lane runs without the specialized path", file=sys.stderr)
    return so


def reexec_under_sanitizer(kind: str, args) -> int:
    so = build_sanitized_so(kind)
    spec_so = build_sanitized_spec_so(kind)
    _, runtime, _, env_var, env_val = _SAN[kind]
    cxx = os.environ.get("CXX", "g++")
    lib = subprocess.run(
        [cxx, f"-print-file-name={runtime}"],
        check=True, capture_output=True, text=True,
    ).stdout.strip()
    if lib == runtime or not os.path.exists(lib):
        print(f"sanitize: {runtime} not found next to {cxx}; cannot run "
              f"the {kind} lane here", file=sys.stderr)
        return 0  # missing toolchain degrades like the native tier does
    env = dict(os.environ)
    env.update({
        "LD_PRELOAD": lib,
        env_var: env_val + ":" + env.get(env_var, ""),
        "MISAKA_INTERP_SO": so,
        "MISAKA_SANITIZE_CHILD": kind,
        **({"MISAKA_SANITIZE_SPEC_SO": spec_so} if spec_so else {}),
        # never touch (or wedge on) a TPU relay from a sanitizer lane
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
    })
    cmd = [sys.executable, os.path.abspath(__file__),
           "--sanitizer", kind, "--seconds", str(args.seconds),
           "--replicas", str(args.replicas),
           "--pool-threads", str(args.pool_threads),
           "--readers", str(args.readers)]
    print(f"# re-exec under {os.path.basename(lib)} "
          f"(MISAKA_INTERP_SO={os.path.relpath(so, REPO)})", file=sys.stderr)
    return subprocess.run(cmd, env=env).returncode


# --- the child scenario -----------------------------------------------------


def _tables():
    """One-lane IN; ADD 2; OUT — the minimal always-progressing serve
    program, built straight from the ISA tables (no parser dependency)."""
    import numpy as np

    from misaka_tpu.tis import isa

    code = np.zeros((1, 3, isa.NFIELDS), np.int32)
    code[0, 0, isa.F_OP] = isa.OP_IN          # IN  ACC
    code[0, 1, isa.F_OP] = isa.OP_ADD         # ADD 2
    code[0, 1, isa.F_SRC] = isa.SRC_IMM
    code[0, 1, isa.F_IMM] = 2
    code[0, 2, isa.F_OP] = isa.OP_OUT         # OUT ACC
    code[0, 2, isa.F_SRC] = isa.SRC_ACC
    return code, np.array([3], np.int32)


def _init_state(B: int, n: int, s: int, stack_cap: int, in_cap: int,
                out_cap: int):
    import numpy as np

    from misaka_tpu.tis import isa

    d = {
        "acc": np.zeros((B, n), np.int32),
        "bak": np.zeros((B, n), np.int32),
        "acc_hi": np.zeros((B, n), np.int32),
        "bak_hi": np.zeros((B, n), np.int32),
        "pc": np.zeros((B, n), np.int32),
        "port_val": np.zeros((B, n, isa.NUM_PORTS), np.int32),
        "port_full": np.zeros((B, n, isa.NUM_PORTS), np.uint8),
        "hold_val": np.zeros((B, n), np.int32),
        "holding": np.zeros((B, n), np.uint8),
        "stack_mem": np.zeros((B, s, stack_cap), np.int32),
        "stack_top": np.zeros((B, s), np.int32),
        "in_buf": np.zeros((B, in_cap), np.int32),
        "out_buf": np.zeros((B, out_cap), np.int32),
        "retired": np.zeros((B, n), np.int32),
    }
    for k in ("in_rd", "in_wr", "out_rd", "out_wr", "tick"):
        d[k] = np.zeros((B,), np.int32)
    return d


def run_scenario(args) -> int:
    import numpy as np

    from misaka_tpu.core import cinterp

    assert os.environ.get("MISAKA_INTERP_SO"), "child needs the override"
    if not cinterp.available():
        print("sanitize: instrumented interpreter failed to load",
              file=sys.stderr)
        return 1

    B, in_cap = args.replicas, 16
    code, prog_len = _tables()
    stop = threading.Event()
    serve_gate = threading.Event()   # set = serve thread may run
    serve_idle = threading.Event()   # set = serve thread parked at the gate
    serve_gate.set()
    errors: list[BaseException] = []
    stats = {"passes": 0, "values": 0, "reads": 0, "closed_reads": 0,
             "cycles": 0, "resident_passes": 0, "trace_reads": 0,
             "trace_records": 0}

    # Pool variants rotated across close/recreate cycles so every ladder
    # rung runs the concurrent serve/close/counter-read race under the
    # sanitizer: the AVX2 group path, the generic group fallback, the
    # scalar per-replica path (MISAKA_SIMD=0), and — when the parent
    # built one — the instrumented SPECIALIZED build's baked tick paths.
    spec_lib = None
    spec_path = os.environ.get("MISAKA_SANITIZE_SPEC_SO")
    if spec_path:
        spec_lib = cinterp.load_specialized(spec_path)
    variants = [(None, None), ("generic", None), ("0", None)]
    # the group/specialized paths only arm with at least one full SIMD
    # group of replicas (kGroupW = 8); below that every variant runs the
    # scalar engine and expecting `specialized` to engage would abort a
    # lane that is correctly degrading
    group_capable = B >= 8
    if spec_lib is not None and group_capable:
        variants.append((None, spec_lib))
    stats["spec_pools"] = 0

    def new_pool(variant: int):
        mode, lib = variants[variant % len(variants)]
        prev = os.environ.pop("MISAKA_SIMD", None)
        if mode is not None:
            os.environ["MISAKA_SIMD"] = mode
        try:
            pool = cinterp.NativePool(
                code, prog_len, 1, 16, in_cap, in_cap,
                replicas=B, threads=args.pool_threads, lib=lib,
            )
        finally:
            os.environ.pop("MISAKA_SIMD", None)
            if prev is not None:
                os.environ["MISAKA_SIMD"] = prev
        if lib is not None:
            assert pool.simd_info()["specialized"], \
                "specialized build did not engage"
            stats["spec_pools"] += 1
        return pool

    box = {"pool": new_pool(0)}
    rng = np.random.default_rng(7)

    def serve_loop():
        # The single serve caller (the device-loop contract); pauses at
        # the gate so close/recreate happens against a quiescent pool —
        # exactly the drain-to-quiescence discipline the engine uses.
        d = _init_state(B, 1, 1, 16, in_cap, in_cap)
        try:
            while not stop.is_set():
                if not serve_gate.is_set():
                    serve_idle.set()
                    serve_gate.wait(timeout=1.0)
                    d = _init_state(B, 1, 1, 16, in_cap, in_cap)
                    continue
                serve_idle.clear()
                pool = box["pool"]
                counts = rng.integers(0, 5, size=B).astype(np.int32)
                # extreme magnitudes drive the 64-bit wrap arithmetic
                # (UBSan's half of the lane); int32 wrap on the wire is
                # the spec, so expectations wrap with i32 semantics
                vals = np.zeros((B, in_cap), np.int32)
                for b in range(B):
                    vals[b, :counts[b]] = rng.choice(
                        [-2**31, -7, 0, 5, 2**31 - 1, 2**31 - 2],
                        size=counts[b],
                    ).astype(np.int32)
                # Alternate the r17 RESIDENT path with the stateless one:
                # import/serve_resident/export race the same scrape
                # readers (and drive the futex dispenser + masked group
                # ticks), and the export-under-load is exactly the
                # lifecycle path a checkpoint takes against a hot pool.
                resident = stats["passes"] % 2 == 1
                active = np.arange(min(2, B), dtype=np.int32)
                if resident:
                    if not pool.is_resident() and not pool.import_state(d):
                        raise AssertionError("resident import refused")
                    packed, progress = pool.serve_resident(vals, counts, 64)
                    assert progress.shape == (B,)
                    # masked partial-fill resident pass (group-mask path)
                    pool.serve_resident(
                        np.zeros((B, in_cap), np.int32),
                        np.zeros((B,), np.int32), 8, active=active,
                    )
                    d = pool.export_state()  # the lifecycle export
                    assert d is not None
                    stats["resident_passes"] += 1
                else:
                    if pool.is_resident():
                        pool.discard_resident()  # d carries the export
                    d, packed = pool.serve(d, vals, counts, ticks=64)
                    # partial-fill serial fast path (n<=4 runs on THIS
                    # thread): a second shape through the same superstep
                    d, _ = pool.serve(
                        d, np.zeros((B, in_cap), np.int32),
                        np.zeros((B,), np.int32), ticks=8, active=active,
                    )
                for b in range(B):
                    rd, wr = int(packed[b, 2]), int(packed[b, 3])
                    got = packed[b, 4:][(rd + np.arange(wr - rd)) % in_cap]
                    want = (vals[b, :counts[b]].astype(np.int64) + 2)
                    want = want.astype(np.uint64).astype(np.uint32)
                    # plain compare, NOT np.testing: numpy.testing's lazy
                    # first import spawns a subprocess (check_support_sve),
                    # and fork() under the TSan runtime deadlocks
                    if not np.array_equal(got.astype(np.uint32), want):
                        raise AssertionError(
                            f"replica {b} served wrong values: "
                            f"{got!r} != {want!r}"
                        )
                    stats["values"] += wr - rd
                stats["passes"] += 1
        except BaseException as e:  # noqa: BLE001 — surfaced at exit
            errors.append(e)
            stop.set()
        finally:
            serve_idle.set()

    def reader_loop():
        # Scrape-thread twin: hammers the counter read AND the r18
        # flight-recorder read API (ring snapshots, aggregate stats)
        # CONCURRENTLY with serve and with close/recreate — TSan over the
        # lock-free ring handshake (relaxed record stores + release
        # cursor / acquire reader) is the point of this lane, and the
        # torn-row discipline must hold while workers lap the reader.
        # "pool is closed" is the typed, expected outcome of losing the
        # close race; a UAF is what ASan/TSan are here to veto.
        try:
            ring = 0
            while not stop.is_set():
                pool = box["pool"]
                try:
                    c = pool.counters()
                    assert c["busy_ns"] >= 0 and c["idle_ns"] >= 0
                    pool.thread_counters()
                    info = pool.trace_info()
                    if info["rings"]:
                        recs, cursor, dropped = pool.trace_read(
                            ring % info["rings"]
                        )
                        # bounded rings: a snapshot never exceeds capacity
                        assert len(recs) <= info["capacity"], \
                            (len(recs), info["capacity"])
                        assert cursor >= len(recs) and dropped >= 0
                        s = pool.trace_stats()
                        assert s["serve_calls"] >= 0 and s["dropped"] >= 0
                        stats["trace_reads"] += 1
                        stats["trace_records"] += len(recs)
                    ring += 1
                    stats["reads"] += 1
                except RuntimeError:
                    stats["closed_reads"] += 1
                except ValueError:
                    stats["closed_reads"] += 1  # ring raced a recreate
        except BaseException as e:  # noqa: BLE001
            errors.append(e)
            stop.set()

    threads = [threading.Thread(target=serve_loop)]
    threads += [threading.Thread(target=reader_loop)
                for _ in range(args.readers)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + args.seconds
    try:
        while time.monotonic() < deadline and not stop.is_set():
            time.sleep(0.2)
            # the PR 7 shape: close while scrape threads are mid-hammer
            serve_gate.clear()
            if not serve_idle.wait(timeout=10):
                errors.append(RuntimeError("serve thread never quiesced"))
                break
            old = box["pool"]
            box["pool"] = new_pool(stats["cycles"] + 1)
            old.close()  # readers may hold `old` RIGHT NOW — the race
            stats["cycles"] += 1
            serve_gate.set()
    finally:
        stop.set()
        serve_gate.set()
        for t in threads:
            t.join(timeout=30)
        box["pool"].close()
    if errors:
        print(f"sanitize: scenario error: {errors[0]!r}", file=sys.stderr)
        return 1
    if not (stats["passes"] and stats["reads"] and stats["cycles"]
            and stats["resident_passes"] and stats["trace_reads"]):
        print(f"sanitize: scenario did not exercise the race: {stats}",
              file=sys.stderr)
        return 1
    print(f"# sanitize[{os.environ.get('MISAKA_SANITIZE_CHILD')}] green: "
          f"{stats['passes']} serve passes / {stats['values']} values "
          f"({stats['resident_passes']} resident), "
          f"{stats['reads']} counter reads "
          f"({stats['closed_reads']} typed closed-pool losses), "
          f"{stats['trace_reads']} ring snapshots / "
          f"{stats['trace_records']} records, "
          f"{stats['cycles']} close/recreate cycles "
          f"({stats['spec_pools']} specialized pools)", file=sys.stderr)
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--sanitizer", default="address",
                    choices=sorted(_SAN))
    ap.add_argument("--seconds", type=float, default=6.0)
    ap.add_argument("--replicas", type=int, default=64)
    ap.add_argument("--pool-threads", type=int, default=8)
    ap.add_argument("--readers", type=int, default=4)
    args = ap.parse_args()
    if os.environ.get("MISAKA_SANITIZE_CHILD"):
        return run_scenario(args)
    return reexec_under_sanitizer(args.sanitizer, args)


if __name__ == "__main__":
    sys.exit(main())
