"""`make usage-smoke`: the r12 observability plane proven end-to-end
against a REAL subprocess server (~15s).

Boots `python -m misaka_tpu.runtime.app` with the registry + SLO armed
(engine=native so the C++ pool serves), drives two tenants with mixed
native+Python load, then asserts the whole health plane through the
public HTTP surface:

  1. GET /debug/usage attributes nonzero CPU-seconds to BOTH tenants and
     the per-program sums land within 20% of the fused-pass wall total
     (the conservation contract; the tier-1 test pins 5%), with measured
     native-pool seconds nonzero;
  2. GET /debug/flamegraph shows a CPython frame aggregate (folded
     stacks with samples) AND the native pool's busy/idle split — mixed
     native+Python load in one view — and ?html=1 serves the viewer;
  3. GET /debug/alerts serves per-program SLO states (ok under healthy
     load) and GET /healthz carries the slo field; misaka_usage_* and
     misaka_slo_* series parse on /metrics.

Exit 0 on success, 1 with a reason on any failed assertion.  The same
assertions run inside tier-1 (tests/test_usage.py, tests/test_slo.py);
this is the standalone tripwire against the real process boundary.
"""

import json
import os
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ADD5 = "IN ACC\nADD 5\nOUT ACC\n"


def post(base, path, data=None, raw=None, timeout=60):
    body = raw if raw is not None else urllib.parse.urlencode(data or {}).encode()
    req = urllib.request.Request(base + path, data=body, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def get(base, path, timeout=30):
    try:
        with urllib.request.urlopen(base + path, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def wait_ready(base, deadline_s=120):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        try:
            status, _ = get(base, "/healthz", timeout=2)
            if status == 200:
                return True
        except OSError:
            pass
        time.sleep(0.25)
    return False


def fail(msg):
    print(f"# usage-smoke FAILED: {msg}", file=sys.stderr)
    sys.exit(1)


def main() -> int:
    import socket

    import numpy as np

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    tmp = tempfile.mkdtemp(prefix="misaka-usage-smoke-")
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "MISAKA_PORT": str(port),
        "MISAKA_BATCH": "16",
        "MISAKA_ENGINE": "native",  # the C++ pool: mixed native+Python load
        "MISAKA_AUTORUN": "1",
        "MISAKA_IN_CAP": "32",
        "MISAKA_OUT_CAP": "32",
        "MISAKA_STACK_CAP": "16",
        "MISAKA_PROGRAMS_DIR": os.path.join(tmp, "programs"),
        "MISAKA_SLO": "p99<5s,err<5%",  # healthy under any CI weather
        "NODE_INFO": json.dumps({"main": {"type": "program"}}),
        "MISAKA_PROGRAMS": json.dumps({"main": "IN ACC\nADD 2\nOUT ACC\n"}),
    }
    proc = subprocess.Popen([sys.executable, "-m", "misaka_tpu.runtime.app"],
                            env=env)
    base = f"http://127.0.0.1:{port}"
    try:
        if not wait_ready(base):
            fail("server did not come up")

        status, body = post(base, "/programs", {"name": "alpha",
                                                "program": ADD5})
        if status != 200:
            fail(f"upload alpha: {status} {body!r}")

        st, body = get(base, "/debug/usage")
        if st != 200:
            fail(f"/debug/usage before: {st}")
        before = json.loads(body)

        # --- mixed load: two tenants, raw lanes, concurrent threads ----
        errors = []

        def hammer(program, delta, n=25):
            vals = np.arange(64, dtype=np.int32)
            path = (f"/programs/{program}/compute_raw?spread=1" if program
                    else "/compute_raw?spread=1")
            for _ in range(n):
                st, out = post(base, path, raw=vals.astype("<i4").tobytes())
                if st != 200 or not np.array_equal(
                    np.frombuffer(out, "<i4"), vals + delta
                ):
                    errors.append((program, st, out[:80]))
                    return

        ts = [
            threading.Thread(target=hammer, args=("alpha", 5)),
            threading.Thread(target=hammer, args=(None, 2)),
            threading.Thread(target=hammer, args=("alpha", 5)),
            threading.Thread(target=hammer, args=(None, 2)),
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        if errors:
            fail(f"traffic errors: {errors[0]}")

        # --- 1. the usage ledger + conservation -------------------------
        st, body = get(base, "/debug/usage")
        if st != 200:
            fail(f"/debug/usage after: {st}")
        after = json.loads(body)
        deltas = {}
        for name, a in after["programs"].items():
            b = before["programs"].get(name, {})
            deltas[name] = {
                k: a[k] - b.get(k, 0) for k in a
            }
        for name in ("alpha", "default"):
            d = deltas.get(name)
            if not d or d["cpu_seconds"] <= 0:
                fail(f"no cpu attribution for {name!r}: {deltas}")
            if d["native_seconds"] <= 0:
                fail(f"no measured native attribution for {name!r}: {d}")
        cpu_sum = sum(d["cpu_seconds"] for d in deltas.values())
        pass_total = (after["pass_seconds_total"]
                      - before["pass_seconds_total"])
        if pass_total <= 0 or abs(cpu_sum - pass_total) > 0.2 * pass_total:
            fail(f"conservation: cpu {cpu_sum:.4f}s vs pass wall "
                 f"{pass_total:.4f}s (>20% apart)")
        if "native_pool" not in after:
            fail("no native_pool busy/idle split in /debug/usage")

        # --- 2. the flamegraph: CPython frames + the native split -------
        st, body = get(base, "/debug/flamegraph")
        if st != 200:
            fail(f"/debug/flamegraph: {st}")
        flame = json.loads(body)
        if flame.get("samples", 0) <= 0 or not flame.get("stacks"):
            fail(f"no CPython samples in the flamegraph: "
                 f"samples={flame.get('samples')}")
        # work_ns is the FIRST-CLASS total (r18): worker busy + the
        # caller-inline lane — on a 1-worker pool the r17 dispenser runs
        # every unit inline on the caller, so worker busy_ns alone is
        # legitimately 0 while caller_inline_ns carries the whole load
        np_flame = flame.get("native_pool") or {}
        if np_flame.get("work_ns", 0) <= 0:
            fail("flamegraph lacks the measured native busy/idle split")
        if "caller_inline_ns" not in np_flame:
            fail("native split lacks the caller-inline lane")
        if not any(";" in k for k in flame["stacks"]):
            fail("flamegraph folded stacks carry no frame chains")
        st, body = get(base, "/debug/flamegraph?html=1")
        if st != 200 or b"<script>" not in body:
            fail(f"flamegraph html viewer: {st}")

        # --- 3. SLO states + metric series -------------------------------
        st, body = get(base, "/debug/alerts")
        if st != 200:
            fail(f"/debug/alerts: {st}")
        alerts = json.loads(body)
        if not alerts["enabled"] or alerts["state"] != "ok":
            fail(f"alerts unhealthy under healthy load: {alerts['state']}")
        progs = alerts["programs"]
        if "alpha" not in progs:
            fail(f"no per-program SLO evaluation for alpha: {sorted(progs)}")
        st, body = get(base, "/healthz")
        health = json.loads(body)
        if health.get("slo") != "ok" or health.get("degraded"):
            fail(f"/healthz slo integration: {health}")
        st, body = get(base, "/metrics")
        from misaka_tpu.utils import metrics as umetrics

        parsed = umetrics.parse_text(body.decode())
        for needle in ("misaka_usage_cpu_seconds_total",
                       "misaka_usage_native_seconds_total",
                       "misaka_slo_state", "misaka_build_info",
                       "misaka_serve_pass_wall_seconds_total"):
            if not any(k.startswith(needle) for k in parsed):
                fail(f"missing metric family {needle}")

        print(json.dumps({
            "usage_smoke": "ok",
            "programs": sorted(deltas),
            "cpu_seconds_sum": round(cpu_sum, 4),
            "pass_seconds_total": round(pass_total, 4),
            "conservation": round(cpu_sum / pass_total, 4),
            "native_work_ns": flame["native_pool"]["work_ns"],
            "native_caller_inline_ns":
                flame["native_pool"]["caller_inline_ns"],
            "flamegraph_samples": flame["samples"],
            "slo_state": alerts["state"],
        }))
        return 0
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()


if __name__ == "__main__":
    sys.exit(main())
