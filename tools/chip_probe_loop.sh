#!/usr/bin/env bash
# Background chip-health prober: appends one line per probe to .chipprobe.log.
# On the first UP it optionally fires the one-shot evidence capture
# (MISAKA_PROBE_AUTOCAPTURE=1) — a wedge-prone chip's up-windows can be
# short, so evidence collection must not wait on a human noticing the log —
# then EXITS (so it never contends with anything that follows).
# Skips a probe while any misaka/bench process is alive — a probe holding the
# relayed chip for up to 120s would stall a real bench toward its watchdog,
# and probing while bench holds the chip would log a false DOWN.
LOG=/root/repo/.chipprobe.log
while true; do
  ts=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  busy=""
  # only PYTHON processes count: supervisor/tool shells legitimately carry
  # these strings inside longer command lines (same rule as bench _preflight)
  for pid in $(pgrep -f 'misaka_tpu|bench\.py|tpu_capture' 2>/dev/null); do
    case "$(cat /proc/"$pid"/comm 2>/dev/null)" in python*) busy=$pid ;; esac
  done
  # a capture run holds the chip end-to-end via its lockfile (covers heredoc
  # steps whose cmdline carries no misaka marker); honor locks < 2h old
  LOCKF=/root/repo/.tpu_capture_active
  if [ -z "$busy" ] && [ -f "$LOCKF" ]; then
    now=$(date -u +%s); stamp=$(cat "$LOCKF" 2>/dev/null || echo 0)
    [ $((now - stamp)) -lt 7200 ] && busy="capture-lock"
  fi
  if [ -n "$busy" ]; then
    echo "$ts SKIP (python misaka/bench pid $busy alive)" >> "$LOG"
  else
    out=$(timeout 120 python /root/repo/tools/chip_probe.py 2>&1)
    rc=$?
    if [ $rc -eq 0 ] && echo "$out" | grep -q "^OK tpu"; then
      echo "$ts UP $out" >> "$LOG"
      if [ "${MISAKA_PROBE_AUTOCAPTURE:-}" = "1" ]; then
        echo "$ts AUTOCAPTURE starting (tools/tpu_capture.sh)" >> "$LOG"
        bash /root/repo/tools/tpu_capture.sh /tmp/tpu_capture_auto \
          >> "$LOG" 2>&1
        cap_rc=$?
        echo "$(date -u +%Y-%m-%dT%H:%M:%SZ) AUTOCAPTURE done rc=$cap_rc" >> "$LOG"
        if [ "$cap_rc" -ne 0 ]; then
          # the chip flapped before the capture's own probe (or a step was
          # killed): keep hunting for the next up-window instead of ending
          # the watch with no evidence
          sleep 600
          continue
        fi
      fi
      exit 0
    fi
    echo "$ts DOWN rc=$rc $(echo "$out" | tail -1)" >> "$LOG"
  fi
  sleep 600
done
