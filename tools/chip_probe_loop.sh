#!/usr/bin/env bash
# Background chip-health prober: appends one line per probe to .chipprobe.log
# and EXITS after the first UP (so it never contends with a capture run).
# Skips a probe while any misaka/bench process is alive — a probe holding the
# relayed chip for up to 120s would stall a real bench toward its watchdog,
# and probing while bench holds the chip would log a false DOWN.
LOG=/root/repo/.chipprobe.log
while true; do
  ts=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  if pgrep -f 'misaka_tpu|bench\.py|tpu_capture' >/dev/null 2>&1; then
    echo "$ts SKIP (misaka/bench process alive)" >> "$LOG"
  else
    out=$(timeout 120 python /root/repo/tools/chip_probe.py 2>&1)
    rc=$?
    if [ $rc -eq 0 ] && echo "$out" | grep -q "^OK tpu"; then
      echo "$ts UP $out" >> "$LOG"
      exit 0
    fi
    echo "$ts DOWN rc=$rc $(echo "$out" | tail -1)" >> "$LOG"
  fi
  sleep 600
done
