#!/bin/bash
# One-shot TPU evidence capture, for the moment the (wedge-prone) relayed
# chip is reachable: fused-kernel parity lane, the full default bench, and
# the roofline sweep — in risk order, each logged, so a mid-sequence wedge
# keeps everything already captured.  Usage: bash tools/tpu_capture.sh [outdir]
set -u
cd "$(dirname "$0")/.."
OUT="${1:-/tmp/tpu_capture}"
mkdir -p "$OUT"

echo "== 0. chip probe =="
timeout 120 python -c "import jax; print(jax.devices()[0].platform)" 2>&1 | tail -1 | tee "$OUT/probe.log"
grep -qi "^tpu$" "$OUT/probe.log" || { echo "chip unreachable; aborting"; exit 3; }

echo "== 1. fused-kernel parity lane (make test-tpu) =="
timeout 1200 make test-tpu 2>&1 | tail -3 | tee "$OUT/test_tpu.log"

echo "== 2. full default bench =="
timeout 1300 python bench.py > "$OUT/bench.json.log" 2> "$OUT/bench.stderr.log"
echo "rc=$?" >> "$OUT/bench.stderr.log"
tail -1 "$OUT/bench.json.log"

echo "== 3. roofline sweep =="
timeout 1300 python bench.py --roofline > "$OUT/roofline.json.log" 2> "$OUT/roofline.stderr.log"
echo "rc=$?" >> "$OUT/roofline.stderr.log"
tail -1 "$OUT/roofline.json.log"

echo "captured under $OUT"
