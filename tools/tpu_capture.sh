#!/bin/bash
# One-shot TPU evidence capture, for the moment the (wedge-prone) relayed
# chip is reachable.  Ordered by EVIDENCE VALUE PER MINUTE under the
# assumption the up-window may be short and a mid-sequence wedge ends it:
#   1. full default bench  — the headline + served + latency + elide A/B +
#      lane matrix (its own risky sections already run last, per-config
#      fault-isolated)
#   2. hardware test lane  — Mosaic-compiled parity incl. elide + walk
#   3. roofline sweep      — batch-axis character of a number step 1 proved
# Each step is logged separately so whatever completed survives.
# Usage: bash tools/tpu_capture.sh [outdir]
set -u
cd "$(dirname "$0")/.."
OUT="${1:-/tmp/tpu_capture}"
mkdir -p "$OUT"

# Hold the chip for the whole capture: the background prober
# (tools/chip_probe_loop.sh) skips while this lockfile is fresh, so a probe
# can never contend with (and potentially wedge) a capture step — including
# the heredoc steps whose cmdline carries no misaka marker.
LOCK=.tpu_capture_active
date -u +%s > "$LOCK"
trap 'rm -f "$LOCK"' EXIT

# An inherited MISAKA_FUSED_ELIDE_HI=1 would make bench.py's default elide
# A/B silently skip (its guard assumes the flag means "already elided") —
# clear it so step 1 always measures the A/B.
unset MISAKA_FUSED_ELIDE_HI

echo "== 0. chip probe =="
timeout 120 python -c "import jax; print(jax.devices()[0].platform)" 2>&1 | tail -1 | tee "$OUT/probe.log"
grep -qi "^tpu$" "$OUT/probe.log" || { echo "chip unreachable; aborting"; exit 3; }

echo "== 1. full default bench (headline, served, latency, elide A/B, lanes) =="
timeout 1400 python bench.py > "$OUT/bench.json.log" 2> "$OUT/bench.stderr.log"
echo "rc=$?" >> "$OUT/bench.stderr.log"
tail -1 "$OUT/bench.json.log"

echo "== 2. fused-kernel parity lane (make test-tpu) =="
timeout 1200 make test-tpu 2>&1 | tail -3 | tee "$OUT/test_tpu.log"

echo "== 3. roofline sweep =="
timeout 1300 python bench.py --roofline > "$OUT/roofline.json.log" 2> "$OUT/roofline.stderr.log"
echo "rc=$?" >> "$OUT/roofline.stderr.log"
tail -1 "$OUT/roofline.json.log"

echo "captured under $OUT"
