#!/bin/bash
# One-shot TPU evidence capture, for the moment the (wedge-prone) relayed
# chip is reachable: fused-kernel parity lane, the full default bench, and
# the roofline sweep — in risk order, each logged, so a mid-sequence wedge
# keeps everything already captured.  Usage: bash tools/tpu_capture.sh [outdir]
set -u
cd "$(dirname "$0")/.."
OUT="${1:-/tmp/tpu_capture}"
mkdir -p "$OUT"

# Hold the chip for the whole capture: the background prober
# (tools/chip_probe_loop.sh) skips while this lockfile is fresh, so a probe
# can never contend with (and potentially wedge) a capture step — including
# the heredoc steps whose cmdline carries no misaka marker.
LOCK=.tpu_capture_active
date -u +%s > "$LOCK"
trap 'rm -f "$LOCK"' EXIT

echo "== 0. chip probe =="
timeout 120 python -c "import jax; print(jax.devices()[0].platform)" 2>&1 | tail -1 | tee "$OUT/probe.log"
grep -qi "^tpu$" "$OUT/probe.log" || { echo "chip unreachable; aborting"; exit 3; }

echo "== 1. fused-kernel parity lane (make test-tpu) =="
timeout 1200 make test-tpu 2>&1 | tail -3 | tee "$OUT/test_tpu.log"

echo "== 2. full default bench =="
timeout 1300 python bench.py > "$OUT/bench.json.log" 2> "$OUT/bench.stderr.log"
echo "rc=$?" >> "$OUT/bench.stderr.log"
tail -1 "$OUT/bench.json.log"

echo "== 3. roofline sweep =="
timeout 1300 python bench.py --roofline > "$OUT/roofline.json.log" 2> "$OUT/roofline.stderr.log"
echo "rc=$?" >> "$OUT/roofline.stderr.log"
tail -1 "$OUT/roofline.json.log"

echo "== 4. hi-plane elision A/B (the r5 cut at the named 4x VPU headroom) =="
timeout 900 python - > "$OUT/elide_ab.json.log" 2> "$OUT/elide_ab.stderr.log" <<'PY'
import json
import os

import bench

# an inherited MISAKA_FUSED_ELIDE_HI=1 would silently turn this into
# elide-vs-elide with speedup 1.0 — pin the baseline to OFF explicitly
os.environ["MISAKA_FUSED_ELIDE_HI"] = "0"
base = bench.bench_config("add2", batch=262144)
os.environ["MISAKA_FUSED_ELIDE_HI"] = "1"
el = bench.bench_config("add2", batch=262144)
print(json.dumps({
    "metric": "add2_elide_hi_ab",
    "baseline_ticks_per_sec": round(base["ticks_per_sec"], 1),
    "elide_ticks_per_sec": round(el["ticks_per_sec"], 1),
    "baseline_throughput": round(base["throughput"], 1),
    "elide_throughput": round(el["throughput"], 1),
    "speedup": round(el["ticks_per_sec"] / base["ticks_per_sec"], 4),
}))
PY
echo "rc=$?" >> "$OUT/elide_ab.stderr.log"
tail -1 "$OUT/elide_ab.json.log"

echo "captured under $OUT"
