"""`make telemetry-smoke`: the durable telemetry plane proven end-to-end
against a REAL subprocess server (~30s).

Boots `python -m misaka_tpu.runtime.app` with MISAKA_TSDB_DIR armed at
test cadence, then walks the whole ISSUE-20 surface through the public
process boundary:

  1. the capture spool rotates >= 2 on-disk segments (one forced via
     POST /captures/rotate, one by the size trigger) and /debug/captures
     reports the spool armed;
  2. kill -9 + relaunch over the same directory: GET /debug/series
     answers with points measured BEFORE the restart (the 7d window
     grammar included) — the boot-time reload, not a checkpoint;
  3. `python -m misaka_tpu usage-report` (the CLI, not the route) shows
     cumulative totals monotone vs the pre-kill export and conserving
     against the pass-wall anchor within 20% (tier-1 pins 5%);
  4. a segment rotated before the kill replays byte-for-byte green
     through `python -m misaka_tpu replay`.

Exit 0 on success, 1 with a reason on any failed assertion.  The same
assertions run inside tier-1 (tests/test_durable.py); this is the
standalone tripwire against the real process boundary.
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def get(base, path, timeout=30):
    try:
        with urllib.request.urlopen(base + path, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def post(base, path, timeout=30):
    req = urllib.request.Request(base + path, data=b"", method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def wait_ready(base, deadline_s=120):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        try:
            status, body = get(base, "/healthz", timeout=2)
            if status == 200 and json.loads(body).get("ok"):
                return True
        except OSError:
            pass
        time.sleep(0.25)
    return False


def fail(msg):
    print(f"# telemetry-smoke FAILED: {msg}", file=sys.stderr)
    sys.exit(1)


def main() -> int:
    import socket

    import numpy as np

    from misaka_tpu.client import MisakaClient

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    base = f"http://127.0.0.1:{port}"
    tmp = tempfile.mkdtemp(prefix="misaka-telemetry-smoke-")
    env = {k: v for k, v in os.environ.items() if not k.startswith("JAX")}
    env.update(
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        MISAKA_PORT=str(port),
        MISAKA_TTL_S="600",
        MISAKA_AUTORUN="1",
        MISAKA_CANARY="0",  # deterministic history for the replay leg
        MISAKA_TSDB_DIR=os.path.join(tmp, "telemetry"),
        MISAKA_TSDB_INTERVAL_S="0.25",
        MISAKA_USAGE_FLUSH_S="0.5",
        MISAKA_CAPTURE_SEG_S="9999",
        MISAKA_CAPTURE_SEG_KB="16",  # small: traffic trips the size trigger
        NODE_INFO=json.dumps({"solo": {"type": "program"}}),
        MISAKA_PROGRAMS=json.dumps({"solo": "IN ACC\nADD 1\nOUT ACC\n"}),
        PYTHONPATH=ROOT,
    )
    launch = [sys.executable, "-m", "misaka_tpu.runtime.app"]
    procs = []
    client = None
    try:
        proc = subprocess.Popen(launch, env=env)
        procs.append(proc)
        if not wait_ready(base):
            fail("server never became healthy")
        client = MisakaClient(base, timeout=60)
        vals = np.arange(16, dtype=np.int32)
        for _ in range(30):
            out = client.compute_raw(vals)
            if not np.array_equal(out, vals + 1):
                fail("compute parity broken")
        # one deterministic cut now (this is the replay comparand) ...
        status, body = post(base, "/captures/rotate")
        if status != 200:
            fail(f"/captures/rotate -> {status}: {body[:200]}")
        rotated = json.loads(body)
        if not rotated.get("records"):
            fail(f"rotation produced no records: {rotated}")
        segment = rotated["path"]
        # ... then more traffic so the 16 KiB size trigger rotates again
        for _ in range(80):
            client.compute_raw(vals)
        deadline = time.monotonic() + 20
        spool = {}
        while time.monotonic() < deadline:
            _, body = get(base, "/debug/captures")
            spool = json.loads(body).get("spool") or {}
            if spool.get("segments", 0) >= 2:
                break
            time.sleep(0.5)
        if spool.get("segments", 0) < 2:
            fail(f"spool never reached 2 segments: {spool}")
        print(f"# spooled {spool['segments']} capture segment(s), "
              f"{spool['rotations']} rotation(s)")
        time.sleep(1.5)  # flush ticks: usage + finalized TSDB slots
        report1 = subprocess.run(
            [sys.executable, "-m", "misaka_tpu", "usage-report",
             "--url", base],
            env=env, cwd=ROOT, capture_output=True, text=True, timeout=120,
        )
        if report1.returncode != 0:
            fail(f"usage-report (pre-kill): {report1.stderr[:400]}")
        totals1 = json.loads(report1.stdout)
        if totals1["pass_wall_seconds"] <= 0:
            fail(f"no pass-wall accrued: {totals1}")
        client.close()
        client = None

        t_kill = time.time()
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
        print("# killed -9; relaunching over the same spool directory")
        proc2 = subprocess.Popen(launch, env=env)
        procs.append(proc2)
        if not wait_ready(base):
            fail("relaunched server never became healthy")
        client = MisakaClient(base, timeout=60)

        # series history spans the restart, through the day grammar too
        for window in ("15m", "7d"):
            got = client.series("misaka_compute_values_total", window=window)
            pts = [p for row in got["series"] for p in row["points"]]
            if not any(p[0] < t_kill for p in pts):
                fail(f"window={window}: no pre-restart points ({len(pts)} "
                     f"points)")
        print("# /debug/series spans the restart (15m + 7d windows)")

        for _ in range(10):
            client.compute_raw(vals)
        time.sleep(1.2)
        report2 = subprocess.run(
            [sys.executable, "-m", "misaka_tpu", "usage-report",
             "--url", base],
            env=env, cwd=ROOT, capture_output=True, text=True, timeout=120,
        )
        if report2.returncode != 0:
            fail(f"usage-report (post-restart): {report2.stderr[:400]}")
        totals2 = json.loads(report2.stdout)
        for prog, row in totals1["cumulative"].items():
            after = totals2["cumulative"].get(prog)
            if after is None:
                fail(f"tenant {prog} vanished across the restart")
            for f, v in row.items():
                if after[f] < v - 1e-6:
                    fail(f"{prog}.{f} went backwards: {v} -> {after[f]}")
        wall = totals2["pass_wall_seconds"]
        cpu = totals2["cpu_seconds_total"]
        if abs(wall - cpu) > 0.20 * max(wall, cpu):
            fail(f"conservation broken: pass_wall={wall} cpu_total={cpu}")
        print(f"# usage-report monotone across restart; conservation "
              f"pass_wall={wall:.3f}s cpu_total={cpu:.3f}s")
        client.close()
        client = None

        replay = subprocess.run(
            [sys.executable, "-m", "misaka_tpu", "replay", segment],
            env=env, cwd=ROOT, capture_output=True, text=True, timeout=300,
        )
        out = replay.stdout + replay.stderr
        if replay.returncode != 0 or "green" not in out:
            fail(f"replay of pre-kill segment not green: {out[:800]}")
        print("# pre-kill rotated segment replays byte-for-byte green")
        print("# telemetry-smoke OK")
        return 0
    finally:
        if client is not None:
            client.close()
        for p in procs:
            if p.poll() is None:
                p.kill()


if __name__ == "__main__":
    sys.exit(main())
