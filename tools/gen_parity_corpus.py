"""Generate the Go-parity corpus: serialized networks + recorded engine outputs.

Each corpus case is one JSON file under tests/corpus/parity/:

  {"name", "node_info", "programs", "inputs", "engine_outputs", "compare"}

`engine_outputs` is what THIS rebuild's engine produced (recorded at
generation time, re-verified by tests/test_parity_corpus.py on every run);
`compare` is "stream" (deterministic Kahn networks: exact output order) or
"multiset" (contended networks: order is schedule-dependent, the multiset is
not).  tools/parity_go.py replays the same cases against the actual Go
reference binary via its own Dockerfile/compose deployment — the check
SURVEY.md §4 promises, runnable the moment an environment has Docker.

Cases are restricted to 1-output-per-input topologies because the replay
feeds the reference through serialized POST /compute (master.go:197-224),
where pairing is unambiguous only at one output per input.

Usage: python tools/gen_parity_corpus.py  (writes tests/corpus/parity/)
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"))

import numpy as np

OUT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tests", "corpus", "parity",
)
N_INPUTS = 8


def main():
    from tests.test_cross_mode import gen_contended, gen_network, run_engine

    os.makedirs(OUT_DIR, exist_ok=True)
    cases = []

    # deterministic Kahn networks: exact stream equality (only 1:1 cadences)
    picked = 0
    seed = 0
    while picked < 8 and seed < 200:
        node_info, programs, outs_per_input = gen_network(seed)
        if outs_per_input == 1:
            cases.append((f"kahn_{seed:03d}", node_info, programs, "stream", 1000 + seed))
            picked += 1
        seed += 1

    # contended networks: multiset equality (1 value out per input)
    for seed in range(4):
        node_info, programs, _k = gen_contended(seed)
        cases.append((f"contended_{seed:03d}", node_info, programs, "multiset", 2000 + seed))

    # the flagship compose network itself
    from misaka_tpu import networks

    add2 = networks.add2()
    cases.append(("add2", add2.node_info, add2.programs, "stream", 42))

    for name, node_info, programs, compare, in_seed in cases:
        node_info = {
            n: (k if isinstance(k, str) else k["type"]) for n, k in node_info.items()
        }
        inputs = np.random.default_rng(in_seed).integers(
            -100, 100, size=N_INPUTS
        ).tolist()
        outs = run_engine(node_info, programs, inputs)
        assert len(outs) == len(inputs), (name, len(outs))
        path = os.path.join(OUT_DIR, f"{name}.json")
        with open(path, "w") as f:
            json.dump(
                {
                    "name": name,
                    "node_info": node_info,
                    "programs": programs,
                    "inputs": inputs,
                    "engine_outputs": outs,
                    "compare": compare,
                },
                f, indent=1,
            )
        print(f"wrote {path}: {len(inputs)} inputs, compare={compare}")


if __name__ == "__main__":
    main()
