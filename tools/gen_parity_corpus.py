"""Generate the Go-parity corpus: serialized networks + recorded engine outputs.

Each corpus case is one JSON file under tests/corpus/parity/:

  {"name", "node_info", "programs", "inputs", "engine_outputs", "compare"}

`engine_outputs` is what THIS rebuild's engine produced (recorded at
generation time, re-verified by tests/test_parity_corpus.py on every run);
`compare` is "stream" (deterministic Kahn networks: exact output order) or
"multiset" (contended networks: order is schedule-dependent, the multiset is
not).  tools/parity_go.py replays the same cases against the actual Go
reference binary via its own Dockerfile/compose deployment — the check
SURVEY.md §4 promises, runnable the moment an environment has Docker.

Cases are restricted to 1-output-per-input topologies because the replay
feeds the reference through serialized POST /compute (master.go:197-224),
where pairing is unambiguous only at one output per input.

Usage: python tools/gen_parity_corpus.py  (writes tests/corpus/parity/)
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"))

import numpy as np

OUT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tests", "corpus", "parity",
)
N_INPUTS = 8


# Hand-written ISA-edge cases (VERDICT r4 item 9): 64-bit register overflow,
# JRO clamping on both edges and through ACC, deep + 64-bit stack traffic,
# and full grammar-form coverage (every MOV/ADD/SUB/JRO/PUSH/POP/OUT form
# plus all five jumps appears in at least one case).  All are deterministic
# single-lane-cadence networks: exact stream compare, 1 output per input —
# replayable against the real Go binary through serialized POST /compute.
HAND_CASES = [
    (
        # 64-bit registers OBSERVED through a branch: acc accumulates to
        # 4e9 (int32-safe imms only — literals past int32 are a documented
        # lowering divergence, lower.py:21-27).  64-bit JGZ sees +4e9 and
        # takes BIG -> outputs x; an int32-only engine sees the wrapped
        # NEGATIVE lo (-294967296), skips the branch, and outputs -1.
        "regs64_jgz_overflow",
        {"p": "program"},
        {"p": "START: IN ACC\nSAV\nMOV 2000000000, ACC\nADD 2000000000\n"
              "JGZ BIG\nOUT -1\nJMP START\nBIG: SWP\nOUT ACC\nJMP START\n"},
    ),
    (
        # negative side: acc reaches -4e9 via NEG+SUB; 64-bit JLZ taken
        # (outputs x), int32 lo is +294967296 so a broken engine outputs -1
        "regs64_jlz_overflow",
        {"p": "program"},
        {"p": "START: IN ACC\nSAV\nMOV 2000000000, ACC\nNEG\n"
              "SUB 2000000000\nJLZ NEGB\nOUT -1\nJMP START\n"
              "NEGB: SWP\nOUT ACC\nJMP START\n"},
    ),
    (
        # acc = 2^32 exactly (hi=1, lo=0): 64-bit JEZ must NOT fire on a
        # zero lo word alone (is_zero checks both planes, regs64.py)
        "regs64_jez_2pow32",
        {"p": "program"},
        {"p": "START: IN ACC\nSAV\nMOV 2000000000, ACC\nADD 2000000000\n"
              "ADD 294967296\nJEZ BAD\nSWP\nOUT ACC\nJMP START\n"
              "BAD: OUT -1\nJMP START\n"},
    ),
    (
        # the int32 WIRE boundary on stacks: pushing an overflowed acc
        # (4e9) truncates to -294967296 on the wire (messenger.proto int32,
        # exactly like the reference's gRPC hop to its stack process), so
        # the popped value is negative — JLZ observes the truncation
        "push_wire_truncation",
        {"p": "program", "s": "stack"},
        {"p": "START: IN ACC\nSAV\nMOV 2000000000, ACC\nADD 2000000000\n"
              "PUSH ACC, s\nPOP s, ACC\nJLZ TR\nOUT -1\nJMP START\n"
              "TR: SWP\nOUT ACC\nJMP START\n"},
    ),
    (
        # JRO +100 clamps to the LAST instruction (program.go:354); the
        # skipped SUB would corrupt the value if the clamp missed.  NO
        # trailing newline: the trailing-NOP quirk (strings.Split parity)
        # would otherwise BE the last slot and swallow the OUT
        "jro_clamp_forward",
        {"p": "program"},
        {"p": "IN ACC\nADD 7\nJRO 100\nSUB 1000\nNOP\nOUT ACC"},
    ),
    (
        # JRO -100 clamps to instruction 0: the loop-back edge
        "jro_clamp_backward",
        {"p": "program"},
        {"p": "IN ACC\nADD 3\nOUT ACC\nJRO -100\n"},
    ),
    (
        # JRO ACC (register form): |x|+3 >= 3 always over-jumps past the
        # trap lines and clamps onto the final OUT (no trailing newline —
        # see jro_clamp_forward); covers JGZ + NEG too
        "jro_acc_clamp",
        {"p": "program"},
        {"p": "START: IN ACC\nJGZ P\nNEG\nP: ADD 3\nJRO ACC\nOUT 999\n"
              "JMP START\nOUT ACC"},
    ),
    (
        # sign classifier: JEZ/JGZ/JMP + SWP + OUT with immediate
        "branch_sign",
        {"p": "program"},
        {"p": "START: IN ACC\nJEZ ZERO\nJGZ POS\nOUT -111\nJMP START\n"
              "ZERO: SWP\nSWP\nOUT 0\nJMP START\nPOS: OUT 111\nJMP START\n"},
    ),
    (
        # JNZ never taken (ACC forced to 0 by SUB ACC), SAV/SWP restore
        "jnz_sav_swp",
        {"p": "program"},
        {"p": "IN ACC\nSAV\nSUB ACC\nJNZ NEVER\nSWP\nNEVER: OUT ACC\n"},
    ),
    (
        # 24-deep per-input stack excursion (LIFO through the HBM plane;
        # int32-safe imms — wire truncation is push_wire_truncation's job)
        "deep_stack_24",
        {"p": "program", "s": "stack"},
        {"p": "IN ACC\n"
              + "PUSH ACC, s\n" * 23
              + "PUSH 1000000000, s\nPOP s, ACC\nSUB 999999958\n"
              + "POP s, NIL\n" * 22
              + "POP s, ACC\nOUT ACC\n"},
    ),
    (
        # two-lane port traffic: MOV imm->port, MOV ACC->port, MOV port->ACC,
        # ADD ACC (doubling), ADD R1, SUB NIL
        "ports_all_mov_forms",
        {"a": "program", "b": "program"},
        {
            "a": "IN ACC\nMOV ACC, b:R0\nMOV 7, b:R1\n",
            "b": "MOV 5, NIL\nMOV R0, ACC\nADD ACC\nADD R1\nSUB NIL\nOUT ACC\n",
        },
    ),
]


def main():
    from tests.test_cross_mode import gen_contended, gen_network, run_engine

    os.makedirs(OUT_DIR, exist_ok=True)
    cases = []

    # deterministic Kahn networks: exact stream equality (only 1:1 cadences)
    picked = 0
    seed = 0
    while picked < 8 and seed < 200:
        node_info, programs, outs_per_input = gen_network(seed)
        if outs_per_input == 1:
            cases.append((f"kahn_{seed:03d}", node_info, programs, "stream", 1000 + seed))
            picked += 1
        seed += 1

    # contended networks: multiset equality (1 value out per input)
    for seed in range(4):
        node_info, programs, _k = gen_contended(seed)
        cases.append((f"contended_{seed:03d}", node_info, programs, "multiset", 2000 + seed))

    # the flagship compose network itself
    from misaka_tpu import networks

    add2 = networks.add2()
    cases.append(("add2", add2.node_info, add2.programs, "stream", 42))

    for name, node_info, programs in HAND_CASES:
        cases.append((name, node_info, programs, "stream", 7000 + len(name)))

    for name, node_info, programs, compare, in_seed in cases:
        node_info = {
            n: (k if isinstance(k, str) else k["type"]) for n, k in node_info.items()
        }
        inputs = np.random.default_rng(in_seed).integers(
            -100, 100, size=N_INPUTS
        ).tolist()
        outs = run_engine(node_info, programs, inputs)
        assert len(outs) == len(inputs), (name, len(outs))
        path = os.path.join(OUT_DIR, f"{name}.json")
        with open(path, "w") as f:
            json.dump(
                {
                    "name": name,
                    "node_info": node_info,
                    "programs": programs,
                    "inputs": inputs,
                    "engine_outputs": outs,
                    "compare": compare,
                },
                f, indent=1,
            )
        print(f"wrote {path}: {len(inputs)} inputs, compare={compare}")


if __name__ == "__main__":
    main()
