"""Benchmark harness: /compute throughput on the current JAX platform.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "inputs/sec", "vs_baseline": N}

The metric is BASELINE.json's headline: values computed per second through
the docker-compose "add-2" network with output parity against the Go
interpreter.  The reference publishes no numbers (BASELINE.md); vs_baseline
is measured against the driver's north-star target of 1e6 inputs/sec.

The DEFAULT run measures, beyond the headline kernel number: served
throughput through the real HTTP surface (raw + text), single-value
latency (engine floor and HTTP p50/p99), lane-scaling ticks/s at
8/64/256 lanes, and the model-parallel engine on a virtual 8-device
mesh — so the driver's artifact tracks every engine every round.
`python bench.py --all` additionally measures every BASELINE config
(add2, acc_loop, ring4, sorter, mesh8) in a "configs" field; the
headline metric stays add2.  `--roofline` appends the add2 batch sweep
behind ARCHITECTURE.md's perf model.

Method: B independent network instances run in lockstep (vmap batch axis);
each instance's input ring is preloaded with Q values, and we time jitted
scan chunks until every instance has emitted all Q outputs.  Outputs are
verified against the config's expected function before the number is
reported — a fast-but-wrong kernel prints nothing.
"""

import json
import os
import sys
import time

import numpy as np

NORTH_STAR = 1_000_000.0  # BASELINE.json north_star target, inputs/sec

# Process start: attach-retry re-execs inherit what REMAINS of the
# whole-run TTL, not a fresh budget (the TTL is a promise to the driver).
_T0 = time.monotonic()

# Filled incrementally by main(); the TTL watchdog dumps it so a mid-run
# device wedge (a hung dispatch cannot be interrupted from Python) still
# leaves every already-measured number in the driver's artifact.
_PAYLOAD = {}


def _arm_ttl(environ=os.environ):
    """Hard deadline for the whole bench (MISAKA_BENCH_TTL_S, default 1140s).

    Covers backend init too: a leaked server wedges the single-client TPU
    relay and `jax.devices()` then hangs forever (VERDICT r3 weak #1) — the
    watchdog turns that into a fast, diagnosable rc=3 instead of eating the
    driver's whole budget.  Whatever sections already completed are printed
    as a partial payload before exiting.
    """
    import threading

    ttl = float(environ.get("MISAKA_BENCH_TTL_S", "1140") or 0)
    if not ttl:
        return

    def boom():
        print(
            f"# bench TTL {ttl:g}s exceeded — aborting (if backend init hung, "
            "check for leaked servers: make stop)",
            file=sys.stderr, flush=True,
        )
        try:
            # Snapshot first: the main thread may be mutating _PAYLOAD at
            # the deadline, and a dump failure must never skip the exit.
            snap = dict(_PAYLOAD)
            if snap:
                snap["partial"] = True
                print(json.dumps(snap), flush=True)
        except Exception:
            pass
        os._exit(3)

    t = threading.Timer(ttl, boom)
    t.daemon = True
    t.start()


def _arm_init_watchdog(environ=os.environ):
    """Separate, SHORTER deadline for backend init (MISAKA_INIT_TTL_S,
    default 240s): a wedged TPU worker (r4: a bad kernel config can wedge
    the remote worker for an hour+ with no local recovery) makes
    jax.devices() hang — fail fast instead of eating the whole bench TTL.

    Rather than dying with nothing (rc=3), the watchdog execve()s a
    REDUCED CPU re-run of this bench (MISAKA_BENCH_FALLBACK=cpu): the
    artifact then still carries measured numbers, honestly labeled with
    `"platform": "cpu"` + a `"fallback"` field, which is strictly more
    information than an empty failure.  execve replaces the whole process,
    including the thread stuck inside the hanging backend init.  Disable
    with MISAKA_BENCH_NO_FALLBACK=1.  Returns a disarm() to call once the
    backend is up.
    """
    import threading

    # 240s: far beyond any healthy init (~10-30s incl. the relay tunnel) but
    # early enough that the CPU fallback still fits a tight driver budget.
    ttl = float(environ.get("MISAKA_INIT_TTL_S", "240") or 0)
    if not ttl:
        return lambda: None
    ready = threading.Event()

    def boom():
        if ready.is_set():
            return
        print(
            f"# TPU backend failed to initialize within {ttl:g}s — the "
            "relayed worker is likely wedged or held by another process "
            "(make stop; otherwise wait for the remote worker to recover)",
            file=sys.stderr, flush=True,
        )
        if (
            environ.get("MISAKA_BENCH_NO_FALLBACK") != "1"
            and environ.get("MISAKA_BENCH_FALLBACK") != "cpu"
        ):
            print(
                "# re-executing on CPU (reduced sections) so the artifact "
                "still carries measured, platform-labeled numbers",
                file=sys.stderr, flush=True,
            )
            try:
                # the backend may have come up between the deadline firing and
                # this point (init completing at ~ttl is exactly when the race
                # is live); a healthy session must not be thrown away
                if ready.is_set():
                    return
                # the artifact must say WHY it is a CPU capture — a silent
                # platform switch reads as a 1000x regression; the child
                # also inherits only what REMAINS of the whole-run TTL
                _exec_cpu_fallback(
                    environ, sys.argv,
                    f"backend init hang: no TPU attach within {ttl:g}s",
                )
            except OSError as e:  # pragma: no cover — then the plain failure
                print(f"# fallback exec failed: {e}", file=sys.stderr, flush=True)
        if ready.is_set():  # init beat the deadline after all — keep the session
            return
        os._exit(3)

    t = threading.Timer(ttl, boom)
    t.daemon = True
    t.start()
    return ready.set


ATTACH_BACKOFF_S = 15.0  # first retry delay; doubles per attempt


def _remaining_ttl(environ) -> str | None:
    """What is left of the whole-run TTL budget, as an env-ready string.
    Computed from wall-clock elapsed since process start, so sleeps and
    hangs are charged against the budget (the TTL is a promise to the
    driver — no child process may be handed a fresh one)."""
    whole = float(environ.get("MISAKA_BENCH_TTL_S", "1140") or 0)
    if not whole:
        return None
    return f"{max(60.0, whole - (time.monotonic() - _T0)):g}"


def _exec_cpu_fallback(environ, argv, reason, execve=os.execve):
    """The ONE copy of the reduced CPU-fallback exec recipe, shared by the
    init-hang watchdog and the attach-retry path: CPU platform, fallback
    label, the failure reason carried into the artifact as
    `tpu_attach_error`, remaining-TTL inheritance, and the full-config /
    sweep flags stripped (reduced means reduced — they cost tens of
    minutes on CPU)."""
    env = dict(environ)
    remaining = _remaining_ttl(environ)
    if remaining is not None:
        env["MISAKA_BENCH_TTL_S"] = remaining
    env.update(
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        MISAKA_BENCH_FALLBACK="cpu",
        MISAKA_INIT_TTL_S="0",
        MISAKA_TPU_ATTACH_ERROR=reason,
    )
    argv = [a for a in argv if a not in ("--all", "--roofline")]
    execve(sys.executable, [sys.executable] + argv, env)


def _retry_or_fallback(
    err, environ=os.environ, execve=os.execve, sleep=time.sleep, argv=None
):
    """TPU attach RAISED (round 3's rc=1 was exactly this: a transient
    backend-init crash that instantly cost the round its TPU number).

    Bounded retries with exponential backoff, each attempt a re-exec of
    this bench (a failed JAX backend is cached in-process, so only a fresh
    process genuinely retries the attach); when the attempts are spent,
    degrade to the reduced CPU capture with the failure reason carried into
    the artifact as `tpu_attach_error` — a retried attach or a labeled
    fallback, never a silent platform switch.  MISAKA_ATTACH_RETRIES
    (default 2) bounds the retries; the re-exec inherits what remains of
    the whole-run TTL so retrying cannot eat the driver's budget.

    Dependencies are injectable for the unit tests (tests/test_bench.py);
    in production every path except NO_FALLBACK execve()s and never
    returns.
    """
    argv = list(sys.argv if argv is None else argv)
    reason = f"{type(err).__name__}: {err}"[:500]
    if (
        environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu"
        or environ.get("MISAKA_BENCH_FALLBACK") == "cpu"
    ):
        raise err  # CPU-only init failing is a real bug, not an attach blip
    attempt = int(environ.get("MISAKA_ATTACH_ATTEMPT", "0") or 0)
    retries = int(environ.get("MISAKA_ATTACH_RETRIES", "2") or 0)
    if attempt < retries:
        backoff = ATTACH_BACKOFF_S * (2 ** attempt)
        print(
            f"# TPU attach failed ({reason}); retrying attach "
            f"{attempt + 1}/{retries} in {backoff:g}s",
            file=sys.stderr, flush=True,
        )
        sleep(backoff)
        env = dict(environ)
        # remaining TTL is computed AFTER the backoff sleep, so the wait
        # itself is charged against the driver's budget
        remaining = _remaining_ttl(environ)
        if remaining is not None:
            env["MISAKA_BENCH_TTL_S"] = remaining
        env["MISAKA_TPU_ATTACH_ERROR"] = reason
        env["MISAKA_ATTACH_ATTEMPT"] = str(attempt + 1)
        execve(sys.executable, [sys.executable] + argv, env)
        return  # only reached when execve is stubbed (tests)
    if environ.get("MISAKA_BENCH_NO_FALLBACK") == "1":
        raise err
    print(
        f"# TPU attach failed after {attempt + 1} attempt(s) ({reason}); "
        "re-executing on CPU (reduced sections) so the artifact still "
        "carries measured, platform-labeled numbers",
        file=sys.stderr, flush=True,
    )
    _exec_cpu_fallback(environ, argv, reason, execve=execve)


def _preflight():
    """Warn about other alive misaka processes before touching the device.

    Only python processes count: supervisor shells/tools legitimately carry
    'misaka_tpu' or 'bench.py' inside longer command lines.
    """
    me = os.getpid()
    for pid in os.listdir("/proc"):
        if not pid.isdigit() or int(pid) == me:
            continue
        try:
            with open(f"/proc/{pid}/comm") as f:
                if not f.read().strip().startswith("python"):
                    continue
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().replace(b"\0", b" ").decode(errors="replace")
        except OSError:
            continue
        if "misaka_tpu" in cmd or "bench.py" in cmd or "chip_probe" in cmd:
            print(
                f"# WARNING: pid {pid} looks like a live misaka process and may "
                f"hold the TPU: {cmd[:120]!r} (make stop kills stragglers)",
                file=sys.stderr, flush=True,
            )


def _cpu_cache_dir(prefix: str) -> str:
    """Cache dir keyed by this host's CPU identity: /tmp outlives machine
    migrations between rounds, and stale entries compiled for a different
    CPU make XLA's AOT loader flood stderr with machine-mismatch errors
    (drowning the bench's own stderr provenance in the driver's tail).

    The whole of /proc/cpuinfo is hashed (x86 "flags", aarch64 "Features",
    model names — all of it) plus platform.machine(), so hosts without an
    x86-style flags line still get distinct dirs."""
    import hashlib
    import platform

    try:
        with open("/proc/cpuinfo") as f:
            # only STABLE identity lines: cpuinfo also carries volatile
            # fields ("cpu MHz", bogomips) that change between reads under
            # frequency scaling and would rename the "persistent" dir
            # every run
            ident = "".join(
                ln
                for ln in f
                if ln.split(":")[0].strip()
                in ("vendor_id", "model name", "flags", "Features",
                    "CPU implementer", "CPU part")
            ).encode()
    except OSError:  # pragma: no cover — no /proc (e.g. macOS)
        ident = b""
    ident += platform.processor().encode() + platform.machine().encode()
    return f"{prefix}_{hashlib.sha1(ident).hexdigest()[:8]}"


def _enable_compile_cache():
    """Persistent XLA compilation cache: repeat runs (driver after manual
    warm-up) skip the 20-40s first-compile cost per engine."""
    try:
        import jax

        jax.config.update(
            "jax_compilation_cache_dir", _cpu_cache_dir("/tmp/misaka_jax_cache")
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception as e:  # pragma: no cover — cache is best-effort
        print(f"# compile cache unavailable: {e}", file=sys.stderr)


def _last_tpu_context():
    """Latest committed platform=="tpu" bench artifact (round, headline), so a
    CPU-fallback payload stays self-describing across rounds instead of
    reading as a 1000x regression (VERDICT r4 weak #7)."""
    import glob
    import re

    best = None
    here = os.path.dirname(os.path.abspath(__file__))
    # Two artifact shapes: the driver's end-of-round BENCH_r{N}.json wraps
    # the bench line under "parsed" (+ stderr "tail"); the builder's
    # mid-round captures BENCH_tpu_r{N}*.json ARE the bench line.
    for path in glob.glob(os.path.join(here, "BENCH_*.json")):
        m = re.fullmatch(
            r"BENCH_(?:tpu_)?r(\d+)\w*\.json", os.path.basename(path)
        )
        if not m:
            continue
        try:
            with open(path) as f:
                data = json.load(f)
        except Exception:
            continue
        parsed = data.get("parsed") if "parsed" in data else data
        if not isinstance(parsed, dict) or parsed.get("value") is None:
            continue  # crashed/partial round: no trustworthy headline
        # rounds 1-2 predate the in-payload platform label, so fall back to
        # the "platform=tpu" marker bench prints to stderr
        on_tpu = parsed.get("platform") == "tpu" or (
            "platform" not in parsed and "platform=tpu" in data.get("tail", "")
        )
        if not on_tpu or parsed.get("fallback") or data.get("rc", 0) != 0:
            continue
        rnd = int(m.group(1))
        val = parsed.get("value")
        if best is None or (rnd, val) > (best["round"], best["value"]):
            best = {
                "round": rnd,
                "metric": parsed.get("metric"),
                "value": val,
                "unit": parsed.get("unit"),
                "vs_baseline": parsed.get("vs_baseline"),
            }
    return best


def _expect_sorter(v):
    return np.where(v > 0, 11, np.where(v < 0, -11, 0)).astype(np.int32)


# Per-config oracle + tick budget per retired value (generous; completion is
# asserted, and an undersized budget retries with double the ticks).
CONFIGS = {
    "add2": dict(expect=lambda v: v + 2, ticks_per_value=14, ordered=True),
    "acc_loop": dict(expect=lambda v: v + 3, ticks_per_value=10, ordered=True),
    "ring4": dict(expect=lambda v: v + 4, ticks_per_value=20, ordered=True),
    "sorter": dict(expect=_expect_sorter, ticks_per_value=10, ordered=True),
    # mesh8's two pipelines race for IN, so per-instance output ORDER is
    # arbitration-dependent; parity is a multiset check.
    "mesh8": dict(expect=lambda v: v + 4, ticks_per_value=12, ordered=False),
}


def _repeat_best(once, first, min_time, max_reps):
    """Best-of-reps timing: repeat `once` (which must verify its own run
    and return elapsed seconds) until `min_time` total or `max_reps` reps.
    Returns (times, best, median).  The ONE copy of the r4 lane-matrix
    methodology, shared by bench_lanes and (since r5) the headline."""
    import statistics

    times = [first]
    while sum(times) < min_time and len(times) < max_reps:
        times.append(once())
    return times, min(times), statistics.median(times)


def bench_config(
    name, batch=262144, per_instance=128, block_batch=2048, max_attempts=3,
    min_time=3.0, max_reps=6,
):
    """Measure one BASELINE config: B instances drain Q values each.

    Uses the fused Pallas kernel on TPU (one launch for the whole run), the
    XLA scan engine elsewhere.  Completion and parity are asserted.

    Best-of-reps since r5 (same methodology the lane matrix adopted in r4;
    `reps` + `throughput_median` recorded): the timed window necessarily
    contains one device->host sync, a 72-103ms relay round trip on the r5
    chip against a ~0.4s kernel — single-shot headlines moved 84.5->124.4M
    between identical runs on relay noise alone (BENCH_tpu_r05*.json).
    Repetition bounds the sync tax; the median keeps pre-r5 single-shot
    rounds comparable."""
    import jax
    import jax.numpy as jnp

    from misaka_tpu import networks

    cfg = CONFIGS[name]
    top = networks.BASELINE_CONFIGS[name](
        in_cap=per_instance, out_cap=per_instance, stack_cap=16
    )
    net = top.compile(batch=batch)

    rng = np.random.default_rng(0)
    vals = rng.integers(-1000, 1000, size=(batch, per_instance)).astype(np.int32)
    if name == "sorter":  # make sure the JEZ branch is exercised too
        vals[:, ::17] = 0
    expected = cfg["expect"](vals)

    def fresh_state():
        state = net.init_state()
        return state._replace(
            in_buf=jnp.asarray(vals),
            in_wr=state.in_wr + np.int32(per_instance),
        )

    on_tpu = jax.devices()[0].platform == "tpu"
    ticks = cfg["ticks_per_value"] * per_instance + 256
    for attempt in range(max_attempts):
        if on_tpu:
            runner = net.fused_runner(ticks, block_batch=block_batch)
        else:
            runner = lambda s: net.run(s, ticks)

        # Warm-up compile; sync via a real transfer (block_until_ready does
        # not wait under the axon relay).
        s = runner(fresh_state())
        _ = int(np.asarray(s.tick)[0])

        def once():
            state = fresh_state()
            _ = int(np.asarray(state.tick)[0])
            t0 = time.perf_counter()
            state = runner(state)
            out_wr = np.asarray(state.out_wr)  # sync point (one host pull)
            return time.perf_counter() - t0, out_wr, state

        total = batch * per_instance
        elapsed, out_wr, state = once()

        if (out_wr == per_instance).all():
            break
        ticks *= 2  # undersized budget: double and retry
    else:
        raise RuntimeError(
            f"{name}: benchmark did not complete: min out_wr "
            f"{out_wr.min()}/{per_instance}"
        )

    # Per-rep verification without a full-buffer host pull (out_buf is
    # ~512MB at headline batch — seconds through the relay per rep): every
    # rep must complete exactly (out_wr == per_instance) and match an
    # order-invariant mod-2^32 checksum computed ON DEVICE; the final
    # state additionally gets the full elementwise parity check below.
    exp_ck = int(expected.astype(np.uint32).sum(dtype=np.uint64) % (1 << 32))

    def check(rep_out_wr, rep_state):
        if not (rep_out_wr == per_instance).all():
            raise RuntimeError(
                f"{name}: rep incomplete {rep_out_wr.min()}/{per_instance}"
            )
        ck = int(jax.device_get(jnp.sum(
            rep_state.out_buf.astype(jnp.uint32), dtype=jnp.uint32
        )))
        if ck != exp_ck:
            raise RuntimeError(f"{name}: rep checksum parity FAILED")

    check(out_wr, state)

    def timed_rep():
        nonlocal state
        rep_elapsed, rep_out_wr, state = once()
        check(rep_out_wr, state)
        return rep_elapsed

    times, elapsed, median = _repeat_best(timed_rep, elapsed, min_time, max_reps)

    out = np.asarray(state.out_buf)
    if cfg["ordered"]:
        ok = (out == expected).all()
    else:
        ok = (np.sort(out, axis=1) == np.sort(expected, axis=1)).all()
    if not ok:
        raise RuntimeError(f"{name}: output parity FAILED")

    return {
        "name": name,
        "throughput": total / elapsed,
        "throughput_median": total / median,
        "reps": len(times),
        "elapsed_s": elapsed,
        "ticks": int(np.asarray(state.tick)[0]),
        "ticks_per_sec": ticks / elapsed,
        "values": total,
        "ticks_per_value": ticks * batch / total,
        "batch": batch,
        "per_instance": per_instance,
    }


def bench_add2(batch=262144, per_instance=128, block_batch=2048):
    """The headline metric (kept as an alias for external callers)."""
    return bench_config("add2", batch, per_instance, block_batch)


def _scrape_metrics(base: str, timeout: float = 10.0) -> dict:
    """GET /metrics parsed into {series: value} (utils/metrics.parse_text
    — the same parser the tests validate the exposition with)."""
    import urllib.request

    from misaka_tpu.utils import metrics as _metrics

    with urllib.request.urlopen(base + "/metrics", timeout=timeout) as resp:
        return _metrics.parse_text(resp.read().decode())


def _scrape_usage(base: str, timeout: float = 10.0) -> dict:
    """GET /debug/usage (the per-program resource ledger, r12)."""
    import urllib.request

    with urllib.request.urlopen(
        base + "/debug/usage", timeout=timeout
    ) as resp:
        return json.loads(resp.read().decode())


def _usage_delta(before: dict, after: dict) -> dict:
    """Per-program accumulator deltas between two /debug/usage scrapes,
    plus the pass-wall conservation pair — the multi-tenant artifact's
    attribution story (mirrors served_metrics_delta from r7)."""
    programs = {}
    for name, a in after.get("programs", {}).items():
        b = before.get("programs", {}).get(name, {})
        d = {
            k: round(a[k] - b.get(k, 0), 6)
            for k in ("requests", "values", "cpu_seconds",
                      "native_seconds", "queue_seconds")
            if k in a
        }
        if any(d.values()):
            programs[name] = d
    pass_delta = round(
        after.get("pass_seconds_total", 0.0)
        - before.get("pass_seconds_total", 0.0), 6,
    )
    cpu_delta = round(
        sum(p.get("cpu_seconds", 0.0) for p in programs.values()), 6
    )
    return {
        "programs": programs,
        "pass_seconds_total": pass_delta,
        "cpu_seconds_total": cpu_delta,
        # attributed/actual: 1.0 = perfect conservation (bench-smoke
        # gates this within 20%; the tier-1 test pins 5%)
        "conservation": round(cpu_delta / pass_delta, 4) if pass_delta
        else None,
    }


def bench_served(
    batch=None,
    in_cap=128,
    chunk_steps=2048,
    threads=8,
    waves=6,
    timeout=120.0,
    mode="raw",
    stripe=None,
    engine="auto",
):
    """Throughput through the PRODUCT surface: a real MasterNode + HTTP
    server + /compute_raw (or /compute_batch with mode="text") requests,
    fused Pallas engine when on TPU, the multi-threaded native C++ tier
    when not (engine="auto" prefers it off-TPU since r6 — the fallback
    that keeps this metric past the 1M/s north star with no chip).

    Round-1's 106M/s was a harness number (kernel-only); this drives the
    actual serve path the way a client fleet would: `threads` concurrent
    HTTP clients each posting spread requests sized to cover their share of
    the batch, for `waves` rounds.  Outputs are parity-checked.  Returns
    served inputs/sec plus the engine that served them.
    """
    import threading as _threading
    import urllib.request

    import jax

    from misaka_tpu import networks
    from misaka_tpu.runtime.master import MasterNode, make_http_server

    on_tpu = jax.devices()[0].platform == "tpu"
    if batch is None:
        # 32768 measured best on the relayed r5 chip (batch sweep,
        # artifacts/r05/served_batch_probe.json): 8192 -> 379-813k/s,
        # 32768 -> 1.49M/s in the probe and 1.81M/s in the final capture
        # (BENCH_tpu_r05_final.json, the serving record — past the 1M/s
        # north star through HTTP), 65536 -> 1.32M/s — bigger waves
        # amortize the 72-103ms per-dispatch relay latency until device
        # compute per wave dominates.
        # CPU default 1024 since r6 (native-tier sweep, this host, raw
        # mode): 256 -> 1.06M/s, 512 -> 1.76M/s, 1024 -> 2.57M/s — batch
        # sizes the per-thread request, and bigger waves amortize the
        # HTTP round trips over the thread-pooled replicas.
        batch = 32768 if on_tpu else 1024
    top = networks.add2(in_cap=in_cap, out_cap=in_cap, stack_cap=16)
    master = MasterNode(
        top, chunk_steps=chunk_steps, batch=batch, engine=engine, stripe=stripe
    )
    httpd = make_http_server(master, port=0)
    server_thread = _threading.Thread(target=httpd.serve_forever, daemon=True)
    server_thread.start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    master.run()

    per_request = (batch // threads) * in_cap  # covers the thread's batch share
    rng = np.random.default_rng(1)

    from misaka_tpu.utils.textcodec import dec_to_ints, ints_to_dec

    if mode == "raw":
        url = base + "/compute_raw?spread=1"
        encode = lambda vals: np.ascontiguousarray(vals, "<i4").tobytes()
        decode = lambda raw: np.frombuffer(raw, dtype="<i4")
    else:
        url = base + "/compute_batch"
        # '+' doubles as the form-encoded space AND the token pad, so the
        # body needs no urlencode pass; the response's JSON int array parses
        # in one vectorized pass (json.loads would re-walk it per value)
        encode = lambda vals: (
            b"values=" + ints_to_dec(vals, b"+", zero_pad=True) + b"&spread=1"
        )
        decode = lambda raw: dec_to_ints(
            raw[raw.index(b"[") + 1 : raw.rindex(b"]")]
        )

    def make_requests(count):
        reqs = []
        for _ in range(count):
            vals = rng.integers(-1000, 1000, size=per_request).astype(np.int32)
            reqs.append([vals, encode(vals), None])
        return reqs

    # Request bodies are encoded BEFORE the timed window and responses are
    # decoded/parity-checked after it: the metric is SERVER throughput, and
    # this in-process client's codec work would otherwise contend for the
    # same GIL the server handlers use — a bench artifact a real client
    # fleet doesn't impose.
    warm_reqs = [make_requests(1) for _ in range(threads)]
    meas_reqs = [make_requests(waves) for _ in range(threads)]
    errors = []

    def worker(reqs):
        try:
            for item in reqs:
                req = urllib.request.Request(url, data=item[1], method="POST")
                with urllib.request.urlopen(req, timeout=timeout) as resp:
                    item[2] = resp.read()
        except Exception as e:  # pragma: no cover — failure path
            errors.append(e)

    def run_wave(all_reqs):
        ws = [
            _threading.Thread(target=worker, args=(reqs,)) for reqs in all_reqs
        ]
        for t in ws:
            t.start()
        for t in ws:
            t.join()
        if errors:
            raise errors[0]

    try:
        run_wave(warm_reqs)  # warmup (compile + queue plumbing)
        # Scrape the live metrics plane around the timed window: the delta
        # embedded in the artifact makes a perf capture carry its own
        # telemetry (requests/values served, chunk iterations, native pool
        # calls) — a regression shows WHERE it happened, not just that the
        # headline moved.  Scrapes sit outside the timed window.
        try:
            metrics_before = _scrape_metrics(base)
        except Exception as e:  # pragma: no cover — telemetry is best-effort
            print(f"# metrics scrape (before) failed: {e}", file=sys.stderr)
            metrics_before = None
        t0 = time.perf_counter()
        run_wave(meas_reqs)
        elapsed = time.perf_counter() - t0
        metrics_delta = None
        if metrics_before is not None:
            try:
                from misaka_tpu.utils import metrics as _metrics

                metrics_delta = _metrics.delta(
                    metrics_before, _scrape_metrics(base)
                )
            except Exception as e:  # pragma: no cover
                print(f"# metrics scrape (after) failed: {e}", file=sys.stderr)
    finally:
        master.pause()
        httpd.shutdown()

    total = 0
    for reqs in warm_reqs + meas_reqs:
        for vals, _, raw in reqs:
            out = decode(raw)
            if not np.array_equal(out, vals + 2):
                raise RuntimeError("served output parity FAILED")
    for reqs in meas_reqs:
        total += sum(len(vals) for vals, _, _ in reqs)
    return {
        "throughput": total / elapsed,
        "values": total,
        "elapsed_s": elapsed,
        "engine": master.engine_name,
        "batch": batch,
        "threads": threads,
        "per_request": per_request,
        "mode": mode,
        "metrics_delta": metrics_delta,
    }


def _sweep_fleet_main(argv):
    """`python bench.py --sweep-fleet HOST PORT C SECONDS PAYLOAD SEED`:
    one keep-alive client-fleet PROCESS for bench_concurrency_sweep.

    Runs C client threads against the server, each holding one persistent
    HTTP/1.1 connection, for SECONDS; prints one JSON line with request
    count, elapsed, and the full per-request latency list (ms).  Lives in
    a separate process so the CLIENT-side Python cost does not share the
    server's GIL — with 64 in-process client threads the sweep measured
    the bench harness, not the server.  Imports stdlib + numpy only.
    """
    import http.client
    import threading as _threading

    host, port = argv[0], int(argv[1])
    n_clients, seconds = int(argv[2]), float(argv[3])
    payload_values, seed = int(argv[4]), int(argv[5])
    rng = np.random.default_rng(seed)
    bodies = []
    for _ in range(8):
        vals = rng.integers(-1000, 1000, size=payload_values).astype(np.int32)
        bodies.append(
            (np.ascontiguousarray(vals, "<i4").tobytes(),
             np.ascontiguousarray(vals + 2, "<i4").tobytes())
        )
    counts = [0] * n_clients
    lats: list[list[float]] = [[] for _ in range(n_clients)]
    errors = []
    stop = _threading.Event()

    def one_client(i):
        try:
            conn = http.client.HTTPConnection(host, port, timeout=60)
            # warmup on the same connection, outside the timed window
            for k in range(2):
                conn.request("POST", "/compute_raw?spread=1", bodies[k][0])
                if conn.getresponse().read() != bodies[k][1]:
                    raise RuntimeError("sweep parity FAILED (warmup)")
            t_end = time.monotonic() + seconds
            k = 0
            while time.monotonic() < t_end and not stop.is_set():
                body, want = bodies[k % 8]
                t0 = time.perf_counter()
                conn.request("POST", "/compute_raw?spread=1", body)
                raw = conn.getresponse().read()
                lats[i].append(time.perf_counter() - t0)
                if raw != want:
                    raise RuntimeError("sweep parity FAILED")
                counts[i] += 1
                k += 1
            conn.close()
        except Exception as e:  # pragma: no cover — failure path
            errors.append(repr(e))
            stop.set()

    threads = [
        _threading.Thread(target=one_client, args=(i,))
        for i in range(n_clients)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0
    out = {
        "requests": sum(counts),
        "elapsed_s": round(elapsed, 4),
        "errors": errors,
        "lats_ms": [
            round(x * 1e3, 3) for l in lats for x in l
        ],
    }
    print(json.dumps(out))


def _overload_fleet_main(argv):
    """`python bench.py --overload-fleet HOST PORT C SECONDS PAYLOAD SEED
    KEY MODE`: one client-fleet PROCESS for bench_overload.

    MODE "good": the in-quota tenant — parity-checked closed loop; ANY
    non-200 is recorded as an error (the drill's zero-client-visible-
    errors contract).  MODE "flood": the abusive tenant — fires as fast
    as responses come back, treating 429 as expected shed (counted, the
    Retry-After header required) and anything else but 200 as an error.
    Prints one JSON line: ok/rejected/errors counts, admitted-request
    latencies (ms), elapsed, and whether every 429 carried Retry-After.
    """
    import http.client
    import threading as _threading

    host, port = argv[0], int(argv[1])
    n_clients, seconds = int(argv[2]), float(argv[3])
    payload_values, seed = int(argv[4]), int(argv[5])
    api_key, mode = argv[6], argv[7]
    pause_s = float(argv[8]) / 1e3 if len(argv) > 8 else 0.0
    rng = np.random.default_rng(seed)
    bodies = []
    for _ in range(8):
        vals = rng.integers(-1000, 1000, size=payload_values).astype(np.int32)
        bodies.append(
            (np.ascontiguousarray(vals, "<i4").tobytes(),
             np.ascontiguousarray(vals + 2, "<i4").tobytes())
        )
    headers = {"X-Misaka-Key": api_key}
    ok = [0] * n_clients
    rejected = [0] * n_clients
    missing_retry_after = [0] * n_clients
    lats: list[list[float]] = [[] for _ in range(n_clients)]
    errors = []
    stop = _threading.Event()

    def one_client(i):
        try:
            conn = http.client.HTTPConnection(host, port, timeout=60)
            t_end = time.monotonic() + seconds
            k = 0
            while time.monotonic() < t_end and not stop.is_set():
                body, want = bodies[k % 8]
                t0 = time.perf_counter()
                conn.request("POST", "/compute_raw?spread=1", body,
                             headers)
                resp = conn.getresponse()
                raw = resp.read()
                dt = time.perf_counter() - t0
                k += 1
                if resp.status == 200:
                    if raw != want:
                        raise RuntimeError("overload parity FAILED")
                    ok[i] += 1
                    lats[i].append(dt)
                elif resp.status == 429 and mode == "flood":
                    rejected[i] += 1
                    if resp.getheader("Retry-After") is None:
                        missing_retry_after[i] += 1
                    if resp.will_close:
                        conn.close()
                        conn = http.client.HTTPConnection(
                            host, port, timeout=60
                        )
                else:
                    raise RuntimeError(
                        f"unexpected status {resp.status}: {raw[:120]!r}"
                    )
                if pause_s:
                    # a finite-capacity abusive client, NOT honoring the
                    # Retry-After: the offered load stays several times
                    # capacity while the drill measures the edge, not
                    # the harness's ability to spin on rejections
                    time.sleep(pause_s)
            conn.close()
        except Exception as e:  # pragma: no cover — failure path
            errors.append(repr(e))
            stop.set()

    threads = [
        _threading.Thread(target=one_client, args=(i,))
        for i in range(n_clients)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0
    print(json.dumps({
        "mode": mode,
        "ok": sum(ok),
        "rejected": sum(rejected),
        "missing_retry_after": sum(missing_retry_after),
        "errors": errors,
        "elapsed_s": round(elapsed, 4),
        "lats_ms": [round(x * 1e3, 3) for l in lats for x in l],
    }))


def bench_concurrency_sweep(
    clients=(1, 4, 16, 64),
    payload_values=64,
    batch=None,
    in_cap=128,
    chunk_steps=2048,
    seconds=3.0,
    warmup_s=0.5,
    engine="auto",
    timeout=60.0,
    http_workers=0,
    fleet_procs=1,
):
    """Multi-tenant serving: C keep-alive HTTP clients each posting SMALL
    raw payloads (64 int32 values — a realistic per-user request) as fast
    as the server answers, for each C in `clients`.

    This is the workload the ROADMAP's millions-of-users north star
    actually looks like, and the one the r06/r07 single-client big-batch
    headline says nothing about: many concurrent small requests exercise
    per-request slot claiming, queue hops, and connection handling instead
    of bulk striping.  Every client holds ONE persistent HTTP/1.1
    connection (http.client) for its whole run — connection setup must not
    be what this lane measures — and every response is parity-checked.

    `http_workers` > 0 boots the multi-process serving plane
    (runtime/frontends.py): N SO_REUSEPORT frontend workers in front of
    the engine, the r8 architecture for scaling HTTP past one GIL.
    `fleet_procs` > 1 runs the client fleet in that many SUBPROCESSES so
    client-side Python does not share the server's GIL (with 64
    in-process client threads the sweep measured the harness, not the
    server); 1 keeps the in-process thread fleet — the harness the
    committed pre-PR baseline was captured with, so A/B comparisons
    against it must keep fleet_procs=1.

    Returns [{clients, p50_ms, p99_ms, requests, throughput}] plus the
    served engine name.
    """
    import subprocess
    import threading as _threading

    import jax

    from misaka_tpu import networks
    from misaka_tpu.runtime.master import MasterNode, make_http_server

    on_tpu = jax.devices()[0].platform == "tpu"
    if batch is None:
        batch = 32768 if on_tpu else 1024  # bench_served's defaults
    top = networks.add2(in_cap=in_cap, out_cap=in_cap, stack_cap=16)
    master = MasterNode(top, chunk_steps=chunk_steps, batch=batch, engine=engine)
    httpd = make_http_server(master, port=0)
    server_thread = _threading.Thread(target=httpd.serve_forever, daemon=True)
    server_thread.start()
    host, port = "127.0.0.1", httpd.server_address[1]
    plane = None
    frontend_procs = []
    if http_workers:
        from misaka_tpu.runtime import frontends

        plane_path = f"/tmp/misaka-bench-plane-{os.getpid()}.sock"
        plane = frontends.start_compute_plane(master, plane_path)
        public_port = frontends.pick_free_port()
        frontend_procs = frontends.spawn_frontends(
            http_workers, public_port, f"http://{host}:{port}", plane_path
        )
        if not frontends.wait_ready(public_port):
            raise RuntimeError("frontend workers did not come up")
        port = public_port
    master.run()

    def run_lane_procs(c):
        """The client fleet as subprocesses (their own GILs)."""
        n_procs = min(fleet_procs, c)
        per = [c // n_procs + (1 if i < c % n_procs else 0)
               for i in range(n_procs)]
        procs = [
            subprocess.Popen(
                [sys.executable, os.path.abspath(__file__), "--sweep-fleet",
                 host, str(port), str(per[i]), str(seconds),
                 str(payload_values), str(100 + i)],
                stdout=subprocess.PIPE,
                env={**os.environ, "JAX_PLATFORMS": "cpu"},
            )
            for i in range(n_procs)
        ]
        outs = [json.loads(p.communicate(timeout=timeout)[0]) for p in procs]
        for o in outs:
            if o["errors"]:
                raise RuntimeError(f"sweep fleet failed: {o['errors'][0]}")
        lats = np.concatenate([np.asarray(o["lats_ms"]) for o in outs])
        n_reqs = sum(o["requests"] for o in outs)
        elapsed = max(o["elapsed_s"] for o in outs)
        return n_reqs, elapsed, lats

    def run_lane_threads(c):
        """The in-process thread fleet (the committed-baseline harness)."""
        import http.client

        rng = np.random.default_rng(11)
        bodies = []
        for _ in range(8):
            vals = rng.integers(
                -1000, 1000, size=payload_values
            ).astype(np.int32)
            bodies.append((vals, np.ascontiguousarray(vals, "<i4").tobytes()))
        lat_per_client = [[] for _ in range(c)]
        counts = [0] * c
        errors = []
        stop = _threading.Event()
        start_bar = _threading.Barrier(c + 1)

        def one_client(i):
            try:
                conn = http.client.HTTPConnection(host, port, timeout=timeout)
                lats = lat_per_client[i]
                t_end = time.monotonic() + warmup_s
                while time.monotonic() < t_end:  # warmup, same connection
                    vals, body = bodies[counts[i] % 8]
                    conn.request("POST", "/compute_raw?spread=1", body)
                    raw = conn.getresponse().read()
                    if not np.array_equal(
                        np.frombuffer(raw, dtype="<i4"), vals + 2
                    ):
                        raise RuntimeError("sweep parity FAILED (warmup)")
                    counts[i] += 1
                counts[i] = 0
                start_bar.wait()
                while not stop.is_set():
                    vals, body = bodies[counts[i] % 8]
                    t0 = time.perf_counter()
                    conn.request("POST", "/compute_raw?spread=1", body)
                    raw = conn.getresponse().read()
                    dt = time.perf_counter() - t0
                    if not np.array_equal(
                        np.frombuffer(raw, dtype="<i4"), vals + 2
                    ):
                        raise RuntimeError("sweep parity FAILED")
                    lats.append(dt)
                    counts[i] += 1
                conn.close()
            except Exception as e:  # pragma: no cover — failure path
                errors.append(e)
                stop.set()
                try:
                    start_bar.abort()
                except Exception:
                    pass

        ts = [
            _threading.Thread(target=one_client, args=(i,)) for i in range(c)
        ]
        for t in ts:
            t.start()
        start_bar.wait()
        t0 = time.perf_counter()
        time.sleep(seconds)
        stop.set()
        for t in ts:
            t.join()
        elapsed = time.perf_counter() - t0
        if errors:
            raise errors[0]
        lats = np.concatenate(
            [np.asarray(l) for l in lat_per_client if l]
        ) * 1e3
        return sum(counts), elapsed, lats

    results = []
    try:
        for c in clients:
            if fleet_procs > 1:
                n_reqs, elapsed, lats = run_lane_procs(c)
            else:
                n_reqs, elapsed, lats = run_lane_threads(c)
            entry = {
                "clients": c,
                "payload_values": payload_values,
                "requests": n_reqs,
                "p50_ms": round(float(np.percentile(lats, 50)), 3),
                "p99_ms": round(float(np.percentile(lats, 99)), 3),
                "throughput": round(n_reqs * payload_values / elapsed, 1),
            }
            results.append(entry)
            print(
                f"# concurrency: C={c} reqs={n_reqs} "
                f"p50={entry['p50_ms']:.2f}ms p99={entry['p99_ms']:.2f}ms "
                f"throughput={entry['throughput']:.0f}/s",
                file=sys.stderr,
            )
    finally:
        for p in frontend_procs:
            p.terminate()
        if plane is not None:
            plane.close()
        master.pause()
        httpd.shutdown()
    out = {
        "engine": master.engine_name,
        "batch": batch,
        "lanes": results,
    }
    if http_workers:
        out["http_workers"] = http_workers
    if fleet_procs > 1:
        out["fleet_procs"] = fleet_procs
    return out


def bench_fleet_scaling(
    replicas=(1, 2, 4),
    clients=64,
    payload_values=64,
    seconds=3.0,
    timeout=120.0,
    http_workers=None,
    client_procs=4,
):
    """The horizontal scale-out lane (r13): a REAL subprocess fleet —
    `MISAKA_FLEET=N` engine replicas (each its own process, native pool,
    and ServeBatcher) behind the shared SO_REUSEPORT frontend tier
    routing with the FleetPlaneRouter — under the 64-client keep-alive
    small-payload workload, for each N in `replicas`.

    This measures the ONE number the single-box lanes cannot: whether
    adding engine replicas moves the 64-client aggregate past the
    single-engine wall (docs/BENCH_HISTORY.md r8: one CPython engine
    process saturates near ~3.5k req/s regardless of native-pool
    speed).  The client fleet runs in `client_procs` subprocesses (their
    own GILs, same harness as the committed r08 frontend sweep) and
    every response is parity-checked.  Returns per-N lanes with
    aggregate values/s, p50/p99, and speedup vs the 1-replica lane.
    """
    import http.client
    import subprocess
    import urllib.request

    from misaka_tpu.runtime import frontends

    add2_env = {
        "NODE_INFO": json.dumps({
            "misaka1": {"type": "program"},
            "misaka2": {"type": "program"},
            "misaka3": {"type": "stack"},
        }),
        "MISAKA_PROGRAMS": json.dumps({
            "misaka1": "IN ACC\nADD 1\nMOV ACC, misaka2:R0\nMOV R0, ACC\n"
                       "OUT ACC\n",
            "misaka2": "MOV R0, ACC\nADD 1\nPUSH ACC, misaka3\n"
                       "POP misaka3, ACC\nMOV ACC, misaka1:R0\n",
        }),
    }

    def run_lane(n):
        port = frontends.pick_free_port()
        fleet_dir = f"/tmp/misaka-bench-fleet-{os.getpid()}-{n}"
        env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",
            "MISAKA_FLEET": str(n),
            "MISAKA_HTTP_WORKERS": str(http_workers or max(4, n + 2)),
            "MISAKA_AUTORUN": "1",
            "MISAKA_PORT": str(port),
            "MISAKA_FLEET_DIR": fleet_dir,
            "MISAKA_TTL_S": "600",
            # the committed serving configuration (r08 sweep harness):
            # B=1024 lockstep instances + in_cap=128 + chunk=2048 per
            # replica — an unbatched 1-instance chunk-128 engine would
            # measure the wrong tier
            "MISAKA_BATCH": "1024",
            "MISAKA_IN_CAP": "128",
            "MISAKA_OUT_CAP": "128",
            "MISAKA_CHUNK_STEPS": "2048",
            **add2_env,
        }
        proc = subprocess.Popen(
            [sys.executable, "-m", "misaka_tpu.runtime.app"], env=env
        )
        try:
            deadline = time.monotonic() + 180
            base = f"http://127.0.0.1:{port}"
            while True:
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"fleet (N={n}) exited during boot: {proc.returncode}"
                    )
                try:
                    with urllib.request.urlopen(
                        base + "/healthz", timeout=5
                    ) as r:
                        payload = json.loads(r.read())
                    if payload.get("ok") and not payload.get("degraded"):
                        break
                except (OSError, http.client.HTTPException):
                    # HTTPException too (MSK002): the fleet endpoint
                    # mid-boot can tear a connection after the status
                    # line — BadStatusLine must read as "not ready yet",
                    # not crash the whole bench lane
                    pass
                if time.monotonic() > deadline:
                    raise RuntimeError(f"fleet (N={n}) never became healthy")
                time.sleep(0.5)
            n_procs = min(client_procs, clients)
            per = [clients // n_procs + (1 if i < clients % n_procs else 0)
                   for i in range(n_procs)]
            fleets = [
                subprocess.Popen(
                    [sys.executable, os.path.abspath(__file__),
                     "--sweep-fleet", "127.0.0.1", str(port), str(per[i]),
                     str(seconds), str(payload_values), str(200 + i)],
                    stdout=subprocess.PIPE,
                    env={**os.environ, "JAX_PLATFORMS": "cpu"},
                )
                for i in range(n_procs)
            ]
            outs = [
                json.loads(p.communicate(timeout=timeout)[0]) for p in fleets
            ]
            for o in outs:
                if o["errors"]:
                    raise RuntimeError(
                        f"fleet lane N={n} client error: {o['errors'][0]}"
                    )
            lats = np.concatenate([np.asarray(o["lats_ms"]) for o in outs])
            n_reqs = sum(o["requests"] for o in outs)
            elapsed = max(o["elapsed_s"] for o in outs)
            return {
                "replicas": n,
                "clients": clients,
                "payload_values": payload_values,
                "requests": n_reqs,
                "p50_ms": round(float(np.percentile(lats, 50)), 3),
                "p99_ms": round(float(np.percentile(lats, 99)), 3),
                "throughput": round(n_reqs * payload_values / elapsed, 1),
            }
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=20)
            except subprocess.TimeoutExpired:
                proc.kill()
            import shutil

            shutil.rmtree(fleet_dir, ignore_errors=True)

    lanes = []
    for n in replicas:
        entry = run_lane(n)
        if lanes:
            entry["speedup_vs_1"] = round(
                entry["throughput"] / lanes[0]["throughput"], 2
            )
        lanes.append(entry)
        print(
            f"# fleet: N={entry['replicas']} reqs={entry['requests']} "
            f"p50={entry['p50_ms']:.2f}ms p99={entry['p99_ms']:.2f}ms "
            f"throughput={entry['throughput']:.0f}/s"
            + (f" ({entry['speedup_vs_1']}x vs 1 replica)"
               if "speedup_vs_1" in entry else ""),
            file=sys.stderr,
        )
    return {"clients": clients, "payload_values": payload_values,
            "lanes": lanes}


def bench_multi_tenant(
    clients=64,
    payload_values=64,
    batch=None,
    in_cap=128,
    chunk_steps=2048,
    seconds=3.0,
    warmup_s=0.5,
    engine="auto",
    timeout=60.0,
):
    """Multi-PROGRAM serving through the registry (r11): C keep-alive
    clients split across three concurrently served tenants — dense (the
    add2 compose network, 2 lanes + stack), compact (acc_loop, one lane),
    and chained (an 8-stage pipeline) — each on its OWN per-program
    engine behind one HTTP server, addressed via POST
    /programs/<name>/compute_raw.

    This is the many-scenarios axis the single-program lanes say nothing
    about: per-program ServeBatchers coalescing independently, the
    registry lease on every request, and three engines sharing the host.
    Every response is parity-checked against its tenant's program delta.
    Returns per-program AND aggregate requests, p50/p99 latency, and
    values/s.
    """
    import http.client
    import threading as _threading

    import jax

    from misaka_tpu import networks
    from misaka_tpu.runtime.master import MasterNode, make_http_server
    from misaka_tpu.runtime.registry import ProgramRegistry

    on_tpu = jax.devices()[0].platform == "tpu"
    if batch is None:
        batch = 32768 if on_tpu else 1024  # bench_served's defaults
    caps = dict(in_cap=in_cap, out_cap=in_cap, stack_cap=16)
    reg = ProgramRegistry(
        None, batch=batch, engine=engine, chunk_steps=chunk_steps, caps=caps
    )
    top = networks.add2(**caps)
    master = MasterNode(top, chunk_steps=chunk_steps, batch=batch, engine=engine)
    reg.seed("dense", master, top)
    # the other two tenants upload through the registry like a client would
    tenants = [("dense", 2)]
    for name, topo, delta in (
        ("compact", networks.acc_loop(**caps), 3),
        ("chained", networks.pipeline(8, **caps), 8),
    ):
        reg.publish(name, topology_json=json.dumps(
            {"nodes": topo.node_info, "programs": topo.programs, **caps}
        ))
        tenants.append((name, delta))
    httpd = make_http_server(master, port=0, registry=reg)
    server_thread = _threading.Thread(target=httpd.serve_forever, daemon=True)
    server_thread.start()
    host, port = "127.0.0.1", httpd.server_address[1]
    master.run()

    rng = np.random.default_rng(7)
    bodies = []
    for _ in range(8):
        vals = rng.integers(-1000, 1000, size=payload_values).astype(np.int32)
        bodies.append((vals, np.ascontiguousarray(vals, "<i4").tobytes()))
    lat_per_client = [[] for _ in range(clients)]
    counts = [0] * clients
    errors = []
    stop = _threading.Event()
    start_bar = _threading.Barrier(clients + 1)

    def one_client(i):
        name, delta = tenants[i % len(tenants)]
        path = f"/programs/{name}/compute_raw?spread=1"
        try:
            conn = http.client.HTTPConnection(host, port, timeout=timeout)
            lats = lat_per_client[i]
            t_end = time.monotonic() + warmup_s
            while time.monotonic() < t_end:  # warmup (activates engines)
                vals, body = bodies[counts[i] % 8]
                conn.request("POST", path, body)
                raw = conn.getresponse().read()
                if not np.array_equal(
                    np.frombuffer(raw, dtype="<i4"), vals + delta
                ):
                    raise RuntimeError(
                        f"multi-tenant parity FAILED (warmup, {name})"
                    )
                counts[i] += 1
            counts[i] = 0
            start_bar.wait()
            while not stop.is_set():
                vals, body = bodies[counts[i] % 8]
                t0 = time.perf_counter()
                conn.request("POST", path, body)
                raw = conn.getresponse().read()
                dt = time.perf_counter() - t0
                if not np.array_equal(
                    np.frombuffer(raw, dtype="<i4"), vals + delta
                ):
                    raise RuntimeError(f"multi-tenant parity FAILED ({name})")
                lats.append(dt)
                counts[i] += 1
            conn.close()
        except Exception as e:  # pragma: no cover — failure path
            errors.append(e)
            stop.set()
            try:
                start_bar.abort()
            except Exception:
                pass

    ts = [
        _threading.Thread(target=one_client, args=(i,)) for i in range(clients)
    ]
    base = f"http://{host}:{port}"
    usage_delta = None
    try:
        usage_before = _scrape_usage(base)
        for t in ts:
            t.start()
        start_bar.wait()
        t0 = time.perf_counter()
        time.sleep(seconds)
        stop.set()
        for t in ts:
            t.join()
        elapsed = time.perf_counter() - t0
        if errors:
            raise errors[0]
        # the per-program attribution story rides the artifact (r12):
        # every tenant's cpu/native/queue seconds for THIS capture, plus
        # the conservation ratio bench-smoke gates
        usage_delta = _usage_delta(usage_before, _scrape_usage(base))
    finally:
        stop.set()
        master.pause()
        reg.close()
        httpd.shutdown()

    per_program = []
    agg_lats = []
    agg_reqs = 0
    for j, (name, _) in enumerate(tenants):
        lats = [
            x for i in range(clients) if i % len(tenants) == j
            for x in lat_per_client[i]
        ]
        n_reqs = sum(
            counts[i] for i in range(clients) if i % len(tenants) == j
        )
        arr = np.asarray(lats) * 1e3 if lats else np.asarray([0.0])
        per_program.append({
            "program": name,
            "clients": sum(
                1 for i in range(clients) if i % len(tenants) == j
            ),
            "requests": n_reqs,
            "p50_ms": round(float(np.percentile(arr, 50)), 3),
            "p99_ms": round(float(np.percentile(arr, 99)), 3),
            "throughput": round(n_reqs * payload_values / elapsed, 1),
        })
        agg_lats.extend(lats)
        agg_reqs += n_reqs
    agg_arr = np.asarray(agg_lats) * 1e3 if agg_lats else np.asarray([0.0])
    out = {
        "engine": engine,
        "batch": batch,
        "clients": clients,
        "payload_values": payload_values,
        "programs": per_program,
        "usage_delta": usage_delta,
        "aggregate": {
            "requests": agg_reqs,
            "p50_ms": round(float(np.percentile(agg_arr, 50)), 3),
            "p99_ms": round(float(np.percentile(agg_arr, 99)), 3),
            "throughput": round(agg_reqs * payload_values / elapsed, 1),
        },
    }
    for p in per_program:
        print(
            f"# multi-tenant: {p['program']} C={p['clients']} "
            f"reqs={p['requests']} p50={p['p50_ms']:.2f}ms "
            f"p99={p['p99_ms']:.2f}ms throughput={p['throughput']:.0f}/s",
            file=sys.stderr,
        )
    print(
        f"# multi-tenant aggregate: C={clients} reqs={agg_reqs} "
        f"p50={out['aggregate']['p50_ms']:.2f}ms "
        f"p99={out['aggregate']['p99_ms']:.2f}ms "
        f"throughput={out['aggregate']['throughput']:.0f}/s",
        file=sys.stderr,
    )
    return out


def bench_overload(
    good_clients=64,
    flood_clients=16,
    payload_values=64,
    flood_payload_values=512,
    batch=None,
    in_cap=128,
    chunk_steps=2048,
    seconds=4.0,
    flood_quota_frac=0.05,
    flood_pause_ms=5.0,
    http_workers=4,
    fleet_procs=4,
    engine="auto",
    timeout=120.0,
):
    """The overload drill (r14): offered load far past capacity across
    two tenants, shed at the DOOR by the production edge.

    Phase 1 (baseline): 64 keep-alive clients of the in-quota tenant,
    no flood — the no-overload 64-lane rate this host serves right now.
    Phase 2 (overload): the key file hot-reloads a `vps` quota onto the
    flood tenant at `flood_quota_frac` of the measured baseline, then
    `good_clients` in-quota clients run concurrently with
    `flood_clients` flooding clients that fire as fast as responses
    return, ignoring the 429s' Retry-After — a sustained offered load
    several times capacity.

    The drill's contract, asserted in the payload's `ok`:
      * every rejection is a typed 429 WITH Retry-After, decided at the
        edge (zero ComputeTimeouts / 5xx for anything admitted);
      * the flooding tenant absorbs the whole shed; the in-quota
        tenant's error count is ZERO;
      * goodput (successfully served values/s, both tenants) holds
        >= 85% of the same-run no-overload baseline;
      * offered load >= 4x the baseline (`offered_x` in the payload).

    Runs the r8 production topology — SO_REUSEPORT frontend workers over
    the compute plane, where frame-level edge decisions amortize the
    rejection cost — with subprocess client fleets (client-side Python
    must not share the harness GIL).  Committed as BENCH_cpu_r14.json;
    bench_smoke gates goodput at 50% of the committed capture.
    """
    import subprocess
    import tempfile
    import threading as _threading

    import jax

    from misaka_tpu import networks
    from misaka_tpu.runtime import edge as edge_mod
    from misaka_tpu.runtime import frontends
    from misaka_tpu.runtime.master import MasterNode, make_http_server

    on_tpu = jax.devices()[0].platform == "tpu"
    if batch is None:
        batch = 32768 if on_tpu else 1024
    tmp = tempfile.mkdtemp(prefix="misaka-overload-")
    keyfile = os.path.join(tmp, "api_keys.json")

    def write_keys(flood_quota: str | None):
        entries = [{"key": "good-key", "tenant": "tenant-good"},
                   {"key": "flood-key", "tenant": "tenant-flood"}]
        if flood_quota is not None:
            entries[1]["quota"] = flood_quota
        with open(keyfile, "w") as f:
            json.dump({"keys": entries}, f)
        # jump the mtime so the engine-side stat (0.5s throttle) sees it
        os.utime(keyfile, (time.time() + 60, time.time() + 60))

    write_keys(None)
    prev_keys = os.environ.get("MISAKA_API_KEYS")
    os.environ["MISAKA_API_KEYS"] = keyfile
    top = networks.add2(in_cap=in_cap, out_cap=in_cap, stack_cap=16)
    master = MasterNode(top, chunk_steps=chunk_steps, batch=batch,
                        engine=engine)
    httpd = make_http_server(master, port=0)
    _threading.Thread(target=httpd.serve_forever, daemon=True).start()
    host, engine_port = "127.0.0.1", httpd.server_address[1]
    plane_path = f"/tmp/misaka-overload-plane-{os.getpid()}.sock"
    plane = frontends.start_compute_plane(master, plane_path)
    port = frontends.pick_free_port()
    frontend_procs = frontends.spawn_frontends(
        http_workers, port, f"http://{host}:{engine_port}", plane_path
    )
    if not frontends.wait_ready(port):
        raise RuntimeError("frontend workers did not come up")
    master.run()

    def run_fleets(specs):
        """[(clients, key, mode, seed, payload)] -> per-mode results."""
        procs = []
        for clients, key, mode, seed, payload in specs:
            n_procs = min(fleet_procs, clients)
            per = [clients // n_procs + (1 if i < clients % n_procs else 0)
                   for i in range(n_procs)]
            pause = flood_pause_ms if mode == "flood" else 0.0
            for i in range(n_procs):
                procs.append((mode, subprocess.Popen(
                    [sys.executable, os.path.abspath(__file__),
                     "--overload-fleet", host, str(port), str(per[i]),
                     str(seconds), str(payload), str(100 + seed + i),
                     key, mode, str(pause)],
                    stdout=subprocess.PIPE,
                    env={**os.environ, "JAX_PLATFORMS": "cpu"},
                )))
        outs = [(m, json.loads(p.communicate(timeout=timeout)[0]))
                for m, p in procs]
        merged = {}
        for mode, o in outs:
            agg = merged.setdefault(mode, {
                "ok": 0, "rejected": 0, "missing_retry_after": 0,
                "errors": [], "elapsed_s": 0.0, "lats_ms": [],
            })
            agg["ok"] += o["ok"]
            agg["rejected"] += o["rejected"]
            agg["missing_retry_after"] += o["missing_retry_after"]
            agg["errors"].extend(o["errors"])
            agg["elapsed_s"] = max(agg["elapsed_s"], o["elapsed_s"])
            agg["lats_ms"].extend(o["lats_ms"])
        return merged

    try:
        # --- phase 1: the no-overload baseline (the same client shape
        # the in-quota tenant keeps during the flood) --------------------
        base = run_fleets([
            (good_clients, "good-key", "good", 0, payload_values),
        ])["good"]
        if base["errors"]:
            raise RuntimeError(f"baseline failed: {base['errors'][0]}")
        baseline_vps = base["ok"] * payload_values / base["elapsed_s"]
        base_lats = np.asarray(base["lats_ms"] or [0.0])
        print(
            f"# overload baseline: C={good_clients} "
            f"goodput={baseline_vps:.0f}/s "
            f"p99={float(np.percentile(base_lats, 99)):.2f}ms",
            file=sys.stderr,
        )
        # --- phase 2: hot-reload the flood quota, then flood ------------
        flood_vps = max(1.0, baseline_vps * flood_quota_frac)
        write_keys(f"vps<{flood_vps:.0f}")
        time.sleep(1.2)  # past the key file's 0.5s stat throttle
        merged = run_fleets([
            (good_clients, "good-key", "good", 10, payload_values),
            (flood_clients, "flood-key", "flood", 50,
             flood_payload_values),
        ])
        good, flood = merged["good"], merged["flood"]
        elapsed = max(good["elapsed_s"], flood["elapsed_s"])
        served_values = (
            good["ok"] * payload_values
            + flood["ok"] * flood_payload_values
        )
        attempts = good["ok"] + flood["ok"] + flood["rejected"]
        goodput = served_values / elapsed
        offered = (
            good["ok"] * payload_values
            + (flood["ok"] + flood["rejected"]) * flood_payload_values
        ) / elapsed
        admitted_lats = np.asarray(
            (good["lats_ms"] + flood["lats_ms"]) or [0.0]
        )
        rejection_ratio = flood["rejected"] / max(1, attempts)
        out = {
            "engine": master.engine_name,
            "batch": batch,
            "http_workers": http_workers,
            "payload_values": payload_values,
            "flood_payload_values": flood_payload_values,
            "good_clients": good_clients,
            "flood_clients": flood_clients,
            "flood_quota_vps": round(flood_vps, 1),
            "baseline": {
                "clients": good_clients,
                "goodput": round(baseline_vps, 1),
                "p50_ms": round(float(np.percentile(base_lats, 50)), 3),
                "p99_ms": round(float(np.percentile(base_lats, 99)), 3),
            },
            "overload": {
                "goodput": round(goodput, 1),
                "offered": round(offered, 1),
                "offered_x": round(offered / max(baseline_vps, 1.0), 2),
                "rejection_ratio": round(rejection_ratio, 4),
                "rejected": flood["rejected"],
                "admitted_p50_ms": round(
                    float(np.percentile(admitted_lats, 50)), 3),
                "admitted_p99_ms": round(
                    float(np.percentile(admitted_lats, 99)), 3),
                "good_tenant_errors": len(good["errors"]),
                "flood_tenant_untyped": len(flood["errors"]),
                "missing_retry_after": flood["missing_retry_after"],
            },
            "goodput_ratio": round(goodput / max(baseline_vps, 1.0), 4),
        }
        out["ok"] = bool(
            not good["errors"]
            and not flood["errors"]
            and flood["rejected"] > 0
            and flood["missing_retry_after"] == 0
            and out["goodput_ratio"] >= 0.85
            and out["overload"]["offered_x"] >= 4.0
        )
        print(
            f"# overload drill: goodput={goodput:.0f}/s "
            f"({out['goodput_ratio']:.2f}x baseline), "
            f"offered={out['overload']['offered_x']:.1f}x, "
            f"rejected={flood['rejected']} "
            f"(ratio {rejection_ratio:.2f}), "
            f"admitted p99={out['overload']['admitted_p99_ms']:.1f}ms, "
            f"good-tenant errors={len(good['errors'])} -> "
            f"{'OK' if out['ok'] else 'FAILED'}",
            file=sys.stderr,
        )
        return out
    finally:
        for p in frontend_procs:
            p.terminate()
        plane.close()
        master.pause()
        httpd.shutdown()
        edge_mod.reset()
        if prev_keys is None:
            os.environ.pop("MISAKA_API_KEYS", None)
        else:
            os.environ["MISAKA_API_KEYS"] = prev_keys


def bench_tracing_ab(pairs=6):
    """Request-tracing overhead A/B (ISSUE r10 budget: mean served-
    throughput ratio >= 0.95 on both lanes, tracing on vs the
    MISAKA_TRACE_REQUESTS=0 kill switch, toggled live via
    tracespan.configure between measurements).

    Both lanes run against ONE shared master + HTTP server booted once,
    ABBA pair ordering.  Fresh-stack-per-measurement was tried first and
    could not resolve the effect: identical configs varied +-25% lane to
    lane (thread-placement lottery across pool/frontend/fleet
    oversubscription), an order of magnitude above the cost being
    measured.  The conc64 lane is the COMMITTED r8 concurrency_sweep
    harness (64 in-process keep-alive clients posting 64-value raw
    payloads straight at the engine) — the frontend-plane variant of
    this lane is a saturated-shared-box measurement whose closed loop
    amplifies ANY extra cycles ~10x (client fleets, 12 workers, and the
    24-thread native pool all compete for the same cores as the engine;
    measured and documented in docs/OBSERVABILITY.md "Overhead").

    sys.setswitchinterval(1ms) runs here as in the production serving
    path (app.py): at the default 5ms, GIL handoff after the
    GIL-released native chunk turns microseconds of added Python on any
    thread into ~0.3ms/chunk of convoy latency — the A/B must measure
    the production configuration, not the amplifier.
    """
    import threading as _threading
    import urllib.request
    import http.client as _http_client

    from misaka_tpu import networks
    from misaka_tpu.runtime.master import MasterNode, make_http_server
    from misaka_tpu.utils import tracespan

    sys.setswitchinterval(0.001)
    batch, in_cap, threads, waves = 1024, 128, 8, 4
    top = networks.add2(in_cap=in_cap, out_cap=in_cap, stack_cap=16)
    master = MasterNode(top, chunk_steps=2048, batch=batch, engine="native")
    httpd = make_http_server(master, port=0)
    _threading.Thread(target=httpd.serve_forever, daemon=True).start()
    host, port = "127.0.0.1", httpd.server_address[1]
    url = f"http://{host}:{port}/compute_raw?spread=1"
    master.run()
    rng = np.random.default_rng(1)
    per_request = (batch // threads) * in_cap

    def raw_lane():
        """The big-batch lane: bench_served's shape on the shared stack."""
        reqs = [
            [
                (v := rng.integers(-1000, 1000, size=per_request)
                 .astype(np.int32)),
                np.ascontiguousarray(v, "<i4").tobytes(), None,
            ]
            for _ in range(threads * waves)
        ]
        errors = []

        def worker(chunk):
            try:
                for item in chunk:
                    req = urllib.request.Request(
                        url, data=item[1], method="POST"
                    )
                    with urllib.request.urlopen(req, timeout=120) as r:
                        item[2] = r.read()
            except Exception as e:  # pragma: no cover
                errors.append(e)

        ws = [
            _threading.Thread(target=worker, args=(reqs[i::threads],))
            for i in range(threads)
        ]
        t0 = time.perf_counter()
        for t in ws:
            t.start()
        for t in ws:
            t.join()
        elapsed = time.perf_counter() - t0
        if errors:
            raise errors[0]
        for vals, _, raw in reqs:
            if not np.array_equal(np.frombuffer(raw, "<i4"), vals + 2):
                raise RuntimeError("trace A/B raw parity FAILED")
        return len(reqs) * per_request / elapsed

    def conc_lane(seconds=2.0, c=64, payload_values=64):
        """The committed 64-client small-request lane (r8 harness): C
        in-process keep-alive clients, each one persistent connection."""
        rng2 = np.random.default_rng(11)
        bodies = []
        for _ in range(8):
            vals = rng2.integers(
                -1000, 1000, size=payload_values
            ).astype(np.int32)
            bodies.append((vals, np.ascontiguousarray(vals, "<i4").tobytes()))
        counts = [0] * c
        errors = []
        stop = _threading.Event()

        def one_client(i):
            try:
                conn = _http_client.HTTPConnection(host, port, timeout=60)
                k = 0
                while not stop.is_set():
                    vals, body = bodies[k % 8]
                    conn.request("POST", "/compute_raw?spread=1", body)
                    raw = conn.getresponse().read()
                    if not np.array_equal(
                        np.frombuffer(raw, dtype="<i4"), vals + 2
                    ):
                        raise RuntimeError("trace A/B sweep parity FAILED")
                    counts[i] += 1
                    k += 1
                conn.close()
            except Exception as e:  # pragma: no cover
                errors.append(e)
                stop.set()

        ts = [
            _threading.Thread(target=one_client, args=(i,)) for i in range(c)
        ]
        t0 = time.perf_counter()
        for t in ts:
            t.start()
        time.sleep(seconds)
        stop.set()
        for t in ts:
            t.join()
        elapsed = time.perf_counter() - t0
        if errors:
            raise errors[0]
        return sum(counts) * payload_values / elapsed

    def set_tracing(on):
        tracespan.configure({} if on else {"MISAKA_TRACE_REQUESTS": "0"})

    conc_pairs = pairs * 2
    out = {
        "method": (
            f"tracing on vs MISAKA_TRACE_REQUESTS=0 (tracespan.configure, "
            f"live toggle), ONE shared master + HTTP server, ABBA pair "
            f"ordering, switchinterval=1ms as in production serving; raw "
            f"= {pairs} pairs of 8 threads x {waves} waves of "
            f"{per_request}-value /compute_raw; conc64 = {conc_pairs} "
            f"pairs of the committed r8 concurrency lane (64 in-process "
            f"keep-alive clients x 64-value payloads x 2s, direct to the "
            f"engine; the noisier lane gets 2x the pairs)"
        ),
        "baseline_raw": [], "instrumented_raw": [],
        "baseline_conc64": [], "instrumented_conc64": [],
    }
    try:
        for on in (False, True):  # warm both paths end to end
            set_tracing(on)
            raw_lane()
            conc_lane(seconds=1.0)
        for i in range(pairs):
            for on in (False, True) if i % 2 == 0 else (True, False):
                set_tracing(on)
                raw = raw_lane()
                key = "instrumented" if on else "baseline"
                out[key + "_raw"].append(round(raw, 1))
                print(
                    f"# tracing A/B raw pair {i} {'on ' if on else 'off'}: "
                    f"{raw:.0f}/s",
                    file=sys.stderr,
                )
        for i in range(conc_pairs):
            for on in (False, True) if i % 2 == 0 else (True, False):
                set_tracing(on)
                conc = conc_lane()
                key = "instrumented" if on else "baseline"
                out[key + "_conc64"].append(round(conc, 1))
                print(
                    f"# tracing A/B conc64 pair {i} "
                    f"{'on ' if on else 'off'}: {conc:.0f}/s",
                    file=sys.stderr,
                )
    finally:
        tracespan.configure()
        master.pause()
        httpd.shutdown()
    out["raw_mean_ratio"] = round(
        sum(out["instrumented_raw"]) / sum(out["baseline_raw"]), 4
    )
    out["conc64_mean_ratio"] = round(
        sum(out["instrumented_conc64"]) / sum(out["baseline_conc64"]), 4
    )
    return out


def bench_capture_ab(pairs=6):
    """Traffic-capture overhead A/B (ISSUE r20 budget: MEDIAN served-
    throughput pair ratio >= 0.95 on both lanes, recorder ARMED at
    sample=1.0 vs idle).

    Same discipline as the committed r10/r18 A/Bs (bench_tracing_ab):
    ONE shared master + HTTP server, ABBA pair ordering, production 1ms
    switch interval, median pair ratios (scheduler-lottery collapses on
    a saturated box swing a mean).  Three recorder states measured:

      killed    MISAKA_CAPTURE=0 — the kill switch; every hook is one
                module-attribute load (reported as killed_vs_idle, the
                `disabled path measured` check: must be ~1.0)
      idle      capture importable and armed-able, not recording — the
                default production state (the A/B BASELINE)
      recording sample=1.0, every request's payload copied into the
                ring (the A/B INSTRUMENTED side; the honest worst case —
                production sampling records a fraction of this)

    The raw lane is the recorder's worst case by construction: 16384
    int32s per request means each record memcpys ~128KiB of payload
    into the ring and churns eviction at the 16MB default budget.
    """
    import threading as _threading
    import urllib.request
    import http.client as _http_client

    from misaka_tpu import networks
    from misaka_tpu.runtime import capture as _capture
    from misaka_tpu.runtime.master import MasterNode, make_http_server

    sys.setswitchinterval(0.001)
    batch, in_cap, threads, waves = 1024, 128, 8, 4
    top = networks.add2(in_cap=in_cap, out_cap=in_cap, stack_cap=16)
    master = MasterNode(top, chunk_steps=2048, batch=batch, engine="native")
    httpd = make_http_server(master, port=0)
    _threading.Thread(target=httpd.serve_forever, daemon=True).start()
    host, port = "127.0.0.1", httpd.server_address[1]
    url = f"http://{host}:{port}/compute_raw?spread=1"
    master.run()
    rng = np.random.default_rng(1)
    per_request = (batch // threads) * in_cap

    def raw_lane():
        reqs = [
            [
                (v := rng.integers(-1000, 1000, size=per_request)
                 .astype(np.int32)),
                np.ascontiguousarray(v, "<i4").tobytes(), None,
            ]
            for _ in range(threads * waves)
        ]
        errors = []

        def worker(chunk):
            try:
                for item in chunk:
                    req = urllib.request.Request(
                        url, data=item[1], method="POST"
                    )
                    with urllib.request.urlopen(req, timeout=120) as r:
                        item[2] = r.read()
            except Exception as e:  # pragma: no cover
                errors.append(e)

        ws = [
            _threading.Thread(target=worker, args=(reqs[i::threads],))
            for i in range(threads)
        ]
        t0 = time.perf_counter()
        for t in ws:
            t.start()
        for t in ws:
            t.join()
        elapsed = time.perf_counter() - t0
        if errors:
            raise errors[0]
        for vals, _, raw in reqs:
            if not np.array_equal(np.frombuffer(raw, "<i4"), vals + 2):
                raise RuntimeError("capture A/B raw parity FAILED")
        return len(reqs) * per_request / elapsed

    def conc_lane(seconds=2.0, c=64, payload_values=64):
        rng2 = np.random.default_rng(11)
        bodies = []
        for _ in range(8):
            vals = rng2.integers(
                -1000, 1000, size=payload_values
            ).astype(np.int32)
            bodies.append((vals, np.ascontiguousarray(vals, "<i4").tobytes()))
        counts = [0] * c
        errors = []
        stop = _threading.Event()

        def one_client(i):
            try:
                conn = _http_client.HTTPConnection(host, port, timeout=60)
                k = 0
                while not stop.is_set():
                    vals, body = bodies[k % 8]
                    conn.request("POST", "/compute_raw?spread=1", body)
                    raw = conn.getresponse().read()
                    if not np.array_equal(
                        np.frombuffer(raw, dtype="<i4"), vals + 2
                    ):
                        raise RuntimeError("capture A/B sweep parity FAILED")
                    counts[i] += 1
                    k += 1
                conn.close()
            except Exception as e:  # pragma: no cover
                errors.append(e)
                stop.set()

        ts = [
            _threading.Thread(target=one_client, args=(i,)) for i in range(c)
        ]
        t0 = time.perf_counter()
        for t in ts:
            t.start()
        time.sleep(seconds)
        stop.set()
        for t in ts:
            t.join()
        elapsed = time.perf_counter() - t0
        if errors:
            raise errors[0]
        return sum(counts) * payload_values / elapsed

    def set_state(state):
        if _capture.recording():
            _capture.stop()
        if state == "killed":
            _capture.configure({"MISAKA_CAPTURE": "0"})
        else:
            _capture.configure({"MISAKA_CAPTURE_SAMPLE": "1.0"})
            if state == "recording":
                anchor = _capture.anchor_from_master("default", master)
                _capture.start(
                    anchors={"default": anchor} if anchor else {}
                )

    conc_pairs = pairs * 2
    out = {
        "method": (
            f"recorder armed at sample=1.0 vs idle (capture.start/stop, "
            f"live toggle), ONE shared master + HTTP server, ABBA pair "
            f"ordering, switchinterval=1ms as in production serving; raw "
            f"= {pairs} pairs of 8 threads x {waves} waves of "
            f"{per_request}-value /compute_raw (~128KiB memcpy per "
            f"record, eviction churn at the 16MB budget); conc64 = "
            f"{conc_pairs} pairs of the committed r8 concurrency lane "
            f"(64 keep-alive clients x 64-value payloads x 2s); "
            f"killed_vs_idle = MISAKA_CAPTURE=0 vs idle on the raw lane "
            f"(the kill switch must measure as a no-op)"
        ),
        "baseline_raw": [], "instrumented_raw": [],
        "baseline_conc64": [], "instrumented_conc64": [],
        "killed_raw": [], "idle_raw": [],
    }
    try:
        for state in ("idle", "recording"):  # warm both paths end to end
            set_state(state)
            raw_lane()
            conc_lane(seconds=1.0)
        for i in range(pairs):
            states = (
                ("idle", "recording") if i % 2 == 0
                else ("recording", "idle")
            )
            for state in states:
                set_state(state)
                raw = raw_lane()
                key = (
                    "instrumented" if state == "recording" else "baseline"
                )
                out[key + "_raw"].append(round(raw, 1))
                print(
                    f"# capture A/B raw pair {i} {state:<9}: {raw:.0f}/s",
                    file=sys.stderr,
                )
        for i in range(conc_pairs):
            states = (
                ("idle", "recording") if i % 2 == 0
                else ("recording", "idle")
            )
            for state in states:
                set_state(state)
                conc = conc_lane()
                key = (
                    "instrumented" if state == "recording" else "baseline"
                )
                out[key + "_conc64"].append(round(conc, 1))
                print(
                    f"# capture A/B conc64 pair {i} {state:<9}: "
                    f"{conc:.0f}/s",
                    file=sys.stderr,
                )
        for i in range(max(2, pairs // 2)):
            states = (
                ("idle", "killed") if i % 2 == 0 else ("killed", "idle")
            )
            for state in states:
                set_state(state)
                raw = raw_lane()
                out[("killed" if state == "killed" else "idle") + "_raw"] \
                    .append(round(raw, 1))
                print(
                    f"# capture A/B kill-switch pair {i} {state:<9}: "
                    f"{raw:.0f}/s",
                    file=sys.stderr,
                )
    finally:
        if _capture.recording():
            _capture.stop()
        _capture.configure()
        master.pause()
        httpd.shutdown()
    for lane in ("raw", "conc64"):
        base = out[f"baseline_{lane}"]
        inst = out[f"instrumented_{lane}"]
        ratios = sorted(round(b and i / b, 4) for i, b in zip(inst, base))
        out[f"{lane}_pair_ratios"] = ratios
        out[f"{lane}_mean_ratio"] = round(sum(inst) / sum(base), 4)
        n = len(ratios)
        out[f"{lane}_median_ratio"] = round(
            ratios[n // 2] if n % 2
            else (ratios[n // 2 - 1] + ratios[n // 2]) / 2, 4
        )
    out["killed_vs_idle_ratio"] = round(
        sum(out["killed_raw"]) / sum(out["idle_raw"]), 4
    ) if out["idle_raw"] else None
    return out


def bench_model_replay(model_path, seconds=8.0, clients=32):
    """Drive a capture-fitted load model (tools/replay.py --emit-model /
    capture.fit_load_model) against a served engine: open-loop Poisson
    arrivals at the fitted rate, payload sizes drawn from the fitted
    value histogram, tenant mix preserved as labels.  Reports achieved
    vs offered rate and latency percentiles — the `bench.py --model`
    lane that turns yesterday's production traffic into today's
    regression harness."""
    import http.client as _http_client
    import threading as _threading

    from misaka_tpu import networks
    from misaka_tpu.runtime.master import MasterNode, make_http_server

    with open(model_path) as f:
        model = json.load(f)
    if model.get("format") != 1:
        raise SystemExit(f"unsupported load-model format: {model_path}")
    rate = float(model["arrival"]["rate_rps"])
    hist = model["values"]["hist"] or [[1, 1]]
    # per-tenant arrival rates (fitted from the durable TSDB tier) beat
    # the capture-window request fractions when the model carries them
    tenants = sorted((
        model.get("tenants_arrival") or model.get("tenants")
        or {"default": 1.0}
    ).items())
    diurnal = (model.get("diurnal") or {}).get("hour_weights_utc")

    sys.setswitchinterval(0.001)
    top = networks.add2(in_cap=4096, out_cap=4096, stack_cap=16)
    master = MasterNode(top, chunk_steps=2048, batch=256, engine="native")
    httpd = make_http_server(master, port=0)
    _threading.Thread(target=httpd.serve_forever, daemon=True).start()
    host, port = "127.0.0.1", httpd.server_address[1]
    master.run()

    rng = np.random.default_rng(5)
    uppers = np.array([u for u, _ in hist], dtype=np.int64)
    weights = np.array([w for _, w in hist], dtype=np.float64)
    weights /= weights.sum()
    t_weights = np.array([w for _, w in tenants], dtype=np.float64)
    t_weights /= t_weights.sum()

    # open loop: one global Poisson arrival schedule, sliced round-robin
    # across the client connections (a closed loop would let a slow
    # server hide behind its own backpressure)
    n_arrivals = max(1, int(rate * seconds))
    gaps = rng.exponential(1.0 / max(rate, 1e-9), size=n_arrivals)
    if diurnal:
        # replay a COMPRESSED day: arrival k lands at simulated UTC
        # hour 24k/n, and the local Poisson intensity scales by that
        # hour's fitted weight (weights average 1.0, so the run's total
        # offered rate stays the headline `rate`)
        w = np.clip(np.array(diurnal, dtype=np.float64), 1e-3, None)
        hour_idx = np.minimum(
            np.arange(n_arrivals) * 24 // max(1, n_arrivals), 23
        )
        gaps = gaps / w[hour_idx]
    arrivals = np.cumsum(gaps)
    sizes = uppers[rng.choice(len(uppers), size=n_arrivals, p=weights)]
    sizes = np.minimum(sizes, 4096)
    tenant_idx = rng.choice(len(tenants), size=n_arrivals, p=t_weights)

    lat: list = []
    sent = [0] * clients
    errors: list = []
    lock = _threading.Lock()
    t_start = time.perf_counter()

    def one_client(ci):
        try:
            conn = _http_client.HTTPConnection(host, port, timeout=60)
            my_lat = []
            for k in range(ci, n_arrivals, clients):
                wait = arrivals[k] - (time.perf_counter() - t_start)
                if wait > 0:
                    time.sleep(wait)
                n = int(sizes[k])
                vals = rng.integers(-1000, 1000, size=n).astype(np.int32)
                body = np.ascontiguousarray(vals, "<i4").tobytes()
                t0 = time.perf_counter()
                conn.request(
                    "POST", "/compute_raw?spread=1", body,
                    {"X-Misaka-Tenant": tenants[tenant_idx[k]][0]},
                )
                raw = conn.getresponse().read()
                my_lat.append(time.perf_counter() - t0)
                if not np.array_equal(
                    np.frombuffer(raw, dtype="<i4"), vals + 2
                ):
                    raise RuntimeError("model-replay parity FAILED")
                sent[ci] += 1
            conn.close()
            with lock:
                lat.extend(my_lat)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    ts = [
        _threading.Thread(target=one_client, args=(i,))
        for i in range(clients)
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    elapsed = time.perf_counter() - t_start
    master.pause()
    httpd.shutdown()
    if errors:
        raise errors[0]
    la = np.array(sorted(lat))
    done = int(sum(sent))
    return {
        "model": model_path,
        "diurnal": bool(diurnal),
        "offered_rps": round(rate, 2),
        "achieved_rps": round(done / elapsed, 2),
        "requests": done,
        "values": int(sizes[:done].sum()),
        "duration_s": round(elapsed, 2),
        "tenants": {name: int((tenant_idx == i).sum())
                    for i, (name, _) in enumerate(tenants)},
        "latency_ms": {
            "p50": round(float(np.percentile(la, 50)) * 1e3, 3),
            "p90": round(float(np.percentile(la, 90)) * 1e3, 3),
            "p99": round(float(np.percentile(la, 99)) * 1e3, 3),
            "max": round(float(la.max()) * 1e3, 3),
        } if len(la) else None,
    }


def bench_edge_native_ab(pairs=4, seconds=2.0, clients=64,
                         payload_values=64, workers=2):
    """Native-edge serving A/B (ISSUE r19): the C++ epoll frontend tier
    (native/frontend.cpp) vs the r8 CPython SO_REUSEPORT worker tier,
    measured as 64 keep-alive clients of small /compute_raw payloads —
    req/s plus p50/p99 request latency.

    ONE shared master + compute plane serves BOTH tiers simultaneously
    (the native edge on one port, the supervised worker pool on
    another, both shipping frames into the same plane), so an ABBA pair
    toggles ONLY which public port the client fleet hammers — engine
    throughput, plane scheduling, and box load are common-mode.  The
    per-pair arrays are embedded for audit; the headline is the MEDIAN
    across pairs (the closed-loop lane's scheduler collapses swing a
    mean, as in every served A/B since r10).

    On a core-starved box (1-CPU CI containers) the two tiers contend
    for the same cycles as the clients and the engine: the ratio then
    measures the scheduler, not the edge — callers gate on it only on
    >= CAPTURE_BOX_CPUS/2 cores (the r17 cross-box discipline), while
    the honest numbers are still recorded.
    """
    import http.client as _http_client
    import tempfile as _tempfile
    import threading as _threading

    from misaka_tpu import networks
    from misaka_tpu.runtime import frontends
    from misaka_tpu.runtime.master import MasterNode, make_http_server

    sys.setswitchinterval(0.001)
    batch, in_cap = 1024, 128
    top = networks.add2(in_cap=in_cap, out_cap=in_cap, stack_cap=16)
    master = MasterNode(top, chunk_steps=2048, batch=batch, engine="native")
    httpd = make_http_server(master, port=0)
    _threading.Thread(target=httpd.serve_forever, daemon=True).start()
    engine_port = httpd.server_address[1]
    master.run()
    plane_path = os.path.join(
        _tempfile.mkdtemp(prefix="msk-edge-ab-"), "plane.sock"
    )
    plane = frontends.start_compute_plane(master, plane_path)
    native = frontends.NativeFrontendSupervisor(
        port=0, proxy_port=engine_port, plane_path=plane_path,
        plane_conns=2,
    )
    worker_port = frontends.pick_free_port()
    sup = frontends.FrontendSupervisor(
        workers, worker_port, f"http://127.0.0.1:{engine_port}",
        plane_path, plane_conns=2,
    )

    def wait_tier(port):
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                conn = _http_client.HTTPConnection(
                    "127.0.0.1", port, timeout=5
                )
                conn.request("GET", "/healthz")
                ok = conn.getresponse()
                ok.read()
                conn.close()
                if ok.status == 200:
                    return
            except (OSError, _http_client.HTTPException):
                pass
            time.sleep(0.2)
        raise RuntimeError(f"serving tier on :{port} did not come up")

    wait_tier(native.port)
    wait_tier(worker_port)

    def lane(port, lane_seconds=seconds, c=clients):
        rng = np.random.default_rng(5)
        bodies = []
        for _ in range(8):
            vals = rng.integers(
                -1000, 1000, size=payload_values
            ).astype(np.int32)
            bodies.append((vals, np.ascontiguousarray(vals, "<i4").tobytes()))
        counts = [0] * c
        lats = [[] for _ in range(c)]
        errors = []
        stop = _threading.Event()

        def one_client(i):
            try:
                conn = _http_client.HTTPConnection(
                    "127.0.0.1", port, timeout=60
                )
                k = 0
                while not stop.is_set():
                    vals, body = bodies[k % 8]
                    t0 = time.perf_counter()
                    conn.request("POST", "/compute_raw", body)
                    raw = conn.getresponse().read()
                    lats[i].append(time.perf_counter() - t0)
                    if not np.array_equal(
                        np.frombuffer(raw, dtype="<i4"), vals + 2
                    ):
                        raise RuntimeError("edge-native A/B parity FAILED")
                    counts[i] += 1
                    k += 1
                conn.close()
            except Exception as e:  # pragma: no cover
                errors.append(e)
                stop.set()

        ts = [
            _threading.Thread(target=one_client, args=(i,)) for i in range(c)
        ]
        t0 = time.perf_counter()
        for t in ts:
            t.start()
        time.sleep(lane_seconds)
        stop.set()
        for t in ts:
            t.join()
        elapsed = time.perf_counter() - t0
        if errors:
            raise errors[0]
        all_l = np.sort(np.concatenate(
            [np.asarray(x) for x in lats if x] or [np.zeros(1)]
        ))
        return {
            "req_s": round(sum(counts) / elapsed, 1),
            "p50_ms": round(
                float(all_l[int(0.5 * (len(all_l) - 1))]) * 1e3, 3
            ),
            "p99_ms": round(
                float(all_l[int(0.99 * (len(all_l) - 1))]) * 1e3, 3
            ),
        }

    out = {
        "method": (
            f"C++ native edge vs {workers} supervised CPython workers, "
            f"BOTH live on ONE shared master + compute plane (only the "
            f"hammered port toggles); {pairs} ABBA pairs of {clients} "
            f"in-process keep-alive clients x {payload_values}-value "
            f"/compute_raw x {seconds}s, switchinterval=1ms as in "
            f"production serving; headline = MEDIAN req/s across pairs, "
            f"per-pair arrays embedded"
        ),
        "cores": os.cpu_count(),
        "native_pairs": [], "worker_pairs": [],
    }
    try:
        for p in (native.port, worker_port):  # warm both tiers end to end
            lane(p, lane_seconds=0.8)
        for i in range(pairs):
            order = [("native", native.port), ("worker", worker_port)]
            if i % 2 == 1:
                order.reverse()
            for name, p in order:
                r = lane(p)
                out[name + "_pairs"].append(r)
                print(
                    f"# edge-native A/B pair {i} {name}: "
                    f"{r['req_s']:.0f} req/s, p50 {r['p50_ms']}ms, "
                    f"p99 {r['p99_ms']}ms",
                    file=sys.stderr,
                )
    finally:
        native.close()
        sup.close()
        plane.close()
        master.pause()
        httpd.shutdown()
    for name in ("native", "worker"):
        rows = out[name + "_pairs"]
        out[name + "_req_s_median"] = round(
            float(np.median([r["req_s"] for r in rows])), 1
        )
        out[name + "_p50_ms_median"] = round(
            float(np.median([r["p50_ms"] for r in rows])), 3
        )
        out[name + "_p99_ms_median"] = round(
            float(np.median([r["p99_ms"] for r in rows])), 3
        )
    out["speedup"] = round(
        out["native_req_s_median"] / max(1e-9, out["worker_req_s_median"]), 3
    )
    return out


def bench_usage_ab(pairs=6):
    """Observability-plane overhead A/B (ISSUE r12 budget: mean served-
    throughput ratio >= 0.95 on both lanes with usage accounting + SLO
    windows + the stack sampler ALL enabled, vs all three killed).

    Same discipline as the committed r10 tracing A/B (bench_tracing_ab):
    ONE shared master + HTTP server, ABBA pair ordering, production
    1ms switch interval — fresh-stack measurement could not resolve
    effects this small (+-25% thread-placement lottery).  The toggles are
    the real kill switches (MISAKA_USAGE=0 via usage.configure, MISAKA_SLO
    unset via slo.configure, sampler.shutdown), so the measured delta is
    exactly what an operator pays for leaving the plane on.
    """
    import threading as _threading
    import urllib.request
    import http.client as _http_client

    from misaka_tpu import networks
    from misaka_tpu.runtime.master import MasterNode, make_http_server
    from misaka_tpu.runtime import usage as _usage
    from misaka_tpu.utils import sampler as _sampler
    from misaka_tpu.utils import slo as _slo

    sys.setswitchinterval(0.001)
    batch, in_cap, threads, waves = 1024, 128, 8, 4
    top = networks.add2(in_cap=in_cap, out_cap=in_cap, stack_cap=16)
    master = MasterNode(top, chunk_steps=2048, batch=batch, engine="native")
    httpd = make_http_server(master, port=0)
    _threading.Thread(target=httpd.serve_forever, daemon=True).start()
    host, port = "127.0.0.1", httpd.server_address[1]
    url = f"http://{host}:{port}/compute_raw?spread=1"
    master.run()
    rng = np.random.default_rng(2)
    per_request = (batch // threads) * in_cap

    def raw_lane():
        reqs = [
            [
                (v := rng.integers(-1000, 1000, size=per_request)
                 .astype(np.int32)),
                np.ascontiguousarray(v, "<i4").tobytes(), None,
            ]
            for _ in range(threads * waves)
        ]
        errors = []

        def worker(chunk):
            try:
                for item in chunk:
                    req = urllib.request.Request(
                        url, data=item[1], method="POST"
                    )
                    with urllib.request.urlopen(req, timeout=120) as r:
                        item[2] = r.read()
            except Exception as e:  # pragma: no cover
                errors.append(e)

        ws = [
            _threading.Thread(target=worker, args=(reqs[i::threads],))
            for i in range(threads)
        ]
        t0 = time.perf_counter()
        for t in ws:
            t.start()
        for t in ws:
            t.join()
        elapsed = time.perf_counter() - t0
        if errors:
            raise errors[0]
        for vals, _, raw in reqs:
            if not np.array_equal(np.frombuffer(raw, "<i4"), vals + 2):
                raise RuntimeError("usage A/B raw parity FAILED")
        return len(reqs) * per_request / elapsed

    def conc_lane(seconds=2.0, c=64, payload_values=64):
        rng2 = np.random.default_rng(13)
        bodies = []
        for _ in range(8):
            vals = rng2.integers(
                -1000, 1000, size=payload_values
            ).astype(np.int32)
            bodies.append((vals, np.ascontiguousarray(vals, "<i4").tobytes()))
        counts = [0] * c
        errors = []
        stop = _threading.Event()

        def one_client(i):
            try:
                conn = _http_client.HTTPConnection(host, port, timeout=60)
                k = 0
                while not stop.is_set():
                    vals, body = bodies[k % 8]
                    conn.request("POST", "/compute_raw?spread=1", body)
                    raw = conn.getresponse().read()
                    if not np.array_equal(
                        np.frombuffer(raw, dtype="<i4"), vals + 2
                    ):
                        raise RuntimeError("usage A/B sweep parity FAILED")
                    counts[i] += 1
                    k += 1
                conn.close()
            except Exception as e:  # pragma: no cover
                errors.append(e)
                stop.set()

        ts = [
            _threading.Thread(target=one_client, args=(i,)) for i in range(c)
        ]
        t0 = time.perf_counter()
        for t in ts:
            t.start()
        time.sleep(seconds)
        stop.set()
        for t in ts:
            t.join()
        elapsed = time.perf_counter() - t0
        if errors:
            raise errors[0]
        return sum(counts) * payload_values / elapsed

    def set_observability(on):
        """All three subsystems together: the plane ships as one."""
        if on:
            _usage.configure({})
            _slo.configure({
                "MISAKA_SLO": "p99<250ms,err<1%",
            })
            _sampler.ensure_started({})
        else:
            _usage.configure({"MISAKA_USAGE": "0"})
            _slo.configure({})
            _sampler.shutdown()

    conc_pairs = pairs * 3
    out = {
        "method": (
            f"usage accounting + SLO windows (p99<250ms,err<1% armed) + "
            f"67Hz duty-cycle-governed stack sampler, ALL ON vs ALL "
            f"KILLED (usage.configure / slo.configure / sampler.shutdown "
            f"— the real kill switches), ONE shared master + HTTP "
            f"server, ABBA pair ordering, switchinterval=1ms as in "
            f"production; raw = {pairs} pairs of 8 threads x {waves} "
            f"waves of {per_request}-value /compute_raw; conc64 = "
            f"{conc_pairs} pairs of the committed r8 concurrency lane "
            f"(64 in-process keep-alive clients x 64-value payloads x "
            f"2.5s, direct to the engine).  Headline = MEDIAN of the "
            f"matched ABBA pair ratios: the closed-loop 64-thread lane "
            f"occasionally collapses 2-5x in EITHER direction on a "
            f"scheduler lottery (observed both ways across captures), "
            f"and a single collapsed lane swings a 12-pair mean by more "
            f"than the whole 5% budget; the median is robust to those "
            f"one-offs while the full per-pair arrays stay embedded"
        ),
        "baseline_raw": [], "instrumented_raw": [],
        "baseline_conc64": [], "instrumented_conc64": [],
    }
    try:
        for on in (False, True):  # warm both paths end to end
            set_observability(on)
            raw_lane()
            conc_lane(seconds=1.0)
        for i in range(pairs):
            for on in (False, True) if i % 2 == 0 else (True, False):
                set_observability(on)
                raw = raw_lane()
                key = "instrumented" if on else "baseline"
                out[key + "_raw"].append(round(raw, 1))
                print(
                    f"# usage A/B raw pair {i} {'on ' if on else 'off'}: "
                    f"{raw:.0f}/s",
                    file=sys.stderr,
                )
        for i in range(conc_pairs):
            for on in (False, True) if i % 2 == 0 else (True, False):
                set_observability(on)
                conc = conc_lane(seconds=2.5)
                key = "instrumented" if on else "baseline"
                out[key + "_conc64"].append(round(conc, 1))
                print(
                    f"# usage A/B conc64 pair {i} "
                    f"{'on ' if on else 'off'}: {conc:.0f}/s",
                    file=sys.stderr,
                )
    finally:
        _usage.configure()
        _slo.configure()
        master.pause()
        httpd.shutdown()
    for lane in ("raw", "conc64"):
        base = out[f"baseline_{lane}"]
        inst = out[f"instrumented_{lane}"]
        ratios = sorted(round(b and i / b, 4) for i, b in zip(inst, base))
        out[f"{lane}_pair_ratios"] = ratios
        out[f"{lane}_mean_ratio"] = round(sum(inst) / sum(base), 4)
        n = len(ratios)
        out[f"{lane}_median_ratio"] = round(
            ratios[n // 2] if n % 2
            else (ratios[n // 2 - 1] + ratios[n // 2]) / 2, 4
        )
    return out


def bench_obs_ab(pairs=6):
    """Observatory overhead A/B (ISSUE r15 budget: MEDIAN served-
    throughput ratio >= 0.95 on both lanes with the embedded TSDB
    collector + regression watchdog + synthetic canary ALL running at
    production cadence, vs all three shut down).

    Same discipline as the committed r10/r12/r14 A/Bs: ONE shared
    master + HTTP server (registry armed so the canary drives the REAL
    full stack), ABBA pair ordering, production 1ms switch interval,
    median-of-pairs headline with the full arrays embedded.  The
    baseline observability plane (usage + SLO + sampler + tracing)
    stays ON on BOTH sides — this measures the observatory's MARGINAL
    cost, which is what an operator pays for upgrading.
    """
    import threading as _threading
    import urllib.request
    import http.client as _http_client

    from misaka_tpu import networks
    from misaka_tpu.runtime.master import MasterNode, make_http_server
    from misaka_tpu.runtime.registry import ProgramRegistry
    from misaka_tpu.runtime import canary as _canary
    from misaka_tpu.utils import tsdb as _tsdb
    from misaka_tpu.utils import watchdog as _watchdog

    sys.setswitchinterval(0.001)
    batch, in_cap, threads, waves = 1024, 128, 8, 4
    caps = dict(in_cap=in_cap, out_cap=in_cap, stack_cap=16)
    top = networks.add2(**caps)
    master = MasterNode(top, chunk_steps=2048, batch=batch, engine="native")
    registry = ProgramRegistry(None, batch=batch, engine="native", caps=caps)
    registry.seed("default", master, top)
    httpd = make_http_server(master, port=0, registry=registry)
    _threading.Thread(target=httpd.serve_forever, daemon=True).start()
    host, port = "127.0.0.1", httpd.server_address[1]
    url = f"http://{host}:{port}/compute_raw?spread=1"
    master.run()
    rng = np.random.default_rng(2)
    per_request = (batch // threads) * in_cap

    def raw_lane():
        reqs = [
            [
                (v := rng.integers(-1000, 1000, size=per_request)
                 .astype(np.int32)),
                np.ascontiguousarray(v, "<i4").tobytes(), None,
            ]
            for _ in range(threads * waves)
        ]
        errors = []

        def worker(chunk):
            try:
                for item in chunk:
                    req = urllib.request.Request(
                        url, data=item[1], method="POST"
                    )
                    with urllib.request.urlopen(req, timeout=120) as r:
                        item[2] = r.read()
            except Exception as e:  # pragma: no cover
                errors.append(e)

        ws = [
            _threading.Thread(target=worker, args=(reqs[i::threads],))
            for i in range(threads)
        ]
        t0 = time.perf_counter()
        for t in ws:
            t.start()
        for t in ws:
            t.join()
        elapsed = time.perf_counter() - t0
        if errors:
            raise errors[0]
        for vals, _, raw in reqs:
            if not np.array_equal(np.frombuffer(raw, "<i4"), vals + 2):
                raise RuntimeError("obs A/B raw parity FAILED")
        return len(reqs) * per_request / elapsed

    def conc_lane(seconds=2.0, c=64, payload_values=64):
        rng2 = np.random.default_rng(13)
        bodies = []
        for _ in range(8):
            vals = rng2.integers(
                -1000, 1000, size=payload_values
            ).astype(np.int32)
            bodies.append((vals, np.ascontiguousarray(vals, "<i4").tobytes()))
        counts = [0] * c
        errors = []
        stop = _threading.Event()

        def one_client(i):
            try:
                conn = _http_client.HTTPConnection(host, port, timeout=60)
                k = 0
                while not stop.is_set():
                    vals, body = bodies[k % 8]
                    conn.request("POST", "/compute_raw?spread=1", body)
                    raw = conn.getresponse().read()
                    if not np.array_equal(
                        np.frombuffer(raw, dtype="<i4"), vals + 2
                    ):
                        raise RuntimeError("obs A/B sweep parity FAILED")
                    counts[i] += 1
                    k += 1
                conn.close()
            except Exception as e:  # pragma: no cover
                errors.append(e)
                stop.set()

        ts = [
            _threading.Thread(target=one_client, args=(i,)) for i in range(c)
        ]
        t0 = time.perf_counter()
        for t in ts:
            t.start()
        time.sleep(seconds)
        stop.set()
        for t in ts:
            t.join()
        elapsed = time.perf_counter() - t0
        if errors:
            raise errors[0]
        return sum(counts) * payload_values / elapsed

    def set_observatory(on):
        """TSDB collector + watchdog + canary together, at production
        cadence — the observatory ships as one."""
        if on:
            _tsdb.ensure_started({})
            _watchdog.ensure_started({})
            _canary.ensure_started(
                f"http://{host}:{port}", registry=registry,
                server=httpd, environ={},
            )
        else:
            _canary.shutdown()
            _watchdog.shutdown()
            _tsdb.shutdown()

    conc_pairs = pairs * 3
    out = {
        "method": (
            f"embedded TSDB collector (5s interval, 1% duty budget) + "
            f"regression watchdog (default rules) + synthetic canary "
            f"(5s cadence, full stack through the armed registry), ALL "
            f"ON vs ALL SHUT DOWN (tsdb/watchdog/canary shutdown — the "
            f"real kill switches); the r12 plane (usage/SLO/sampler/"
            f"tracing) stays ON on both sides, so this is the "
            f"observatory's MARGINAL cost.  ONE shared master + HTTP "
            f"server + registry, ABBA pair ordering, switchinterval="
            f"1ms as in production; raw = {pairs} pairs of 8 threads x "
            f"{waves} waves of {per_request}-value /compute_raw; conc64 "
            f"= {conc_pairs} pairs of the committed r8 concurrency lane "
            f"(64 in-process keep-alive clients x 64-value payloads x "
            f"2.5s).  Headline = MEDIAN of the matched ABBA pair "
            f"ratios: the closed-loop 64-thread lane collapses 2-5x in "
            f"EITHER direction on scheduler lottery (observed both "
            f"ways across captures), and one collapsed lane swings a "
            f"12-pair mean past the whole 5% budget; the full per-pair "
            f"arrays stay embedded"
        ),
        "baseline_raw": [], "instrumented_raw": [],
        "baseline_conc64": [], "instrumented_conc64": [],
    }
    try:
        for on in (False, True):  # warm both paths end to end
            set_observatory(on)
            raw_lane()
            conc_lane(seconds=1.0)
        for i in range(pairs):
            for on in (False, True) if i % 2 == 0 else (True, False):
                set_observatory(on)
                raw = raw_lane()
                key = "instrumented" if on else "baseline"
                out[key + "_raw"].append(round(raw, 1))
                print(
                    f"# obs A/B raw pair {i} {'on ' if on else 'off'}: "
                    f"{raw:.0f}/s",
                    file=sys.stderr,
                )
        for i in range(conc_pairs):
            for on in (False, True) if i % 2 == 0 else (True, False):
                set_observatory(on)
                conc = conc_lane(seconds=2.5)
                key = "instrumented" if on else "baseline"
                out[key + "_conc64"].append(round(conc, 1))
                print(
                    f"# obs A/B conc64 pair {i} "
                    f"{'on ' if on else 'off'}: {conc:.0f}/s",
                    file=sys.stderr,
                )
    finally:
        set_observatory(False)
        master.pause()
        registry.close()
        httpd.shutdown()
    for lane in ("raw", "conc64"):
        base = out[f"baseline_{lane}"]
        inst = out[f"instrumented_{lane}"]
        ratios = sorted(round(b and i / b, 4) for i, b in zip(inst, base))
        out[f"{lane}_pair_ratios"] = ratios
        out[f"{lane}_mean_ratio"] = round(sum(inst) / sum(base), 4)
        n = len(ratios)
        out[f"{lane}_median_ratio"] = round(
            ratios[n // 2] if n % 2
            else (ratios[n // 2 - 1] + ratios[n // 2]) / 2, 4
        )
    return out


def bench_durable_ab(pairs=6):
    """Durable-telemetry overhead A/B (ISSUE r23 budget: MEDIAN served-
    throughput ratio >= 0.95 on both lanes with the WHOLE durable plane
    armed — TSDB disk spool + long-horizon tier, usage ledger spool,
    always-on capture recording with rotation daemon — vs the plane
    disarmed, i.e. today's in-memory telemetry).

    Same discipline as the committed r15 observatory A/B: ONE shared
    master + HTTP server + registry, ABBA pair ordering, production 1ms
    switch interval, median-of-pairs headline with the full arrays
    embedded.  The in-memory observability stack (TSDB collector,
    usage, SLO, sampler, tracing) stays ON on BOTH sides — the ratio
    isolates exactly what MISAKA_TSDB_DIR adds: fsync'd spool appends
    on the collector tick, the usage flusher, and per-request capture
    records."""
    import shutil
    import tempfile
    import threading as _threading
    import urllib.request
    import http.client as _http_client

    from misaka_tpu import networks
    from misaka_tpu.runtime.master import MasterNode, make_http_server
    from misaka_tpu.runtime.registry import ProgramRegistry
    from misaka_tpu.runtime import capture as _capture
    from misaka_tpu.runtime import usage as _usage
    from misaka_tpu.utils import tsdb as _tsdb

    sys.setswitchinterval(0.001)
    batch, in_cap, threads, waves = 1024, 128, 8, 4
    caps = dict(in_cap=in_cap, out_cap=in_cap, stack_cap=16)
    top = networks.add2(**caps)
    master = MasterNode(top, chunk_steps=2048, batch=batch, engine="native")
    registry = ProgramRegistry(None, batch=batch, engine="native", caps=caps)
    registry.seed("default", master, top)
    httpd = make_http_server(master, port=0, registry=registry)
    _threading.Thread(target=httpd.serve_forever, daemon=True).start()
    host, port = "127.0.0.1", httpd.server_address[1]
    url = f"http://{host}:{port}/compute_raw?spread=1"
    master.run()
    rng = np.random.default_rng(2)
    per_request = (batch // threads) * in_cap
    spool_root = tempfile.mkdtemp(prefix="misaka-durable-ab-")

    def raw_lane():
        reqs = [
            [
                (v := rng.integers(-1000, 1000, size=per_request)
                 .astype(np.int32)),
                np.ascontiguousarray(v, "<i4").tobytes(), None,
            ]
            for _ in range(threads * waves)
        ]
        errors = []

        def worker(chunk):
            try:
                for item in chunk:
                    req = urllib.request.Request(
                        url, data=item[1], method="POST"
                    )
                    with urllib.request.urlopen(req, timeout=120) as r:
                        item[2] = r.read()
            except Exception as e:  # pragma: no cover
                errors.append(e)

        ws = [
            _threading.Thread(target=worker, args=(reqs[i::threads],))
            for i in range(threads)
        ]
        t0 = time.perf_counter()
        for t in ws:
            t.start()
        for t in ws:
            t.join()
        elapsed = time.perf_counter() - t0
        if errors:
            raise errors[0]
        for vals, _, raw in reqs:
            if not np.array_equal(np.frombuffer(raw, "<i4"), vals + 2):
                raise RuntimeError("durable A/B raw parity FAILED")
        return len(reqs) * per_request / elapsed

    def conc_lane(seconds=2.0, c=64, payload_values=64):
        rng2 = np.random.default_rng(13)
        bodies = []
        for _ in range(8):
            vals = rng2.integers(
                -1000, 1000, size=payload_values
            ).astype(np.int32)
            bodies.append((vals, np.ascontiguousarray(vals, "<i4").tobytes()))
        counts = [0] * c
        errors = []
        stop = _threading.Event()

        def one_client(i):
            try:
                conn = _http_client.HTTPConnection(host, port, timeout=60)
                k = 0
                while not stop.is_set():
                    vals, body = bodies[k % 8]
                    conn.request("POST", "/compute_raw?spread=1", body)
                    raw = conn.getresponse().read()
                    if not np.array_equal(
                        np.frombuffer(raw, dtype="<i4"), vals + 2
                    ):
                        raise RuntimeError("durable A/B sweep parity FAILED")
                    counts[i] += 1
                    k += 1
                conn.close()
            except Exception as e:  # pragma: no cover
                errors.append(e)
                stop.set()

        ts = [
            _threading.Thread(target=one_client, args=(i,)) for i in range(c)
        ]
        t0 = time.perf_counter()
        for t in ts:
            t.start()
        time.sleep(seconds)
        stop.set()
        for t in ts:
            t.join()
        elapsed = time.perf_counter() - t0
        if errors:
            raise errors[0]
        return sum(counts) * payload_values / elapsed

    def set_durable(on):
        """The whole MISAKA_TSDB_DIR plane as one toggle (it ships as
        one switch): disk-spooling TSDB + usage ledger spool + always-on
        capture.  OFF = today's in-memory collector, still running."""
        _capture.shutdown_spool()
        if _capture.RECORDING:
            _capture.stop()
        _usage.shutdown_spool()
        _tsdb.shutdown()
        if on:
            env = {"MISAKA_TSDB_DIR": spool_root}
            _tsdb.ensure_started(env)
            _usage.ensure_spool(env)
            _capture.ensure_spool(env, anchor_fn=None)
        else:
            _tsdb.ensure_started({})

    conc_pairs = pairs * 3
    out = {
        "method": (
            f"durable telemetry plane ARMED (MISAKA_TSDB_DIR: TSDB disk "
            f"spool + 5m long-horizon tier, usage-ledger spool flushing "
            f"every 15s, always-on capture recording every request into "
            f"the rotation ring) vs DISARMED (the committed in-memory "
            f"r15 observability stack, still fully on) — the marginal "
            f"cost of durability, nothing else.  ONE shared master + "
            f"HTTP server + registry, ABBA pair ordering, "
            f"switchinterval=1ms; raw = {pairs} pairs of 8 threads x "
            f"{waves} waves of {per_request}-value /compute_raw; conc64 "
            f"= {conc_pairs} pairs of 64 in-process keep-alive clients "
            f"x 64-value payloads x 2.5s.  Headline = MEDIAN of the "
            f"matched ABBA pair ratios (scheduler-collapse discipline "
            f"of every served A/B since r10); full per-pair arrays "
            f"embedded"
        ),
        "baseline_raw": [], "durable_raw": [],
        "baseline_conc64": [], "durable_conc64": [],
    }
    try:
        for on in (False, True):  # warm both paths end to end
            set_durable(on)
            raw_lane()
            conc_lane(seconds=1.0)
        for i in range(pairs):
            for on in (False, True) if i % 2 == 0 else (True, False):
                set_durable(on)
                raw = raw_lane()
                key = "durable" if on else "baseline"
                out[key + "_raw"].append(round(raw, 1))
                print(
                    f"# durable A/B raw pair {i} {'on ' if on else 'off'}: "
                    f"{raw:.0f}/s",
                    file=sys.stderr,
                )
        for i in range(conc_pairs):
            for on in (False, True) if i % 2 == 0 else (True, False):
                set_durable(on)
                conc = conc_lane(seconds=2.5)
                key = "durable" if on else "baseline"
                out[key + "_conc64"].append(round(conc, 1))
                print(
                    f"# durable A/B conc64 pair {i} "
                    f"{'on ' if on else 'off'}: {conc:.0f}/s",
                    file=sys.stderr,
                )
    finally:
        set_durable(False)
        _tsdb.shutdown()
        master.pause()
        registry.close()
        httpd.shutdown()
        shutil.rmtree(spool_root, ignore_errors=True)
    for lane in ("raw", "conc64"):
        base = out[f"baseline_{lane}"]
        inst = out[f"durable_{lane}"]
        ratios = sorted(round(b and i / b, 4) for i, b in zip(inst, base))
        out[f"{lane}_pair_ratios"] = ratios
        out[f"{lane}_mean_ratio"] = round(sum(inst) / sum(base), 4)
        n = len(ratios)
        out[f"{lane}_median_ratio"] = round(
            ratios[n // 2] if n % 2
            else (ratios[n // 2 - 1] + ratios[n // 2]) / 2, 4
        )
    return out


def bench_native_trace_ab(pairs=6):
    """Native flight-recorder overhead A/B (ISSUE r18 budget: MEDIAN
    ratio >= 0.95 on both lanes with the recorder armed vs disarmed).

    Two lanes, both on ONE shared stack with ABBA pair ordering (the
    committed r10/r12/r14/r15 discipline): `raw` is the served
    /compute_raw throughput lane (the recorder's cost on the full HTTP
    path), and `call256` is the r17 B=256 light-fill call-overhead lane
    (serve-call wall — the recorder's per-call emit cost with nowhere to
    hide it).  The toggle is misaka_pool_trace_set via
    native_serve.set_trace — the SAME pools serve both sides, so the
    pair ratio isolates the emit branch + ring stores + the throttled
    Python-side stats pull, not a pool-construction lottery."""
    import threading as _threading
    import urllib.request

    from misaka_tpu import networks
    from misaka_tpu.core import native_serve
    from misaka_tpu.runtime.master import MasterNode, make_http_server

    sys.setswitchinterval(0.001)
    batch, in_cap, threads, waves = 1024, 128, 8, 4
    caps = dict(in_cap=in_cap, out_cap=in_cap, stack_cap=16)
    top = networks.add2(**caps)
    master = MasterNode(top, chunk_steps=2048, batch=batch, engine="native")
    httpd = make_http_server(master, port=0)
    _threading.Thread(target=httpd.serve_forever, daemon=True).start()
    host, port = "127.0.0.1", httpd.server_address[1]
    url = f"http://{host}:{port}/compute_raw?spread=1"
    master.run()
    rng = np.random.default_rng(7)
    per_request = (batch // threads) * in_cap

    def raw_lane():
        reqs = [
            [
                (v := rng.integers(-1000, 1000, size=per_request)
                 .astype(np.int32)),
                np.ascontiguousarray(v, "<i4").tobytes(), None,
            ]
            for _ in range(threads * waves)
        ]
        errors = []

        def worker(chunk):
            try:
                for item in chunk:
                    req = urllib.request.Request(
                        url, data=item[1], method="POST"
                    )
                    with urllib.request.urlopen(req, timeout=120) as r:
                        item[2] = r.read()
            except Exception as e:  # pragma: no cover
                errors.append(e)

        ws = [
            _threading.Thread(target=worker, args=(reqs[i::threads],))
            for i in range(threads)
        ]
        t0 = time.perf_counter()
        for t in ws:
            t.start()
        for t in ws:
            t.join()
        elapsed = time.perf_counter() - t0
        if errors:
            raise errors[0]
        for vals, _, raw in reqs:
            if not np.array_equal(np.frombuffer(raw, "<i4"), vals + 2):
                raise RuntimeError("native-trace A/B raw parity FAILED")
        return len(reqs) * per_request / elapsed

    # the r17 B=256 call-overhead lane: ONE shared pool, light fill,
    # resident — the serve-call wall is all dispatch + recorder
    net256 = networks.add2(**caps).compile(batch=256)
    pool256 = native_serve.NativeServePool(net256, chunk_steps=64)
    call_state = [net256.init_state()]
    vals256 = np.zeros((256, in_cap), np.int32)
    vals256[0, 0] = 5
    counts256 = np.zeros((256,), np.int32)
    counts256[0] = 1

    def call256_lane(rounds=400):
        state = call_state[0]
        for _ in range(10):  # warm: arms residency after any toggle
            state, packed = pool256.serve(state, vals256, counts256)
            if packed[0, 3] <= packed[0, 2]:
                raise RuntimeError("call-overhead lane lost a value")
        t0 = time.perf_counter()
        for _ in range(rounds):
            state, _ = pool256.serve(state, vals256, counts256)
        dt = time.perf_counter() - t0
        call_state[0] = state
        return rounds / dt

    out = {
        "method": (
            f"native flight recorder ARMED vs DISARMED at runtime "
            f"(native_serve.set_trace -> misaka_pool_trace_set: same "
            f"pools both sides, emit sites reduce to one relaxed flag "
            f"load when off); ONE shared master + HTTP server + one "
            f"shared B=256 pool, ABBA pair ordering, switchinterval=1ms "
            f"as in production; raw = {pairs} pairs of {threads} "
            f"threads x {waves} waves of {per_request}-value "
            f"/compute_raw; call256 = {pairs * 3} pairs of 400 "
            f"light-fill resident serve calls on the shared B=256 pool "
            f"(the r17 call-overhead shape).  Headline = MEDIAN of the "
            f"matched ABBA pair ratios, full per-pair arrays embedded"
        ),
        "baseline_raw": [], "instrumented_raw": [],
        "baseline_call256": [], "instrumented_call256": [],
    }
    try:
        for on in (False, True):  # warm both paths end to end
            native_serve.set_trace(on)
            raw_lane()
            call256_lane(rounds=100)
        for i in range(pairs):
            for on in (False, True) if i % 2 == 0 else (True, False):
                native_serve.set_trace(on)
                raw = raw_lane()
                key = "instrumented" if on else "baseline"
                out[key + "_raw"].append(round(raw, 1))
                print(
                    f"# native-trace A/B raw pair {i} "
                    f"{'on ' if on else 'off'}: {raw:.0f}/s",
                    file=sys.stderr,
                )
        for i in range(pairs * 3):
            for on in (False, True) if i % 2 == 0 else (True, False):
                native_serve.set_trace(on)
                calls = call256_lane()
                key = "instrumented" if on else "baseline"
                out[key + "_call256"].append(round(calls, 1))
                print(
                    f"# native-trace A/B call256 pair {i} "
                    f"{'on ' if on else 'off'}: {calls:.0f} calls/s",
                    file=sys.stderr,
                )
    finally:
        native_serve.set_trace(native_serve.trace_enabled())
        pool256.close()
        master.pause()
        httpd.shutdown()
    for lane in ("raw", "call256"):
        base = out[f"baseline_{lane}"]
        inst = out[f"instrumented_{lane}"]
        ratios = sorted(round(b and i / b, 4) for i, b in zip(inst, base))
        out[f"{lane}_pair_ratios"] = ratios
        out[f"{lane}_mean_ratio"] = round(sum(inst) / sum(base), 4)
        n = len(ratios)
        out[f"{lane}_median_ratio"] = round(
            ratios[n // 2] if n % 2
            else (ratios[n // 2 - 1] + ratios[n // 2]) / 2, 4
        )
    return out


def bench_edge_ab(pairs=6):
    """Production-edge overhead A/B (ISSUE r14 budget: MEDIAN served-
    throughput ratio >= 0.95 on both lanes with every edge kill switch
    OFF — auth + quota + admission all armed — vs the chain disarmed).

    Same discipline as the committed r10/r12 A/Bs: ONE shared master +
    HTTP server, ABBA pair ordering, production 1ms switch interval,
    median-of-pairs headline with the full arrays embedded.  The toggle
    mutates the INSTALLED chain (the same object the handlers consult),
    so the measured delta is exactly the per-request cost of the armed
    chain: key-file HMAC lookup + two token buckets + the admission
    governor's live waiting-values read.  Clients send the API key on
    BOTH sides (identical wire bytes; the disarmed chain skips without
    reading it)."""
    import tempfile
    import threading as _threading
    import urllib.request
    import http.client as _http_client

    from misaka_tpu import networks
    from misaka_tpu.runtime import edge as edge_mod
    from misaka_tpu.runtime.master import MasterNode, make_http_server

    sys.setswitchinterval(0.001)
    batch, in_cap, threads, waves = 1024, 128, 8, 4
    tmp = tempfile.mkdtemp(prefix="misaka-edge-ab-")
    keyfile = os.path.join(tmp, "keys.json")
    with open(keyfile, "w") as f:
        json.dump({"keys": [{
            "key": "ab-key", "tenant": "ab",
            # generous: the A/B measures check cost, never a shed
            "quota": "rps<10000000,vps<4000000000",
        }]}, f)
    prev_keys = os.environ.get("MISAKA_API_KEYS")
    os.environ["MISAKA_API_KEYS"] = keyfile
    top = networks.add2(in_cap=in_cap, out_cap=in_cap, stack_cap=16)
    master = MasterNode(top, chunk_steps=2048, batch=batch, engine="native")
    httpd = make_http_server(master, port=0)
    _threading.Thread(target=httpd.serve_forever, daemon=True).start()
    host, port = "127.0.0.1", httpd.server_address[1]
    url = f"http://{host}:{port}/compute_raw?spread=1"
    master.run()
    chain = edge_mod.current()
    assert chain.armed and chain.keyfile is not None
    armed_state = (chain.keyfile, chain.quota_enabled, chain.governor)
    headers = {"X-Misaka-Key": "ab-key"}

    def set_edge(on):
        if on:
            chain.keyfile, chain.quota_enabled, chain.governor = armed_state
        else:
            chain.keyfile = None
            chain.quota_enabled = False
            chain.governor = None

    rng = np.random.default_rng(2)
    per_request = (batch // threads) * in_cap

    def raw_lane():
        reqs = [
            [
                (v := rng.integers(-1000, 1000, size=per_request)
                 .astype(np.int32)),
                np.ascontiguousarray(v, "<i4").tobytes(), None,
            ]
            for _ in range(threads * waves)
        ]
        errors = []

        def worker(chunk):
            try:
                for item in chunk:
                    req = urllib.request.Request(
                        url, data=item[1], headers=headers, method="POST"
                    )
                    with urllib.request.urlopen(req, timeout=120) as r:
                        item[2] = r.read()
            except Exception as e:  # pragma: no cover
                errors.append(e)

        ws = [
            _threading.Thread(target=worker, args=(reqs[i::threads],))
            for i in range(threads)
        ]
        t0 = time.perf_counter()
        for t in ws:
            t.start()
        for t in ws:
            t.join()
        elapsed = time.perf_counter() - t0
        if errors:
            raise errors[0]
        for vals, _, raw in reqs:
            if not np.array_equal(np.frombuffer(raw, "<i4"), vals + 2):
                raise RuntimeError("edge A/B raw parity FAILED")
        return len(reqs) * per_request / elapsed

    def conc_lane(seconds=2.5, c=64, payload_values=64):
        rng2 = np.random.default_rng(13)
        bodies = []
        for _ in range(8):
            vals = rng2.integers(
                -1000, 1000, size=payload_values
            ).astype(np.int32)
            bodies.append((vals, np.ascontiguousarray(vals, "<i4").tobytes()))
        counts = [0] * c
        errors = []
        stop = _threading.Event()

        def one_client(i):
            try:
                conn = _http_client.HTTPConnection(host, port, timeout=60)
                k = 0
                while not stop.is_set():
                    vals, body = bodies[k % 8]
                    conn.request(
                        "POST", "/compute_raw?spread=1", body, headers
                    )
                    raw = conn.getresponse().read()
                    if not np.array_equal(
                        np.frombuffer(raw, dtype="<i4"), vals + 2
                    ):
                        raise RuntimeError("edge A/B sweep parity FAILED")
                    counts[i] += 1
                    k += 1
                conn.close()
            except Exception as e:  # pragma: no cover
                errors.append(e)
                stop.set()

        ts = [
            _threading.Thread(target=one_client, args=(i,)) for i in range(c)
        ]
        t0 = time.perf_counter()
        for t in ts:
            t.start()
        time.sleep(seconds)
        stop.set()
        for t in ts:
            t.join()
        elapsed = time.perf_counter() - t0
        if errors:
            raise errors[0]
        return sum(counts) * payload_values / elapsed

    conc_pairs = pairs * 3
    out = {
        "method": (
            f"API-key auth (HMAC key file) + per-tenant token-bucket "
            f"quota + admission governor ALL ARMED vs the chain "
            f"disarmed (the installed chain's own stage switches), ONE "
            f"shared master + HTTP server, ABBA pair ordering, "
            f"switchinterval=1ms as in production; clients send the key "
            f"header on BOTH sides.  raw = {pairs} pairs of 8 threads x "
            f"{waves} waves of {per_request}-value /compute_raw; conc64 "
            f"= {conc_pairs} pairs of 64 keep-alive clients x 64-value "
            f"payloads x 2.5s.  Headline = MEDIAN of the matched ABBA "
            f"pair ratios (the r12 discipline: the closed-loop conc "
            f"lane collapses 2-5x either way on scheduler lottery)"
        ),
        "baseline_raw": [], "instrumented_raw": [],
        "baseline_conc64": [], "instrumented_conc64": [],
    }
    try:
        for on in (False, True):  # warm both paths end to end
            set_edge(on)
            raw_lane()
            conc_lane(seconds=1.0)
        for i in range(pairs):
            for on in (False, True) if i % 2 == 0 else (True, False):
                set_edge(on)
                raw = raw_lane()
                key = "instrumented" if on else "baseline"
                out[key + "_raw"].append(round(raw, 1))
                print(
                    f"# edge A/B raw pair {i} {'on ' if on else 'off'}: "
                    f"{raw:.0f}/s",
                    file=sys.stderr,
                )
        for i in range(conc_pairs):
            for on in (False, True) if i % 2 == 0 else (True, False):
                set_edge(on)
                conc = conc_lane(seconds=2.5)
                key = "instrumented" if on else "baseline"
                out[key + "_conc64"].append(round(conc, 1))
                print(
                    f"# edge A/B conc64 pair {i} "
                    f"{'on ' if on else 'off'}: {conc:.0f}/s",
                    file=sys.stderr,
                )
    finally:
        set_edge(True)
        master.pause()
        httpd.shutdown()
        edge_mod.reset()
        if prev_keys is None:
            os.environ.pop("MISAKA_API_KEYS", None)
        else:
            os.environ["MISAKA_API_KEYS"] = prev_keys
    for lane in ("raw", "conc64"):
        base = out[f"baseline_{lane}"]
        inst = out[f"instrumented_{lane}"]
        ratios = sorted(round(b and i / b, 4) for i, b in zip(inst, base))
        out[f"{lane}_pair_ratios"] = ratios
        out[f"{lane}_mean_ratio"] = round(sum(inst) / sum(base), 4)
        n = len(ratios)
        out[f"{lane}_median_ratio"] = round(
            ratios[n // 2] if n % 2
            else (ratios[n // 2 - 1] + ratios[n // 2]) / 2, 4
        )
    return out


def bench_native_pool(
    threads=None, batch=256, in_cap=128, chunk_steps=2048, rounds=4,
    simd=None, specialized=False,
):
    """Direct (no-HTTP) throughput of the multi-threaded native C++ tier:
    B replica interpreters × `rounds` full ring refills each, sharded
    across `threads` OS threads (core/native_serve.NativeServePool).
    Every round must fully drain and parity-check, like every other lane.

    `simd` pins MISAKA_SIMD for the pool ("0" scalar / "generic" /
    None=auto); `specialized=True` compiles-or-reuses the per-program
    specialized build (core/specialize.py, shared content-keyed cache).

    HARNESS NOTE (r16): the parity check uses np.array_equal, not
    numpy.testing — at SIMD rates the old assert_array_equal cost
    ~1.5 ms/round of pure harness, capping the measurement near 16M/s
    while the pool itself served 30M+.  Captures before r16 carry that
    overhead; same-harness A/B lives in bench_simd_scaling()'s mode
    table.
    """
    from misaka_tpu import networks
    from misaka_tpu.core.native_serve import NativeServePool

    net = networks.add2(in_cap=in_cap, out_cap=in_cap, stack_cap=16).compile(
        batch=batch
    )
    spec_so = None
    if specialized:
        from misaka_tpu.core import specialize

        spec_so = specialize.build(net)
        if spec_so is None:
            raise RuntimeError("specialized build unavailable")
    prev = os.environ.get("MISAKA_SIMD")
    if simd is None:
        os.environ.pop("MISAKA_SIMD", None)
    else:
        os.environ["MISAKA_SIMD"] = simd
    try:
        pool = NativeServePool(
            net, chunk_steps=chunk_steps, threads=threads, specialized=spec_so
        )
    finally:
        if prev is None:
            os.environ.pop("MISAKA_SIMD", None)
        else:
            os.environ["MISAKA_SIMD"] = prev
    info = pool.simd_info()
    rng = np.random.default_rng(5)
    counts = np.full((batch,), in_cap, np.int32)
    # feeds pre-generated OUTSIDE the timed loop, expectations too: at
    # SIMD rates the rng was measurable harness (see the docstring note)
    feeds = [
        rng.integers(-1000, 1000, size=(batch, in_cap)).astype(np.int32)
        for _ in range(rounds + 1)
    ]
    wants = [v + 2 for v in feeds]

    def one_round(state, k):
        state, packed = pool.serve(state, feeds[k], counts)
        rd, wr = packed[:, 2], packed[:, 3]
        if not (wr - rd == in_cap).all():
            raise RuntimeError(
                f"native pool round incomplete: min drained "
                f"{int((wr - rd).min())}/{in_cap}"
            )
        # each round feeds exactly in_cap values, so the ring read cursor
        # is back at slot 0 every round and the packed ring IS the output
        # stream in order — one vectorized compare, no gather
        if (rd % in_cap).any():
            raise RuntimeError("native pool ring cursor misaligned")
        if not np.array_equal(packed[:, 4:], wants[k]):
            raise RuntimeError("native pool parity FAILED")
        return state

    state = one_round(net.init_state(), rounds)  # warm (first touch)
    t0 = time.perf_counter()
    for k in range(rounds):
        state = one_round(state, k)
    elapsed = time.perf_counter() - t0
    used = pool.threads
    pool.close()
    total = rounds * batch * in_cap
    return {
        "throughput": total / elapsed,
        "values": total,
        "elapsed_s": elapsed,
        "threads": used,
        "batch": batch,
        "in_cap": in_cap,
        "simd": info,
    }


def bench_call_overhead(batches=(1, 64, 256, 4096), rounds=300):
    """The r17 per-CALL overhead lane: serve-call wall at light fill (one
    value to slot 0 per call, full-batch pass) across batch sizes,
    residency ON vs OFF on the same engine construction path.  At B>=256
    the stateless call wall is dominated by the state import/export round
    trip (~200us at B=256 in the r16 profile) plus the thread wake —
    exactly the floors resident state (in-C++ between calls) and the
    futex/spin dispenser remove.  calls/s on the resident B=256 lane is
    the bench-smoke-gated figure; `speedup` is the A/B ratio the r17
    acceptance criterion reads (>= 2x at B=256)."""
    from misaka_tpu import networks
    from misaka_tpu.core import native_serve

    out = {}
    for B in batches:
        # the SERVING ring shape (bench_native_pool's): with tiny rings
        # the state round trip is a few KB and the lane measures nothing
        net = networks.add2(in_cap=128, out_cap=128, stack_cap=16).compile(
            batch=None if B == 1 else B
        )
        entry = {}
        for mode in ("resident", "stateless"):
            prev = os.environ.get("MISAKA_NATIVE_RESIDENT")
            os.environ["MISAKA_NATIVE_RESIDENT"] = (
                "1" if mode == "resident" else "0"
            )
            try:
                if B == 1:
                    eng = native_serve.NativeServe(net)
                else:
                    eng = native_serve.NativeServePool(net, chunk_steps=64)
            finally:
                if prev is None:
                    os.environ.pop("MISAKA_NATIVE_RESIDENT", None)
                else:
                    os.environ["MISAKA_NATIVE_RESIDENT"] = prev
            state = net.init_state()
            if B == 1:
                vals = np.zeros((net.in_cap,), np.int32)
                vals[0] = 5

                def call(state, eng=eng, vals=vals):
                    st, packed = eng.serve_chunk(state, vals, 1, 64)
                    if packed[3] <= packed[2]:
                        raise RuntimeError("call-overhead lane lost a value")
                    return st
            else:
                vals = np.zeros((B, net.in_cap), np.int32)
                vals[0, 0] = 5
                counts = np.zeros((B,), np.int32)
                counts[0] = 1

                def call(state, eng=eng, vals=vals, counts=counts):
                    st, packed = eng.serve(state, vals, counts)
                    if packed[0, 3] <= packed[0, 2]:
                        raise RuntimeError("call-overhead lane lost a value")
                    return st
            for _ in range(10):  # warm: arms residency, faults pages
                state = call(state)
            t0 = time.perf_counter()
            for _ in range(rounds):
                state = call(state)
            dt = time.perf_counter() - t0
            entry[mode] = {
                "us_per_call": round(dt / rounds * 1e6, 2),
                "calls_per_s": round(rounds / dt, 1),
            }
            eng.close()
        entry["speedup"] = round(
            entry["resident"]["calls_per_s"]
            / entry["stateless"]["calls_per_s"], 3
        )
        out[str(B)] = entry
        print(
            f"# call-overhead B={B}: resident "
            f"{entry['resident']['us_per_call']}us/call vs stateless "
            f"{entry['stateless']['us_per_call']}us/call "
            f"({entry['speedup']}x)",
            file=sys.stderr,
        )
    return out


def bench_jit_ab(batches=(256, 4096), pairs=3, rounds=30, in_cap=128):
    """The r21 copy-and-patch A/B: full-fill serve throughput through
    NativeServePool with the JIT fragment tables armed vs the
    switch-threaded group tick one rung down, same harness, ABBA pairs
    (off-on / on-off alternation so drift cancels).  Parity-checked
    every round like bench_native_pool; the acceptance criterion reads
    the per-batch MEDIAN pair ratio (>= 1.15 at B >= 256).

    HARNESS NOTE: threads=1 pinned and the clock is time.thread_time —
    a 1-worker pool runs the whole pass inline on the caller, so caller
    CPU time IS the pass and the shared box's preemption (which hits
    both lanes but lands unevenly inside an ABBA pair) drops out of the
    A/B.  Wall-clock on this container swung pair ratios +-8% run to
    run; CPU time holds them within ~2%."""
    import statistics

    from misaka_tpu import networks
    from misaka_tpu.core import jit
    from misaka_tpu.core.native_serve import NativeServePool

    out = {}
    for B in batches:
        net = networks.add2(
            in_cap=in_cap, out_cap=in_cap, stack_cap=16
        ).compile(batch=B)
        rng = np.random.default_rng(5)
        counts = np.full((B,), in_cap, np.int32)
        feeds = [
            rng.integers(-1000, 1000, size=(B, in_cap)).astype(np.int32)
            for _ in range(3)
        ]
        wants = [v + 2 for v in feeds]

        def lane(use_jit, B=B, net=net, feeds=feeds, wants=wants,
                 counts=counts):
            prog = jit.prepare(net) if use_jit else None
            if use_jit and prog is None:
                raise RuntimeError("jit prepare failed (rung unavailable)")
            pool = NativeServePool(
                net, chunk_steps=2048, threads=1, jit_program=prog
            )
            if use_jit and not pool.simd_info()["jit"]:
                pool.close()
                raise RuntimeError("jit arm refused (rung unavailable)")
            state = net.init_state()
            state, _ = pool.serve(state, feeds[0], counts)  # warm
            t0 = time.thread_time()
            for k in range(rounds):
                state, packed = pool.serve(state, feeds[k % 3], counts)
                if not np.array_equal(packed[:, 4:], wants[k % 3]):
                    raise RuntimeError("jit A/B parity FAILED")
            dt = time.thread_time() - t0
            pool.close()
            if prog is not None:
                prog.close()
            return rounds * B * in_cap / dt

        offs, ons = [], []
        for _ in range(pairs):
            offs.append(lane(False)); ons.append(lane(True))
            ons.append(lane(True));   offs.append(lane(False))
        ratios = sorted(o / f for o, f in zip(ons, offs))
        entry = {
            "jit_throughput": [round(x, 1) for x in ons],
            "switch_throughput": [round(x, 1) for x in offs],
            "jit_median": round(statistics.median(ons), 1),
            "switch_median": round(statistics.median(offs), 1),
            "median_ratio": round(
                statistics.median(ons) / statistics.median(offs), 3
            ),
        }
        out[str(B)] = entry
        print(
            f"# jit A/B B={B}: jit {entry['jit_median']:.0f}/s vs "
            f"switch-threaded {entry['switch_median']:.0f}/s "
            f"({entry['median_ratio']}x, pairs={pairs})",
            file=sys.stderr,
        )
    return out


def bench_elision_sweep(batches=(64, 1024, 4096, 16384), pairs=3,
                        ticks=64, in_cap=128):
    """The r21 pack-row elision sweep: sparse-fill resident serving (ONE
    hot replica out of B, active=[0]) with the quiescent-row elision
    armed (reused packed buffer + dirty ledger) vs the r20 behavior
    (fresh buffer, every row re-packed every call), MISAKA_PACK_ELIDE
    pinned at pool creation.  calls/s per lane, ABBA medians.

    Harness notes: threads=1 (a 1-worker pool runs the whole pass inline
    on the caller — on this container's single core a dispenser wake
    would only add scheduler noise to both lanes), and the clock is
    time.thread_time — caller CPU time — because the pass under
    measurement runs entirely on the calling thread and the shared box's
    preemption otherwise swamps the A/B.  The elidable term is
    B-proportional while the per-call floor (~tens of us: Python
    dispatch + feed + masked group ticks) is flat, so the ratio grows
    with B; the sweep's large end is where the pack pass dominates and
    the >= 2x acceptance criterion is read."""
    import statistics

    from misaka_tpu import networks
    from misaka_tpu.core.native_serve import NativeServePool

    out = {}
    for B in batches:
        net = networks.add2(
            in_cap=in_cap, out_cap=in_cap, stack_cap=16
        ).compile(batch=B)
        rounds = max(600, min(12_000, 12_000_000 // B))

        def lane(elide, B=B, net=net, rounds=rounds):
            prev = os.environ.get("MISAKA_PACK_ELIDE")
            os.environ["MISAKA_PACK_ELIDE"] = "1" if elide else "0"
            try:
                pool = NativeServePool(net, chunk_steps=ticks, threads=1)
            finally:
                if prev is None:
                    os.environ.pop("MISAKA_PACK_ELIDE", None)
                else:
                    os.environ["MISAKA_PACK_ELIDE"] = prev
            vals = np.zeros((B, net.in_cap), np.int32)
            vals[0, 0] = 5
            counts = np.zeros((B,), np.int32)
            counts[0] = 1
            active = np.array([0], np.int32)
            state = net.init_state()
            state, _ = pool.serve(state, vals, counts, active=active)
            raw = pool._pool  # the serving fast path, minus engine wrap
            for _ in range(10):
                p, _pr = raw.serve_resident(
                    vals, counts, ticks, active=active, reuse_out=elide)
            t0 = time.thread_time()
            for _ in range(rounds):
                p, _pr = raw.serve_resident(
                    vals, counts, ticks, active=active, reuse_out=elide)
            dt = time.thread_time() - t0
            # the hot replica fed 5 every call -> add2 emits 7s into its
            # ring, slot 0 first (quiescent rows aren't checkable here:
            # pack writes each ring's VALID region only, so their slots
            # are whatever the output buffer held)
            if not (p[0, 4:] == 7).any():
                raise RuntimeError("elision lane parity FAILED")
            ctr = raw.counters()
            pool.close()
            return rounds / dt, ctr["elided_rows"]

        ons, offs, elided = [], [], 0
        for _ in range(pairs):
            offs.append(lane(False)[0])
            r, elided = lane(True); ons.append(r)
            r, elided = lane(True); ons.append(r)
            offs.append(lane(False)[0])
        entry = {
            "rounds": rounds,
            "on_calls_per_s": [round(x, 1) for x in ons],
            "off_calls_per_s": [round(x, 1) for x in offs],
            "on_median": round(statistics.median(ons), 1),
            "off_median": round(statistics.median(offs), 1),
            "median_speedup": round(
                statistics.median(ons) / statistics.median(offs), 3
            ),
            "elided_rows_per_lane": int(elided),
        }
        out[str(B)] = entry
        print(
            f"# elision B={B}: on {entry['on_median']:.0f} calls/s vs "
            f"off {entry['off_median']:.0f} calls/s "
            f"({entry['median_speedup']}x; {elided} rows elided/lane)",
            file=sys.stderr,
        )
    return out


def bench_r21_overhead(pairs=3, rounds=4):
    """The r21 kill-switch overhead check: full-fill pool throughput with
    MISAKA_JIT=0 and MISAKA_PACK_ELIDE=0 (the r20-equivalent path plus
    the disabled machinery's residual branches) vs the defaults with no
    JIT program armed and elision armed-but-unfired (full fill dirties
    every row).  Median ABBA ratio must hold >= 0.95: throwing the kill
    switches — and carrying the machinery unused — must cost nothing."""
    import statistics

    def lane(killed):
        prev_j = os.environ.get("MISAKA_JIT")
        prev_e = os.environ.get("MISAKA_PACK_ELIDE")
        if killed:
            os.environ["MISAKA_JIT"] = "0"
            os.environ["MISAKA_PACK_ELIDE"] = "0"
        try:
            return bench_native_pool(rounds=rounds)["throughput"]
        finally:
            for k, prev in (("MISAKA_JIT", prev_j),
                            ("MISAKA_PACK_ELIDE", prev_e)):
                if prev is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = prev

    kills, defaults = [], []
    for _ in range(pairs):
        defaults.append(lane(False)); kills.append(lane(True))
        kills.append(lane(True));     defaults.append(lane(False))
    entry = {
        "killed_throughput": [round(x, 1) for x in kills],
        "default_throughput": [round(x, 1) for x in defaults],
        "killed_median": round(statistics.median(kills), 1),
        "default_median": round(statistics.median(defaults), 1),
        "median_ratio": round(
            statistics.median(kills) / statistics.median(defaults), 3
        ),
    }
    print(
        f"# r21 kill-switch overhead: {entry['killed_median']:.0f}/s "
        f"killed vs {entry['default_median']:.0f}/s default "
        f"({entry['median_ratio']}x)",
        file=sys.stderr,
    )
    return entry


def bench_native_scaling(max_threads=None):
    """Per-thread scaling of the native tier — the evidence that the CPU
    fallback's >=1M/s serving number rides the thread pool, not a fluke:
    [{threads, throughput, speedup_vs_1}] over a 1..n_cores sweep."""
    if max_threads is None:
        max_threads = os.cpu_count() or 1
    sweep, t = [], 1
    while t < max_threads:
        sweep.append(t)
        t *= 2
    sweep.append(max_threads)
    out = []
    for t in sweep:
        r = bench_native_pool(threads=t)
        entry = {
            "threads": r["threads"],
            "throughput": round(r["throughput"], 1),
        }
        if out:
            entry["speedup_vs_1"] = round(r["throughput"] / out[0]["throughput"], 2)
        out.append(entry)
        print(
            f"# native pool: threads={r['threads']} "
            f"throughput={r['throughput']:.0f}/s",
            file=sys.stderr,
        )
    return out


def bench_simd_scaling(max_threads=None, rounds=6):
    """The r16 SIMD lane: per-thread scaling of the group engine PLUS a
    same-harness mode table at max threads — scalar (MISAKA_SIMD=0, the
    pre-r16 engine), the generic group fallback, the AVX2 group path, and
    the per-program specialized build.  The mode table is the honest
    attribution: every number in it shares one harness, one box, one
    moment."""
    if max_threads is None:
        max_threads = os.cpu_count() or 1
    sweep, t = [], 1
    while t < max_threads:
        sweep.append(t)
        t *= 2
    sweep.append(max_threads)
    out = {"sweep": [], "modes": {}}
    for t in sweep:
        r = bench_native_pool(threads=t, rounds=rounds)
        entry = {"threads": r["threads"], "throughput": round(r["throughput"], 1)}
        if out["sweep"]:
            entry["speedup_vs_1"] = round(
                r["throughput"] / out["sweep"][0]["throughput"], 2
            )
        out["sweep"].append(entry)
        print(
            f"# simd pool: threads={r['threads']} "
            f"throughput={r['throughput']:.0f}/s", file=sys.stderr,
        )
    for mode, kw in (
        ("scalar", dict(simd="0")),
        ("generic", dict(simd="generic")),
        ("avx2", dict(simd=None)),
        ("specialized", dict(simd=None, specialized=True)),
    ):
        try:
            r = bench_native_pool(threads=max_threads, rounds=rounds, **kw)
        except Exception as e:  # no toolchain for the spec build etc.
            print(f"# simd mode {mode} skipped: {e}", file=sys.stderr)
            continue
        out["modes"][mode] = {
            "throughput": round(r["throughput"], 1),
            "simd": r["simd"],
        }
        print(
            f"# simd mode {mode}: {r['throughput']:.0f}/s {r['simd']}",
            file=sys.stderr,
        )
    if "scalar" in out["modes"]:
        base = out["modes"]["scalar"]["throughput"]
        for mode, entry in out["modes"].items():
            entry["speedup_vs_scalar"] = round(entry["throughput"] / base, 2)
    return out


def bench_wire_ab(pairs=3, seconds=2.0, clients=64, payload_values=64):
    """Binary protocol vs decimal text on the 64-client lane (r16): ONE
    shared native master + HTTP server, ABBA pair ordering.  `binary` is
    the headered /compute_raw form the client now speaks by default
    (utils/wire.py); `text` is the legacy /compute_batch decimal form.
    Reports throughput AND per-request p50/p99 — the wire's win is
    latency (encode/parse per value) as much as bytes."""
    import http.client as _http_client
    import threading as _threading

    from misaka_tpu import networks
    from misaka_tpu.runtime.master import MasterNode, make_http_server
    from misaka_tpu.utils import wire as wire_mod

    sys.setswitchinterval(0.001)
    top = networks.add2(in_cap=128, out_cap=128, stack_cap=16)
    master = MasterNode(top, chunk_steps=2048, batch=1024, engine="native")
    httpd = make_http_server(master, port=0)
    _threading.Thread(target=httpd.serve_forever, daemon=True).start()
    host, port = "127.0.0.1", httpd.server_address[1]
    master.run()

    def conc_lane(wire_kind: str, secs: float):
        rng = np.random.default_rng(11)
        bodies = []
        for _ in range(8):
            vals = rng.integers(
                -1000, 1000, size=payload_values
            ).astype(np.int32)
            raw = np.ascontiguousarray(vals, "<i4").tobytes()
            if wire_kind == "binary":
                bodies.append((vals, wire_mod.pack(raw)))
            else:
                bodies.append((
                    vals,
                    b"values="
                    + b"+".join(b"%d" % v for v in vals.tolist())
                    + b"&spread=1",
                ))
        counts = [0] * clients
        lats: list[list[float]] = [[] for _ in range(clients)]
        errors = []
        stop = _threading.Event()
        hdrs_bin = {
            "Content-Type": wire_mod.CONTENT_TYPE,
            "Accept": wire_mod.CONTENT_TYPE,
        }

        def one_client(i):
            try:
                conn = _http_client.HTTPConnection(host, port, timeout=60)
                k = 0
                while not stop.is_set():
                    vals, body = bodies[k % 8]
                    t0 = time.perf_counter()
                    if wire_kind == "binary":
                        conn.request(
                            "POST", "/compute_raw?spread=1", body, hdrs_bin
                        )
                        raw = conn.getresponse().read()
                        got = np.frombuffer(
                            wire_mod.unpack(raw), dtype="<i4"
                        )
                    else:
                        conn.request("POST", "/compute_batch", body)
                        got = np.asarray(
                            json.loads(conn.getresponse().read())["values"],
                            np.int32,
                        )
                    lats[i].append(time.perf_counter() - t0)
                    if not np.array_equal(got, vals + 2):
                        raise RuntimeError(f"wire A/B parity FAILED ({wire_kind})")
                    counts[i] += 1
                    k += 1
                conn.close()
            except Exception as e:  # pragma: no cover
                errors.append(e)
                stop.set()

        ts = [
            _threading.Thread(target=one_client, args=(i,))
            for i in range(clients)
        ]
        t0 = time.perf_counter()
        for t in ts:
            t.start()
        time.sleep(secs)
        stop.set()
        for t in ts:
            t.join()
        elapsed = time.perf_counter() - t0
        if errors:
            raise errors[0]
        all_lats = sorted(v for ls in lats for v in ls)
        return {
            "throughput": sum(counts) * payload_values / elapsed,
            "p50_ms": round(all_lats[len(all_lats) // 2] * 1e3, 3),
            "p99_ms": round(all_lats[int(len(all_lats) * 0.99)] * 1e3, 3),
        }

    out = {
        "method": (
            f"ONE shared native master + HTTP server, ABBA pairs: "
            f"{clients} in-process keep-alive clients x "
            f"{payload_values}-value payloads x {seconds}s; binary = "
            f"headered /compute_raw (utils/wire.py, the client default), "
            f"text = legacy decimal /compute_batch"
        ),
        "binary": [], "text": [],
    }
    try:
        for kind in ("text", "binary"):  # warm both paths end to end
            conc_lane(kind, 0.5)
        for i in range(pairs):
            order = ("text", "binary") if i % 2 == 0 else ("binary", "text")
            for kind in order:
                r = conc_lane(kind, seconds)
                out[kind].append(r)
                print(
                    f"# wire A/B pair {i} {kind}: {r['throughput']:.0f}/s "
                    f"p50={r['p50_ms']}ms p99={r['p99_ms']}ms",
                    file=sys.stderr,
                )
    finally:
        master.pause()
        httpd.shutdown()
    for kind in ("binary", "text"):
        rs = out[kind]
        out[f"{kind}_throughput"] = round(
            sorted(r["throughput"] for r in rs)[len(rs) // 2], 1
        )
        out[f"{kind}_p50_ms"] = sorted(r["p50_ms"] for r in rs)[len(rs) // 2]
    out["binary_vs_text_throughput"] = round(
        out["binary_throughput"] / out["text_throughput"], 3
    )
    out["binary_vs_text_p50"] = round(
        out["binary_p50_ms"] / out["text_p50_ms"], 3
    )
    return out


def bench_dist_ab(pairs=3, seconds=2.0, clients=16, payload_values=64,
                  failover_seconds=4.0):
    """The r22 multi-host lanes: plane-transport overhead (unix vs TCP
    vs TCP+mTLS, the MSK1 codec identical on all three) and the
    kill-mid-load failover window through FleetPlaneRouter.

    Transport lane: ONE shared native master serves its compute plane on
    a unix socket, a loopback TCP socket, and a loopback TCP socket
    wrapped in CA-pinned mTLS (throwaway openssl cert; the lane records
    null when openssl is absent).  `clients` PlaneClient threads each
    push `payload_values`-value frames for `seconds`; ABBA-rotated
    pairs, per-frame p50/p99.  The headline is the ratio: what crossing
    a host boundary (and paying the TLS record layer) costs the plane.

    Failover lane: a FleetPlaneRouter over TWO planes; mid-load one
    plane is closed abruptly (the kill -9 stand-in — every connection
    dies with it).  Reports the client-observed p50/p99/max across the
    whole run and the error count, which must be ZERO: the hedge path
    (half-remaining-deadline attempts onto the surviving sibling) is the
    product claim, and the max latency IS the failover window."""
    import ssl as _ssl  # noqa: F401 - asserts the stdlib TLS stack exists
    import shutil as _shutil
    import subprocess as _subprocess
    import tempfile as _tempfile
    import threading as _threading

    from misaka_tpu import networks
    from misaka_tpu.runtime import frontends
    from misaka_tpu.runtime.master import MasterNode

    sys.setswitchinterval(0.001)
    rng = np.random.default_rng(22)
    vals = rng.integers(-1000, 1000, size=payload_values).astype(np.int32)
    body = np.ascontiguousarray(vals, "<i4").tobytes()
    want = vals + 2

    top = networks.add2(in_cap=128, out_cap=128, stack_cap=16)
    master = MasterNode(top, chunk_steps=2048, batch=1024, engine="native")
    master.run()
    tmp = _tempfile.mkdtemp(prefix="misaka-bench-dist-")
    tls_ok = _shutil.which("openssl") is not None
    saved_env = {
        k: os.environ.get(k)
        for k in ("MISAKA_PLANE_TLS_CERT", "MISAKA_PLANE_TLS_KEY",
                  "MISAKA_PLANE_TLS_CA")
    }
    if tls_ok:
        cert = os.path.join(tmp, "plane.pem")
        key = os.path.join(tmp, "plane.key")
        _subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "ec", "-pkeyopt",
             "ec_paramgen_curve:prime256v1", "-nodes", "-keyout", key,
             "-out", cert, "-days", "1", "-subj", "/CN=misaka-bench"],
            check=True, capture_output=True,
        )

    def _tls_env(on: bool) -> None:
        for k in saved_env:
            os.environ.pop(k, None)
        if on:
            os.environ.update({
                "MISAKA_PLANE_TLS_CERT": cert,
                "MISAKA_PLANE_TLS_KEY": key,
                "MISAKA_PLANE_TLS_CA": cert,
            })

    def lane(addr: str, secs: float) -> dict:
        plane = frontends.start_compute_plane(master, addr)
        client = frontends.PlaneClient(addr, conns=2, timeout=30)
        counts = [0] * clients
        lats: list[list[float]] = [[] for _ in range(clients)]
        errors: list[str] = []
        stop = _threading.Event()

        def one(i: int) -> None:
            try:
                while not stop.is_set():
                    t0 = time.perf_counter()
                    out = client.compute_raw(body, timeout=30)
                    lats[i].append(time.perf_counter() - t0)
                    if not np.array_equal(
                        np.frombuffer(out, dtype="<i4"), want
                    ):
                        errors.append(f"client {i}: wrong values")
                        return
                    counts[i] += 1
            except Exception as e:  # noqa: BLE001 - recorded, asserted
                errors.append(f"client {i}: {type(e).__name__}: {e}")

        try:
            threads = [
                _threading.Thread(target=one, args=(i,), daemon=True)
                for i in range(clients)
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            time.sleep(secs)
            stop.set()
            for t in threads:
                t.join(timeout=30)
            dt = time.perf_counter() - t0
        finally:
            client.close()
            plane.close()
        if errors:
            raise RuntimeError(f"transport lane errors: {errors[:3]}")
        flat = sorted(x for ls in lats for x in ls)
        return {
            "throughput": round(sum(counts) * payload_values / dt, 1),
            "req_s": round(sum(counts) / dt, 1),
            "p50_ms": round(1e3 * flat[len(flat) // 2], 3),
            "p99_ms": round(1e3 * flat[int(len(flat) * 0.99)], 3),
        }

    kinds = ["unix", "tcp"] + (["tcp_mtls"] if tls_ok else [])

    def run_kind(kind: str, secs: float) -> dict:
        _tls_env(kind == "tcp_mtls")
        if kind == "unix":
            addr = os.path.join(tmp, f"plane-{time.monotonic_ns()}.sock")
        else:
            addr = f"127.0.0.1:{frontends.pick_free_port()}"
        return lane(addr, secs)

    out: dict = {
        "method": (
            f"ONE shared native master, ABBA-rotated pairs: {clients} "
            f"PlaneClient threads x {payload_values}-value MSK1 frames "
            f"x {seconds}s per lane; tcp_mtls = CA-pinned TLS around "
            f"the same HMAC handshake (throwaway openssl cert)"
        ),
        **{k: [] for k in kinds},
    }
    failover: dict = {}
    try:
        for kind in kinds:  # warm every transport end to end
            run_kind(kind, 0.4)
        for i in range(pairs):
            order = kinds if i % 2 == 0 else list(reversed(kinds))
            for kind in order:
                r = run_kind(kind, seconds)
                out[kind].append(r)
                print(
                    f"# dist A/B pair {i} {kind}: {r['throughput']:.0f}/s "
                    f"p50={r['p50_ms']}ms p99={r['p99_ms']}ms",
                    file=sys.stderr,
                )
        # --- the failover window ----------------------------------------
        _tls_env(tls_ok)
        addrs = [
            f"127.0.0.1:{frontends.pick_free_port()}" for _ in range(2)
        ]
        planes = [frontends.start_compute_plane(master, a) for a in addrs]
        router = frontends.FleetPlaneRouter(
            addrs, conns=1, timeout=30, probe_s=0.1
        )
        lats2: list[list[float]] = [[] for _ in range(clients)]
        errors2: list[str] = []
        stop2 = _threading.Event()

        def hammer(i: int) -> None:
            while not stop2.is_set():
                t0 = time.perf_counter()
                try:
                    o = router.compute_raw(body, timeout=30)
                    lats2[i].append(time.perf_counter() - t0)
                    if not np.array_equal(
                        np.frombuffer(o, dtype="<i4"), want
                    ):
                        errors2.append(f"client {i}: wrong values")
                        return
                except Exception as e:  # noqa: BLE001 - the assertion
                    errors2.append(f"client {i}: {type(e).__name__}: {e}")
                    return

        try:
            threads = [
                _threading.Thread(target=hammer, args=(i,), daemon=True)
                for i in range(clients)
            ]
            for t in threads:
                t.start()
            time.sleep(failover_seconds * 0.4)
            kill_t = time.perf_counter()
            planes[1].close()  # the kill -9 stand-in: every conn dies
            time.sleep(failover_seconds * 0.6)
            stop2.set()
            for t in threads:
                t.join(timeout=30)
            kill_rel = round(time.perf_counter() - kill_t, 3)
        finally:
            router.close()
            for p in planes:
                p.close()
        flat2 = sorted(x for ls in lats2 for x in ls)
        failover = {
            "clients": clients,
            "transport": "tcp_mtls" if tls_ok else "tcp",
            "requests": len(flat2),
            "errors": len(errors2),
            "error_samples": errors2[:3],
            "p50_ms": round(1e3 * flat2[len(flat2) // 2], 3),
            "p99_ms": round(1e3 * flat2[int(len(flat2) * 0.99)], 3),
            # the failover window: the worst client-observed latency —
            # a hedged frame pays detection + redial + replay, never an
            # error
            "max_ms": round(1e3 * flat2[-1], 3),
            "post_kill_s": kill_rel,
        }
    finally:
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        master.pause()
        _shutil.rmtree(tmp, ignore_errors=True)
    for kind in kinds:
        rs = out[kind]
        out[f"{kind}_throughput"] = round(
            sorted(r["throughput"] for r in rs)[len(rs) // 2], 1
        )
        out[f"{kind}_p50_ms"] = sorted(r["p50_ms"] for r in rs)[len(rs) // 2]
    out["tcp_vs_unix"] = round(
        out["tcp_throughput"] / out["unix_throughput"], 3
    )
    if tls_ok:
        out["mtls_vs_tcp"] = round(
            out["tcp_mtls_throughput"] / out["tcp_throughput"], 3
        )
        out["mtls_vs_unix"] = round(
            out["tcp_mtls_throughput"] / out["unix_throughput"], 3
        )
    out["failover"] = failover
    return out


# The committed BENCH_cpu_r08.json 64-client x 64-value coalesced lane
# (concurrency_sweep_frontends) on this host.  bench_smoke gates the live
# measurement against HALF of it — a regression tripwire for the serve
# scheduler + partial-fill + frontend plane.  (ISSUE r8 originally asked
# for >= 50% of the single-big-batch rate; measured physics says no: a
# 64-value HTTP request costs ~100-200us of per-request Python across
# client+server, capping ANY single-GIL HTTP plane near ~3.5k req/s
# (~225k values/s) — under 10% of the 2.3M/s big-batch rate, which pays
# that cost once per 16k values.  The committed-lane gate pins what the
# architecture actually achieves instead of an unreachable ratio.)
R08_COALESCED_64 = 220_000.0

# The committed r11 multi-tenant capture on this host (64 clients split
# across dense/compact/chained registry tenants, engine=native, aggregate
# values/s through /programs/<name>/compute_raw).  bench_smoke gates at
# half: a regression in per-program routing, the registry lease path, or
# cross-engine contention trips it.  (The lane measures ~0.6x of the
# single-program 64-client in-harness rate — three engines coalesce
# independently, so each sees a third of the traffic.)
R11_MULTI_TENANT_64 = 49_000.0

# The committed r14 overload-drill capture on this host
# (BENCH_cpu_r14.json): 64 in-quota clients + 16 bulk-payload flooding
# clients at ~6x offered load, the flood shed at the door by the
# production edge (typed 429 + Retry-After, runtime/edge.py) — goodput
# held 0.91x of the same-run no-overload baseline with ZERO in-quota
# errors.  bench_smoke gates the live drill's GOODPUT at half: a
# regression in the edge chain, the worker shed cache, or the quota
# plumbing trips it (so does any untyped rejection — the drill's own
# `ok` folds in).
R14_OVERLOAD_GOODPUT = 167_753.6

# The committed r13 fleet capture on this host (BENCH_cpu_r13.json): a
# REAL MISAKA_FLEET=4 subprocess fleet — 4 engine replicas behind the
# shared SO_REUSEPORT frontend tier, FleetPlaneRouter least-depth
# dispatch, 64 keep-alive clients x 64-value payloads.  bench_smoke
# gates the live measurement at HALF: a regression in the fleet router,
# the plane-conns coalescing discipline, or replica supervision trips
# it.  (3.35x the single-engine in-harness rate measured the same day —
# the r8 single-process wall, horizontally broken.)
R13_FLEET_64 = 237_980.6

# The committed r16 SIMD pool capture on this host (BENCH_cpu_r16.json):
# the struct-of-arrays group engine (AVX2, kGroupW=8) + per-program
# specialized ticks at 24 threads, measured by bench_native_pool's light
# harness (np.array_equal parity — see its docstring; the r13-era ~11.4M
# scalar number carried ~1.5 ms/round of harness on top of the old
# engine).  bench_smoke gates the live pool at 50% — per the repo's
# gate-at-50%-to-ride-the-±30%-box-spread discipline — which also keeps
# the ISSUE 12 acceptance floor (2.5x the 11.4M r13-era baseline = 28.5M)
# above the gate only at capture time, not on every noisy CI box.
R16_SIMD_POOL = 29_730_382.4

# The box the r08-r16 absolute captures were taken on (24 cores).  The
# r17 container exposes ONE cpu (BENCH_HISTORY r17), where those gates
# are physically unreachable on any code: bench-smoke SKIPS a cross-box
# absolute gate — loudly, with the measurement still recorded — when the
# current box has less than half the capture box's cores, so the gates
# stay armed on comparable hardware instead of failing every CI run for
# environmental reasons.
CAPTURE_BOX_CPUS = 24


def _cross_box() -> bool:
    return (os.cpu_count() or 1) < CAPTURE_BOX_CPUS // 2

# r17 resident-state serving: calls/s of the RESIDENT full-batch serve at
# B=256 with one fed value — the per-call overhead lane (the stateless
# twin measured 2.2x slower same-harness; BENCH_cpu_r17.json, captured on
# a 1-CPU container — see BENCH_HISTORY r17 for the box-change note).
R17_CALL_OVERHEAD_256 = 11_673.5

# r19 native serving edge: 64-client keep-alive req/s of 64-value
# /compute_raw through the C++ epoll frontend (native/frontend.cpp),
# median across ABBA pairs vs the CPython worker tier on one shared
# engine (BENCH_cpu_r19.json, captured on the same 1-CPU container as
# r17/r18: 1421.6 req/s vs 1002.1 for the workers, 1.42x with p50
# 43ms vs 61ms — core-starved; the >=3x-vs-CPython acceptance is
# recorded there but arms only on >= CAPTURE_BOX_CPUS/2 cores).
R19_EDGE_NATIVE_REQ_S = 1_421.6

# r21 copy-and-patch + pack-row elision (BENCH_cpu_r21.json, captured on
# the same 1-CPU container as r17/r19 — absolute rates are core-starved,
# the A/B ratios are the portable story): full-fill serve through the
# JIT fragment tables at B=256, values/s (1.26x the switch-threaded rung
# same-harness), and the elision lane's armed calls/s at B=4096 (1-hot
# resident sparse fill, threads=1 + thread_time — see bench_elision_sweep;
# 1.89x the repack-everything path, 4.99x at the B=16384 asymptote).
R21_JIT_POOL_256 = 4_666_509.2
R21_ELISION_ON_4096 = 21_632.1

# r22 multi-host plane (BENCH_cpu_r22.json, captured on the same 1-CPU
# container as r17-r21, so the gate stays armed everywhere): the mTLS
# TCP transport lane — 16 PlaneClient threads x 64-value MSK1 frames
# against one native master, CA-pinned TLS around the HMAC handshake —
# measured 0.83x the unix-socket plane same-run (195.6k vs 235.5k
# values/s; the TLS record layer + loopback TCP is the whole gap).  The
# failover lane (one of two planes killed mid-load through
# FleetPlaneRouter) is gated on ZERO errors, not throughput: its max
# client-observed latency (45ms captured) IS the failover window.
R22_PLANE_MTLS_64 = 195_601.2


def bench_smoke(target=NORTH_STAR):
    """`make bench-smoke`: a ~5s bench_served through the multi-threaded
    native tier; exits nonzero below the 1M/s north star, so a regression
    of the CPU-fallback serving path is caught BEFORE a driver capture
    lands on it (the r4/r5 captures served scan-compact at 0.16-0.34M/s
    with this tier sitting unused).  Since r8 it also drives the
    64-client x 64-value coalesced lane through the frontend serving
    plane and fails below 50% of the committed r08 capture."""
    served = bench_served(mode="raw", waves=4, engine="native")
    line = {
        "metric": "bench_smoke_served_throughput",
        "value": round(served["throughput"], 1),
        "unit": "inputs/sec",
        "served_engine": served["engine"],
        "batch": served["batch"],
        "threads": served["threads"],
        "target": target,
        "ok": bool(served["throughput"] >= target and served["engine"] == "native"),
        "metrics_delta": served.get("metrics_delta"),
    }
    try:
        sweep = bench_concurrency_sweep(
            clients=(64,), seconds=2.0, engine="native",
            http_workers=6, fleet_procs=4,
        )
        small = sweep["lanes"][0]["throughput"]
        line["coalesced_small_throughput"] = round(small, 1)
        line["coalesced_small_p50_ms"] = sweep["lanes"][0]["p50_ms"]
        line["coalesced_target"] = round(0.5 * R08_COALESCED_64, 1)
        if small < 0.5 * R08_COALESCED_64:
            if _cross_box():
                line.setdefault("cross_box_gates_skipped", []).append("r08")
                print(
                    f"# bench-smoke: r08 coalesced gate SKIPPED cross-box "
                    f"({os.cpu_count()} cpus vs the {CAPTURE_BOX_CPUS}-core "
                    f"capture box); measured {small:.0f}/s",
                    file=sys.stderr,
                )
            else:
                line["ok"] = False
                print(
                    f"# bench-smoke: coalesced 64-client lane "
                    f"{small:.0f}/s < {0.5 * R08_COALESCED_64:.0f}/s "
                    f"(50% of the committed r08 capture)",
                    file=sys.stderr,
                )
    except Exception as e:  # infra failure IS a smoke failure
        line["ok"] = False
        line["coalesced_error"] = str(e)[:200]
    try:
        # the registry lane: 64 clients across three per-program engines
        # (cross-box: 16 — a 64-CPython-client stampede on a 1-core box
        # starves the registry's activation path into drain timeouts,
        # measured identically on pre-r17 code; the attribution and
        # conservation gates below stay fully armed either way)
        mt = bench_multi_tenant(
            clients=64 if not _cross_box() else 16,
            seconds=1.5, engine="native",
        )
        agg = mt["aggregate"]["throughput"]
        line["multi_tenant_throughput"] = round(agg, 1)
        line["multi_tenant_p50_ms"] = mt["aggregate"]["p50_ms"]
        line["multi_tenant_target"] = round(0.5 * R11_MULTI_TENANT_64, 1)
        if agg < 0.5 * R11_MULTI_TENANT_64:
            if _cross_box():
                line.setdefault("cross_box_gates_skipped", []).append("r11")
                print(
                    f"# bench-smoke: r11 multi-tenant gate SKIPPED "
                    f"cross-box; measured {agg:.0f}/s",
                    file=sys.stderr,
                )
            else:
                line["ok"] = False
                print(
                    f"# bench-smoke: multi-tenant lane {agg:.0f}/s < "
                    f"{0.5 * R11_MULTI_TENANT_64:.0f}/s "
                    f"(50% of the committed r11 capture)",
                    file=sys.stderr,
                )
        # the r12 attribution gate: per-program CPU-seconds must be
        # nonzero for every tenant and sum to within 20% of the total
        # fused-pass wall time (the independently-accumulated anchor) —
        # a broken ledger is an observability regression, not a perf one
        ud = mt.get("usage_delta") or {}
        line["usage_delta"] = ud
        progs = ud.get("programs", {})
        conservation = ud.get("conservation")
        # every EXPECTED tenant must appear under its own name — a tenant
        # whose attribution is lost or collapsed into "other" would
        # otherwise pass (the remaining labels still sum to ~1.0), which
        # is exactly the per-tenant regression this gate exists to catch
        expected = {"dense", "compact", "chained"}
        attributed_ok = bool(
            expected <= set(progs)
            and all(
                progs[t].get("cpu_seconds", 0) > 0 for t in expected
            )
            and conservation is not None
            and 0.8 <= conservation <= 1.2
        )
        if not attributed_ok:
            line["ok"] = False
            print(
                f"# bench-smoke: usage attribution FAILED "
                f"(conservation={conservation}, programs="
                f"{ {k: p.get('cpu_seconds') for k, p in progs.items()} })",
                file=sys.stderr,
            )
    except Exception as e:  # infra failure IS a smoke failure
        line["ok"] = False
        line["multi_tenant_error"] = str(e)[:200]
    try:
        # the fleet lane (r13): 4 engine replicas, 64 keep-alive clients
        fl = bench_fleet_scaling(replicas=(4,), seconds=2.0)
        agg = fl["lanes"][0]["throughput"]
        line["fleet_throughput"] = round(agg, 1)
        line["fleet_p50_ms"] = fl["lanes"][0]["p50_ms"]
        line["fleet_target"] = round(0.5 * R13_FLEET_64, 1)
        if agg < 0.5 * R13_FLEET_64:
            if _cross_box():
                line.setdefault("cross_box_gates_skipped", []).append("r13")
                print(
                    f"# bench-smoke: r13 fleet gate SKIPPED cross-box; "
                    f"measured {agg:.0f}/s",
                    file=sys.stderr,
                )
            else:
                line["ok"] = False
                print(
                    f"# bench-smoke: fleet 4-replica lane {agg:.0f}/s < "
                    f"{0.5 * R13_FLEET_64:.0f}/s "
                    f"(50% of the committed r13 capture)",
                    file=sys.stderr,
                )
    except Exception as e:  # infra failure IS a smoke failure
        line["ok"] = False
        line["fleet_error"] = str(e)[:200]
    try:
        drill = bench_overload(seconds=2.0)
        over = drill["overload"]
        goodput = over["goodput"]
        line["overload_goodput"] = round(goodput, 1)
        line["overload_target"] = round(0.5 * R14_OVERLOAD_GOODPUT, 1)
        line["overload_drill_ok"] = drill["ok"]  # incl. the 0.85 hold
        if goodput < 0.5 * R14_OVERLOAD_GOODPUT:
            if _cross_box():
                line.setdefault("cross_box_gates_skipped", []).append("r14")
                print(
                    f"# bench-smoke: r14 goodput gate SKIPPED cross-box; "
                    f"measured {goodput:.0f}/s",
                    file=sys.stderr,
                )
            else:
                line["ok"] = False
                print(
                    f"# bench-smoke: overload-drill goodput "
                    f"{goodput:.0f}/s < {0.5 * R14_OVERLOAD_GOODPUT:.0f}/s "
                    f"(50% of the committed r14 capture)",
                    file=sys.stderr,
                )
        # the typed-shed contract gates HARD even in the short smoke
        # window (the 0.85 goodput hold is the full lane's criterion —
        # too noise-sensitive at smoke duration, reported not gated)
        if (
            over["good_tenant_errors"]
            or over["flood_tenant_untyped"]
            or over["missing_retry_after"]
            or not over["rejected"]
        ):
            line["ok"] = False
            print(
                "# bench-smoke: overload drill shed contract FAILED "
                "(untyped rejections or in-quota tenant errors)",
                file=sys.stderr,
            )
    except Exception as e:  # infra failure IS a smoke failure
        line["ok"] = False
        line["overload_error"] = str(e)[:200]
    try:
        # the r16 SIMD pool gate: the direct (no-HTTP) group-engine rate
        # at full thread count, 50% of the committed capture
        pool = bench_native_pool(rounds=3)
        line["simd_pool_throughput"] = round(pool["throughput"], 1)
        line["simd_pool_info"] = pool["simd"]
        line["simd_pool_target"] = round(0.5 * R16_SIMD_POOL, 1)
        if pool["throughput"] < 0.5 * R16_SIMD_POOL:
            if _cross_box():
                line.setdefault("cross_box_gates_skipped", []).append("r16")
                print(
                    f"# bench-smoke: r16 SIMD pool gate SKIPPED cross-box; "
                    f"measured {pool['throughput']:.0f}/s",
                    file=sys.stderr,
                )
            else:
                line["ok"] = False
                print(
                    f"# bench-smoke: SIMD pool {pool['throughput']:.0f}/s "
                    f"< {0.5 * R16_SIMD_POOL:.0f}/s "
                    f"(50% of the committed r16 capture)",
                    file=sys.stderr,
                )
        # the r17 residency gate: resident serve-call rate at B=256,
        # 50% of the committed capture (the per-call overhead lane)
        co = bench_call_overhead(batches=(256,), rounds=150)["256"]
        line["call_overhead_256"] = co
        line["call_overhead_target"] = round(0.5 * R17_CALL_OVERHEAD_256, 1)
        if co["resident"]["calls_per_s"] < 0.5 * R17_CALL_OVERHEAD_256:
            line["ok"] = False
            print(
                f"# bench-smoke: resident call rate "
                f"{co['resident']['calls_per_s']:.0f}/s < "
                f"{0.5 * R17_CALL_OVERHEAD_256:.0f}/s "
                f"(50% of the committed r17 capture)",
                file=sys.stderr,
            )
        # the r19 native-edge gate: 64-client keep-alive req/s through
        # the C++ frontend at 50% of the committed capture.  Cross-box
        # (< CAPTURE_BOX_CPUS/2 cores) the gate SKIPS loudly with the
        # measurement still recorded, per the r16 discipline; the
        # vs-CPython >=3x acceptance lives in the standalone
        # --edge-native lane, armed under the same core floor.
        ena = bench_edge_native_ab(pairs=1, seconds=1.2)
        line["edge_native_req_s"] = ena["native_req_s_median"]
        line["edge_native_target"] = round(0.5 * R19_EDGE_NATIVE_REQ_S, 1)
        if ena["native_req_s_median"] < 0.5 * R19_EDGE_NATIVE_REQ_S:
            if _cross_box():
                line.setdefault("cross_box_gates_skipped", []).append("r19")
                print(
                    f"# bench-smoke: r19 native-edge gate SKIPPED "
                    f"cross-box; measured "
                    f"{ena['native_req_s_median']:.0f} req/s",
                    file=sys.stderr,
                )
            else:
                line["ok"] = False
                print(
                    f"# bench-smoke: native edge "
                    f"{ena['native_req_s_median']:.0f} req/s < "
                    f"{0.5 * R19_EDGE_NATIVE_REQ_S:.0f} req/s "
                    f"(50% of the committed r19 capture)",
                    file=sys.stderr,
                )
        # the r21 JIT + elision gates: both captured on the 1-CPU box
        # (like r17), so they stay armed everywhere — 50% of the
        # committed absolute rates, with the full ratio acceptance
        # (>=1.15x JIT, >=2x elision asymptote) living in --elision
        jab = bench_jit_ab(batches=(256,), pairs=1, rounds=10)["256"]
        line["jit_pool_256"] = jab["jit_median"]
        line["jit_pool_target"] = round(0.5 * R21_JIT_POOL_256, 1)
        if jab["jit_median"] < 0.5 * R21_JIT_POOL_256:
            line["ok"] = False
            print(
                f"# bench-smoke: JIT pool {jab['jit_median']:.0f}/s < "
                f"{0.5 * R21_JIT_POOL_256:.0f}/s "
                f"(50% of the committed r21 capture)",
                file=sys.stderr,
            )
        el = bench_elision_sweep(batches=(4096,), pairs=1)["4096"]
        line["elision_on_4096"] = el["on_median"]
        line["elision_target"] = round(0.5 * R21_ELISION_ON_4096, 1)
        if el["on_median"] < 0.5 * R21_ELISION_ON_4096:
            line["ok"] = False
            print(
                f"# bench-smoke: elided resident calls "
                f"{el['on_median']:.0f}/s < "
                f"{0.5 * R21_ELISION_ON_4096:.0f}/s "
                f"(50% of the committed r21 capture)",
                file=sys.stderr,
            )
        # the r22 multi-host gates (captured on the 1-CPU box, armed
        # everywhere): the mTLS plane transport at 50% of the committed
        # capture, and the router failover drill at ZERO client errors.
        # Without openssl the lane runs plain TCP and the throughput
        # gate reads that lane instead (same codec, same gate bar).
        dab = bench_dist_ab(pairs=1, seconds=1.0)
        mtls = dab.get("tcp_mtls_throughput", dab["tcp_throughput"])
        line["dist_mtls_throughput"] = mtls
        line["dist_mtls_target"] = round(0.5 * R22_PLANE_MTLS_64, 1)
        line["dist_failover_errors"] = dab["failover"]["errors"]
        line["dist_failover_max_ms"] = dab["failover"]["max_ms"]
        if mtls < 0.5 * R22_PLANE_MTLS_64:
            line["ok"] = False
            print(
                f"# bench-smoke: mTLS plane {mtls:.0f}/s < "
                f"{0.5 * R22_PLANE_MTLS_64:.0f}/s "
                f"(50% of the committed r22 capture)",
                file=sys.stderr,
            )
        if dab["failover"]["errors"]:
            line["ok"] = False
            print(
                f"# bench-smoke: {dab['failover']['errors']} client "
                f"error(s) through the r22 failover drill (want 0): "
                f"{dab['failover']['error_samples']}",
                file=sys.stderr,
            )
    except Exception as e:  # infra failure IS a smoke failure
        line["ok"] = False
        line["simd_pool_error"] = str(e)[:200]
    print(json.dumps(line))
    if not line["ok"]:
        print(
            f"# bench-smoke FAILED: {served['engine']} served "
            f"{served['throughput']:.0f}/s (target {target:.0f}/s); "
            f"coalesced lane {line.get('coalesced_small_throughput')}",
            file=sys.stderr,
        )
        sys.exit(1)


def bench_lanes(n_lanes, batch=None, per_instance=32, engine="dense", min_time=1.0):
    """Ticks/s of one engine on an n-stage pipeline: the routing-cliff probe.

    The DENSE scan engine's one-hot dest matrix is O(N·4N) per tick (enough
    to fault the TPU worker at 256 lanes x production batches — which is why
    CompiledNetwork auto-switches to the COMPACT scatter-election kernel,
    core/routing.py, at COMPACT_AUTO_LANES); the fused kernel unrolls
    per-instruction sends.  This measures where each engine bends
    ("arbitrary number of program nodes", README.md:10-18).  The dense
    batch shrinks with N^2 to bound the election-matrix footprint, and short
    runs repeat until `min_time` to amortize the relayed-device dispatch
    latency (~0.1-0.4s/call, which otherwise IS the number at 8 lanes).
    Completion and output parity (v + n) are asserted per repetition.
    """
    import jax
    import jax.numpy as jnp

    from misaka_tpu import networks

    on_tpu = jax.devices()[0].platform == "tpu"
    if batch is None:
        batch = 4096 if on_tpu else 64
        if engine == "dense":
            # Keep the dense one-hot intermediate (batch x N x 4N bool) under
            # ~4 MiB: 64 lanes x 4096 batch (67 MiB) was measured to wedge
            # or fault the r4 TPU worker (for 1h+, unrecoverable locally);
            # 1 GiB (256 x 4096) faults it reliably.  Wide margin on purpose
            # — the artifact matters more than dense wide-lane fidelity.
            batch = min(batch, max(16, 2**22 // (4 * n_lanes * n_lanes)))
        elif engine in ("compact", "chained"):
            # Elections are linear in batch*N (scatter or chained); cap the
            # index space at the measured-safe region (256 lanes x 1024
            # batch ran clean; 256 x 4096 has faulted once in a
            # mixed-config sequence).
            batch = min(batch, max(128, 2**18 // n_lanes))
        elif engine == "fused" and on_tpu and n_lanes >= 64:
            # 64 lanes = 1,102 carry rows: the 4 MB carry budget rejects
            # every >=1024 block and Mosaic tiling rejects every partial
            # <1024 block (fused.py eager check), so the only viable wide
            # fused config is single-block with batch <= 512.
            batch = min(batch, 512)
    top = networks.pipeline(
        n_lanes, in_cap=per_instance, out_cap=per_instance, stack_cap=8
    )
    net = top.compile(batch=batch)

    rng = np.random.default_rng(2)
    vals = rng.integers(-1000, 1000, size=(batch, per_instance)).astype(np.int32)

    def fresh_state():
        state = net.init_state()
        return state._replace(
            in_buf=jnp.asarray(vals),
            in_wr=state.in_wr + np.int32(per_instance),
        )

    # fill (3 ticks/stage) + drain (3 ticks/value) + slack
    ticks = 3 * n_lanes + 3 * per_instance + 64
    block_used = None
    if engine == "fused":
        # wide nets blow the VMEM carry budget at big blocks (64 lanes =
        # 1102 carry rows = 9 MB at block 2048): the shared walk
        # (engine.fused_runner_walk) picks the largest fitting block
        runner, block_used = net.fused_runner_walk(
            ticks, candidates=(2048, 1024, 512, 256, 128)
        )
    else:
        runner = lambda s: net.run(s, ticks, engine=engine)

    def once():
        state = fresh_state()
        _ = int(np.asarray(state.tick)[0])
        t0 = time.perf_counter()
        state = runner(state)
        done = int(np.asarray(state.out_wr).min())  # sync point
        dt = time.perf_counter() - t0
        assert done >= per_instance, f"lanes={n_lanes}: incomplete {done}/{per_instance}"
        np.testing.assert_array_equal(np.asarray(state.out_buf), vals + n_lanes)
        return dt

    once()  # warm-up compile
    # best-of-reps since r4 (r3 and earlier: single timed run); median is
    # emitted alongside so single-shot rounds stay comparable
    times, elapsed, median = _repeat_best(once, once(), min_time, 6)

    total = batch * per_instance
    out = {
        "lanes": n_lanes,
        "engine": engine,
        "batch": batch,
        "ticks": ticks,
        "reps": len(times),
        "ticks_per_sec": ticks / elapsed,
        "ticks_per_sec_median": ticks / median,
        "throughput": total / elapsed,
        "elapsed_s": elapsed,
    }
    if block_used is not None:
        # provenance: a ticks/s shift must be attributable to code vs a
        # silently different block size picked by the walk
        out["block_batch"] = block_used
    return out


def bench_roofline(batches=(65536, 262144, 1048576), per_instance=128):
    """add2 fused-kernel ticks/s across batch sizes — the measured side of
    ARCHITECTURE.md's perf model (is 136M values/s compute- or
    dispatch-bound, and what does the batch axis buy?)."""
    out = []
    for b in batches:
        r = bench_config("add2", batch=b, per_instance=per_instance)
        out.append(
            {
                "batch": b,
                "ticks_per_sec": round(r["ticks_per_sec"], 1),
                "throughput": round(r["throughput"], 1),
            }
        )
        print(
            f"# roofline add2: batch={b} ticks/s={r['ticks_per_sec']:.0f} "
            f"throughput={r['throughput']:.0f}/s",
            file=sys.stderr,
        )
    return out


def bench_sharded(n_devices=8, batch=512, per_instance=32, timeout=900):
    """Measure the lane-sharded (model-parallel) engine on a virtual N-device
    CPU mesh vs the single-device scan engine on the SAME network and batch —
    the first recorded numbers for parallel/sharded.py's per-tick collective
    design (VERDICT r2 weak #4).

    Runs in a subprocess because the virtual device count must be set before
    JAX initializes.  The absolute ticks/sec are CPU numbers; the deliverable
    is the sharded/single ratio — the replication+collective overhead a real
    multi-chip mesh must amortize — plus a mesh-served throughput through the
    product MasterNode path with output parity.
    """
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env.update(
        {
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",
            "XLA_FLAGS": env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n_devices}",
        }
    )
    out = subprocess.run(
        [
            sys.executable, os.path.abspath(__file__), "--sharded-worker",
            str(n_devices), str(batch), str(per_instance),
        ],
        env=env, capture_output=True, text=True, timeout=timeout,
    )
    if out.returncode != 0:
        raise RuntimeError(f"sharded bench failed: {out.stderr[-2000:]}")
    return json.loads(out.stdout.strip().splitlines()[-1])


def _sharded_worker(n_devices, batch, per_instance):
    """Subprocess body for bench_sharded (runs on the virtual CPU mesh)."""
    import jax
    import jax.numpy as jnp

    from misaka_tpu import networks
    from misaka_tpu.parallel.mesh import make_mesh, shard_state
    from misaka_tpu.parallel.routed import make_routed_runner
    from misaka_tpu.parallel.sharded import make_sharded_runner
    from misaka_tpu.runtime.master import MasterNode

    assert len(jax.devices()) >= n_devices, "virtual device count not applied"
    top = networks.mesh8(in_cap=per_instance, out_cap=per_instance, stack_cap=16)
    net = top.compile(batch=batch)
    steps = 12 * per_instance + 256

    rng = np.random.default_rng(0)
    vals = rng.integers(-1000, 1000, size=(batch, per_instance)).astype(np.int32)

    def fresh_state():
        state = net.init_state()
        return state._replace(
            in_buf=jnp.asarray(vals),
            in_wr=state.in_wr + np.int32(per_instance),
        )

    def timed(runner, place):
        s = runner(place(fresh_state()))          # warm-up compile
        _ = int(np.asarray(s.tick)[0])
        s = place(fresh_state())
        _ = int(np.asarray(s.tick)[0])
        t0 = time.perf_counter()
        s = runner(s)
        done = int(np.asarray(s.out_wr).min())    # sync point
        dt = time.perf_counter() - t0
        assert done >= per_instance, f"incomplete: {done}/{per_instance}"
        out = np.sort(np.asarray(s.out_buf)[:, :per_instance], axis=1)
        np.testing.assert_array_equal(out, np.sort(vals + 4, axis=1))
        return dt

    mesh = make_mesh(n_devices, model_parallel=n_devices)
    # The headline model-parallel number is the statically-routed
    # two-collective kernel (parallel/routed.py, the default serving engine);
    # the first-generation occupancy-gather kernel rides along as the A/B
    # comparison the routed design must beat (VERDICT r3 item 2).
    routed = make_routed_runner(
        net.code, net.prog_len, mesh, num_steps=steps, batched=True
    )
    dt_routed = timed(routed, lambda s: shard_state(s, mesh, batched=True))
    gather = make_sharded_runner(
        net.code, net.prog_len, mesh, num_steps=steps, batched=True
    )
    dt_gather = timed(gather, lambda s: shard_state(s, mesh, batched=True))
    # TWO single-chip baselines since r5: the platform-auto kernel (what a
    # user actually gets — compact on CPU since the crossover change) and
    # dense (r4-and-earlier's auto at 8 lanes, kept for cross-round
    # continuity).  The auto baseline moving is exactly why ratios must
    # name their denominator.
    dt_single = timed(lambda s: net.run(s, steps), lambda s: s)
    dt_single_dense = timed(
        lambda s: net.run(s, steps, engine="dense"), lambda s: s
    )

    # Mesh serving through the product path: MasterNode + compute_spread,
    # SUSTAINED (8 client threads x waves keep the pipeline full) and
    # measured against the identical single-chip serve on the SAME network,
    # in_cap, and chunk — r4's one-shot spread vs the add2 HTTP number read
    # as a 12-20x serving gap that does not exist (VERDICT r4 weak #3).
    # chunk_steps ~ ticks-per-feed (12 ticks/value * in_cap): an oversized
    # chunk burns dead ticks after the ring drains (2048 measured 5x slower
    # than 256 at in_cap=32).
    import threading as _threading

    def serve_sustained(mp, threads=8, waves=3):
        kw = dict(data_parallel=1, model_parallel=mp) if mp > 1 else {}
        master = MasterNode(
            top, chunk_steps=256, batch=batch, engine="scan", **kw
        )
        master.run()
        per_request = (batch // threads) * per_instance
        try:
            warm = rng.integers(-1000, 1000, size=per_request).astype(np.int32)
            np.testing.assert_array_equal(
                master.compute_spread(warm, timeout=600, return_array=True),
                warm + 4,
            )
            errs: list[Exception] = []

            def client(seed):
                try:
                    r = np.random.default_rng(seed)
                    for _ in range(waves):
                        vals = r.integers(
                            -1000, 1000, size=per_request
                        ).astype(np.int32)
                        got = master.compute_spread(
                            vals, timeout=600, return_array=True
                        )
                        np.testing.assert_array_equal(got, vals + 4)
                except Exception as e:  # pragma: no cover — surfaced below
                    errs.append(e)

            ts = [
                _threading.Thread(target=client, args=(7 + i,))
                for i in range(threads)
            ]
            t0 = time.perf_counter()
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            dt = time.perf_counter() - t0
            if errs:
                raise errs[0]
            return threads * waves * per_request / dt
        finally:
            master.pause()

    served_mesh = serve_sustained(n_devices)
    served_single = serve_sustained(1)

    total = batch * per_instance
    print(json.dumps({
        "n_devices": n_devices,
        "batch": batch,
        "ticks": steps,
        # `sharded_*` = THE model-parallel engine (now parallel/routed.py;
        # r3 and earlier it was the gather kernel — engine names below keep
        # cross-round comparisons honest).
        "sharded_engine": "routed",
        "routed_ticks_per_sec": round(steps / dt_routed, 1),
        "gather_ticks_per_sec": round(steps / dt_gather, 1),
        # single_* = the platform-AUTO kernel (compact on CPU since the r5
        # crossover change; r4's auto at 8 lanes was dense).
        # single_dense_* keeps r4's denominator comparable across rounds.
        "single_engine": "auto",
        "single_ticks_per_sec": round(steps / dt_single, 1),
        "single_dense_ticks_per_sec": round(steps / dt_single_dense, 1),
        "sharded_ticks_per_sec": round(steps / dt_routed, 1),
        "sharded_vs_single": round(dt_single / dt_routed, 4),
        "sharded_vs_single_dense": round(dt_single_dense / dt_routed, 4),
        "gather_vs_single": round(dt_single / dt_gather, 4),
        "routed_vs_gather": round(dt_gather / dt_routed, 4),
        "sharded_throughput": round(total / dt_routed, 1),
        # sustained (threads x waves) since r5; r4's one-shot spread for the
        # same config measured 6356/s — compare methodology, not just values
        "mesh_served_mode": "sustained-8x3",
        "mesh_served_throughput": round(served_mesh, 1),
        "single_served_throughput": round(served_single, 1),
        "mesh_served_vs_single": round(served_mesh / served_single, 4),
    }))


def bench_latency_http(samples=200, warmup=20, engine="auto"):
    """p50/p99 of a REAL single-value HTTP POST /compute against a running
    master — the number a reference client would see (the kernel-floor
    variant below strips the HTTP+queue layers).  engine="native" measures
    the host-interpreter latency tier (core/native_serve.py): zero device
    dispatches on the request path."""
    import threading as _threading
    import urllib.request
    from urllib.parse import urlencode

    from misaka_tpu import networks
    from misaka_tpu.runtime.master import MasterNode, make_http_server

    top = networks.add2(in_cap=16, out_cap=16, stack_cap=16)
    master = MasterNode(top, chunk_steps=16, engine=engine)
    httpd = make_http_server(master, port=0)
    _threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    master.run()

    def one(v):
        body = urlencode({"value": str(v)}).encode()
        req = urllib.request.Request(base + "/compute", data=body, method="POST")
        t0 = time.perf_counter()
        with urllib.request.urlopen(req, timeout=30) as resp:
            out = json.loads(resp.read())["value"]
        dt = time.perf_counter() - t0
        assert out == v + 2, (out, v)
        return dt

    try:
        for i in range(warmup):
            one(i)
        times = [one(i) for i in range(samples)]
    finally:
        master.pause()
        httpd.shutdown()
    us = np.asarray(times) * 1e6
    return {
        "p50_us": float(np.percentile(us, 50)),
        "p99_us": float(np.percentile(us, 99)),
        "samples": samples,
    }


def bench_latency(samples=200, chunk=16, warmup=20):
    """Single-value end-to-end latency through the engine (unbatched add2).

    Uses the minimal-sync serving shape: enqueue + `chunk` supersteps +
    drain fused into ONE jitted call, so a request costs one dispatch and
    one scalar readback — the per-request latency floor (the HTTP master
    adds queue hops on top).  Returns p50/p99 in microseconds.  Note: on a
    relayed/remote device this mostly measures the host<->device link.
    """
    import jax
    import numpy as np

    from misaka_tpu import networks
    from misaka_tpu.core.step import step

    net = networks.add2(in_cap=16, out_cap=16, stack_cap=16).compile()
    code, prog_len = net._tables

    import functools

    @functools.partial(jax.jit, donate_argnums=(0,))
    def compute_one(state, v):
        in_cap = state.in_buf.shape[0]
        out_cap = state.out_buf.shape[0]
        state = state._replace(
            in_buf=state.in_buf.at[state.in_wr % in_cap].set(v),
            in_wr=state.in_wr + 1,
        )

        def body(s, _):
            return step(code, prog_len, s), None

        state, _ = jax.lax.scan(body, state, None, length=chunk)
        out_val = state.out_buf[(state.out_wr - 1) % out_cap]
        done = state.out_wr - state.out_rd  # 1 iff the value retired in-chunk
        return state._replace(out_rd=state.out_wr), out_val, done

    state = net.init_state()

    def one(state, v):
        t0 = time.perf_counter()
        state, out, done = compute_one(state, v)
        out = int(out)  # the single host sync
        dt = time.perf_counter() - t0
        assert int(done) == 1 and out == v + 2, (out, int(done))
        return state, dt

    for i in range(warmup):
        state, _ = one(state, i)
    times = []
    for i in range(samples):
        state, dt = one(state, i)
        times.append(dt)
    us = np.asarray(times) * 1e6
    return {
        "p50_us": float(np.percentile(us, 50)),
        "p99_us": float(np.percentile(us, 99)),
        "samples": samples,
        "chunk": chunk,
    }


def main():
    _arm_ttl()
    _preflight()
    _enable_compile_cache()
    backend_up = _arm_init_watchdog()
    import jax

    # reduced means reduced: in fallback mode the full-config sweep is
    # ignored even if the flag leaked through (the exec path also strips it)
    run_all = "--all" in sys.argv and os.environ.get("MISAKA_BENCH_FALLBACK") != "cpu"
    try:
        platform = jax.devices()[0].platform
    except Exception as e:
        # transient init crash (r3's rc=1): bounded re-exec retries with
        # backoff, then the labeled CPU fallback — see _retry_or_fallback
        _retry_or_fallback(e)
        raise  # unreachable in production (the helper execve()s or raises)
    backend_up()

    payload = _PAYLOAD  # module global: the TTL watchdog dumps partial runs
    fallback = os.environ.get("MISAKA_BENCH_FALLBACK") == "cpu"
    # labels go in BEFORE any measuring: a partial TTL dump must never emit
    # CPU numbers indistinguishable from TPU ones
    payload["platform"] = platform
    attach_err = os.environ.get("MISAKA_TPU_ATTACH_ERROR")
    if attach_err:
        # why this capture is (or nearly was) a CPU one: the last attach
        # failure, surviving retries — on a platform=tpu payload it means
        # the retry loop RECOVERED the chip
        payload["tpu_attach_error"] = attach_err
    if os.environ.get("MISAKA_ATTACH_ATTEMPT"):
        payload["tpu_attach_attempts"] = int(os.environ["MISAKA_ATTACH_ATTEMPT"])
    if fallback:
        payload["fallback"] = "cpu (TPU backend unavailable at init)"
        # a reduced CPU number reads as a 1000x regression unless the artifact
        # carries the last real TPU measurement alongside it
        last = _last_tpu_context()
        if last:
            payload["last_tpu"] = last
    results = {}
    for name in CONFIGS if run_all else ["add2"]:
        # fallback mode shrinks the batch: the CPU number is an honest
        # label, not a target, and the artifact must fit a tight budget
        # TPU headline batch 1048576 since late r5: the batch probe
        # measured 262144 -> 153.0M/s, 524288 -> 157.0M/s, 1048576 ->
        # 163.3M/s (artifacts/r05/headline_batch_probe.json) — per-tick
        # fixed cost keeps amortizing past 262k, matching the roofline
        # sweep's shape.  CPU keeps 262144: a 4x bigger batch would eat
        # the outage-round artifact's TTL for no headline (CPU is
        # host-bound) and break comparability with BENCH_cpu_r04/r05.
        # Only the add2 HEADLINE runs at the 1048576 measured-best batch:
        # five configs at 1M (one fresh ~60s compile each + 4 reps of ~0.8s)
        # measured past the 1140s whole-run TTL (BENCH_tpu_r05_all_b1m.json
        # is the resulting honest partial) — secondary configs keep 262144.
        # CPU headline batch 65536 since r6: this container's XLA-CPU scan
        # measured 63k values/s (jax 0.4 runtime) — 262144 costs ~530s PER
        # RUN, so the r4/r5 batch blows the whole-run TTL before a single
        # served number lands.  The payload records `batch`, and throughput
        # is amortized-fixed-cost-flat at these sizes, so the headline
        # stays cross-round comparable.
        big = platform == "tpu" and name == "add2"
        if fallback:
            batch = 32768
        elif platform == "tpu":
            batch = 1048576 if big else 262144
        else:
            batch = 65536
        r = bench_config(name, batch=batch)
        results[name] = r
        print(
            f"# {name}: platform={platform} batch={r['batch']} "
            f"q={r['per_instance']} values={r['values']} "
            f"elapsed={r['elapsed_s']:.3f}s ticks={r['ticks']} "
            f"ticks/value={r['ticks_per_value']:.2f} "
            f"throughput={r['throughput']:.0f}/s",
            file=sys.stderr,
        )
        # straight into the watchdog-dumped payload: a wedge mid---all must
        # not lose the configs that already finished
        payload.setdefault("configs", {})[name] = round(r["throughput"], 1)

    headline = results["add2"]
    payload.update(
        metric="add2_compute_throughput",
        value=round(headline["throughput"], 1),
        value_median=round(headline["throughput_median"], 1),
        reps=headline["reps"],
        unit="inputs/sec",
        vs_baseline=round(headline["throughput"] / NORTH_STAR, 3),
        ticks_per_sec=round(headline["ticks_per_sec"], 1),
    )
    if not run_all:
        payload.pop("configs", None)
    if platform == "tpu" and os.environ.get("MISAKA_FUSED_ELIDE_HI") != "1":
        # The hi-plane elision A/B rides the DEFAULT TPU run: the driver's
        # plain `python bench.py` may be the round's only hardware session,
        # and the r5 VPU-headroom cut needs a measured delta, not a flag
        # someone must remember (ARCHITECTURE.md "Headroom, named").
        try:
            os.environ["MISAKA_FUSED_ELIDE_HI"] = "1"
            el = bench_config("add2", batch=headline["batch"])
            payload["elide_hi_ticks_per_sec"] = round(el["ticks_per_sec"], 1)
            payload["elide_hi_speedup"] = round(
                el["ticks_per_sec"] / headline["ticks_per_sec"], 4
            )
            print(
                f"# elide-hi A/B: {el['ticks_per_sec']:.0f} vs "
                f"{headline['ticks_per_sec']:.0f} ticks/s "
                f"({payload['elide_hi_speedup']:.3f}x)",
                file=sys.stderr,
            )
        except Exception as e:  # pragma: no cover — A/B must not cost the run
            print(f"# elide-hi A/B failed: {e}", file=sys.stderr)
        finally:
            os.environ.pop("MISAKA_FUSED_ELIDE_HI", None)
    # Served throughput is part of the DEFAULT run: the north-star metric
    # must reach the driver's captured artifact through the product surface,
    # not live only behind a flag (VERDICT r2 weak #5).
    # Process-level warm-up first: the first serve cycle in a fresh process
    # pays one-time costs INSIDE its timed window (compile-cache writes
    # etc.) — measured 18k -> 91k/s across four identical calls, enough to
    # invert the raw-vs-text ranking by call order alone.  Skipped in
    # fallback mode, whose contract is a minimal reduced-TTL artifact.
    if not fallback:
        bench_served(mode="raw", waves=1)
    for mode, key in (("raw", "served_throughput"), ("text", "served_text_throughput")):
        served = bench_served(mode=mode, waves=2 if fallback else 6)
        print(
            f"# served[{mode}]: engine={served['engine']} batch={served['batch']} "
            f"threads={served['threads']} values={served['values']} "
            f"elapsed={served['elapsed_s']:.3f}s "
            f"throughput={served['throughput']:.0f}/s (through HTTP "
            f"{'/compute_raw' if mode == 'raw' else '/compute_batch'})",
            file=sys.stderr,
        )
        payload[key] = round(served["throughput"], 1)
        # each serve capture embeds its own /metrics before/after delta:
        # the artifact carries the telemetry that explains its numbers
        if served.get("metrics_delta"):
            payload.setdefault("served_metrics_delta", {})[mode] = served[
                "metrics_delta"
            ]
    payload["served_engine"] = served["engine"]

    if platform != "tpu":
        # a CPU serving number must be attributable: per-thread scaling of
        # the native tier proves the >=1M/s fallback rides the thread pool
        # (and where this host's ceiling is), not a measurement fluke
        try:
            from misaka_tpu.core import native_serve

            if native_serve.available():
                payload["native_scaling"] = bench_native_scaling()
                # the r16 lanes: SIMD mode table + binary-vs-text wire A/B
                payload["simd_scaling"] = bench_simd_scaling()
                payload["wire_ab"] = bench_wire_ab()
                # the r17 lane: per-call overhead, residency on/off A/B
                payload["call_overhead"] = bench_call_overhead(rounds=200)
        except Exception as e:  # pragma: no cover — must not cost the run
            print(f"# native scaling lane failed: {e}", file=sys.stderr)
        if not fallback:
            # the multi-tenant lane (r8): C keep-alive clients x 64-value
            # payloads through the serve scheduler — the workload the
            # single-client big-batch headline says nothing about
            try:
                payload["concurrency_sweep"] = bench_concurrency_sweep(
                    seconds=2.0
                )
            except Exception as e:  # pragma: no cover
                print(f"# concurrency sweep lane failed: {e}", file=sys.stderr)
            # the multi-PROGRAM lane (r11): the same 64 clients split
            # across three registry tenants on per-program engines
            try:
                payload["multi_tenant"] = bench_multi_tenant(seconds=2.0)
            except Exception as e:  # pragma: no cover
                print(f"# multi-tenant lane failed: {e}", file=sys.stderr)

    if fallback:
        print(json.dumps(payload))
        return

    # Latency, lane scaling, and the sharded engine are all part of the
    # DEFAULT run: the driver's plain `python bench.py` artifact must track
    # every engine every round (VERDICT r3 weak #3/#5 and items 3/5).
    lat = bench_latency(samples=100)
    print(
        f"# latency floor: p50={lat['p50_us']:.0f}us p99={lat['p99_us']:.0f}us "
        f"(single value, chunk={lat['chunk']}, n={lat['samples']})",
        file=sys.stderr,
    )
    payload["latency_us_p50"] = round(lat["p50_us"], 1)
    payload["latency_us_p99"] = round(lat["p99_us"], 1)
    hlat = bench_latency_http(samples=100, warmup=10)
    print(
        f"# latency HTTP: p50={hlat['p50_us']:.0f}us p99={hlat['p99_us']:.0f}us "
        f"(single value through POST /compute, n={hlat['samples']})",
        file=sys.stderr,
    )
    payload["http_latency_us_p50"] = round(hlat["p50_us"], 1)
    payload["http_latency_us_p99"] = round(hlat["p99_us"], 1)
    # The native (host C++) engine's latency tier: on a relayed chip the
    # device-dispatch floor dominates http_latency_us_*, and this lane is
    # the measured answer (zero dispatches on the request path).
    try:
        from misaka_tpu.core import native_serve

        if native_serve.available():
            nlat = bench_latency_http(samples=100, warmup=10, engine="native")
            print(
                f"# latency HTTP native engine: p50={nlat['p50_us']:.0f}us "
                f"p99={nlat['p99_us']:.0f}us (n={nlat['samples']})",
                file=sys.stderr,
            )
            payload["native_http_latency_us_p50"] = round(nlat["p50_us"], 1)
            payload["native_http_latency_us_p99"] = round(nlat["p99_us"], 1)
    except Exception as e:  # the latency tier must not cost the artifact
        print(f"# native latency lane failed: {e}", file=sys.stderr)

    # The sharded engine runs in a CPU subprocess (virtual mesh), so it is
    # immune to TPU wedges — keep it before the riskier lane matrix.
    sh = bench_sharded()
    print(
        f"# sharded: {sh['n_devices']}-device virtual mesh routed "
        f"ticks/s={sh['sharded_ticks_per_sec']:.0f} vs single "
        f"{sh['single_ticks_per_sec']:.0f} "
        f"(ratio {sh['sharded_vs_single']:.3f}; routed beats gather "
        f"{sh['routed_vs_gather']:.2f}x); mesh-served sustained "
        f"{sh['mesh_served_throughput']:.0f}/s vs single-served "
        f"{sh['single_served_throughput']:.0f}/s "
        f"({sh['mesh_served_vs_single']:.2f}x)",
        file=sys.stderr,
    )
    payload["sharded"] = sh

    if "--roofline" in sys.argv:
        payload["roofline"] = bench_roofline()

    # The routing-cliff matrix.  Dense stays in/near its small-N regime on
    # TPU (64-lane x full-batch dense wedged the r4 TPU worker; wide dense
    # numbers come from CPU runs); compact covers 64 and up (it is the
    # auto-selected wide-network kernel).  Each config is individually
    # fault-isolated so one bad compile can't blank the rest — and this
    # section runs LAST so a wedge costs only the lane numbers.
    # 16/32 x {dense, compact} bracket the dense->compact crossover so
    # COMPACT_AUTO_LANES is set from data, not interpolation (VERDICT r4
    # weak #2 / item 3).
    # "chained" is the scatter-free compact variant (core/routing.py
    # ChainTable): on CPU it measures ~0.7x compact (XLA CPU scatters are
    # fine), on TPU it is the A/B against the measured scatter
    # serialization ceiling — the decision data for flipping the wide-lane
    # TPU default.
    if platform == "tpu":
        # (1024, "compact") is NOT in the default matrix: it reproducibly
        # crashed the TPU worker in both r5 captures (error entries in
        # BENCH_tpu_r05*.json lane_scaling), and a crash kills every config
        # after it in-process — including, twice, the chained/fused A/Bs
        # that used to sit behind it.  The measured 1024-lane fault IS the
        # documented ceiling; re-crashing the shared worker every bench run
        # buys nothing.
        lane_matrix = [
            (8, "dense"), (16, "dense"), (32, "dense"),
            (16, "compact"), (32, "compact"), (64, "compact"),
            (256, "compact"),
            (64, "chained"), (256, "chained"), (64, "fused"),
        ]
    else:
        lane_matrix = [
            (8, "dense"), (16, "dense"), (32, "dense"), (64, "dense"),
            (256, "dense"),
            (16, "compact"), (32, "compact"), (64, "compact"),
            (256, "compact"), (64, "chained"), (256, "chained"),
        ]
    lanes = []
    # bind BEFORE the loop: a TTL dump mid-matrix then carries the configs
    # that already finished (the list mutates in place)
    payload["lane_scaling"] = lanes
    for n, engine in lane_matrix:
        try:
            r = bench_lanes(n, engine=engine)
        except Exception as e:  # pragma: no cover — keep the artifact alive
            print(f"# lanes={n} engine={engine} FAILED: {e}", file=sys.stderr)
            lanes.append({"lanes": n, "engine": engine, "error": str(e)[:200]})
            continue
        print(
            f"# lanes={n} engine={engine}: ticks/s={r['ticks_per_sec']:.0f} "
            f"throughput={r['throughput']:.0f}/s (batch={r['batch']}, "
            f"reps={r['reps']})",
            file=sys.stderr,
        )
        entry = {
            "lanes": n,
            "engine": engine,
            "batch": r["batch"],
            "reps": r["reps"],
            "ticks_per_sec": round(r["ticks_per_sec"], 1),
            "ticks_per_sec_median": round(r["ticks_per_sec_median"], 1),
            "throughput": round(r["throughput"], 1),
        }
        if "block_batch" in r:
            entry["block_batch"] = r["block_batch"]
        lanes.append(entry)
    print(json.dumps(payload))


if __name__ == "__main__":
    if "--sharded-worker" in sys.argv:
        i = sys.argv.index("--sharded-worker")
        _sharded_worker(*map(int, sys.argv[i + 1 : i + 4]))
    elif "--smoke" in sys.argv:
        bench_smoke()
    elif "--multi-tenant" in sys.argv:
        # standalone registry-lane capture (the r11 multi-program lane)
        import jax  # noqa: F401 — device selection before the lane

        print(json.dumps({
            "metric": "multi_tenant_throughput",
            **bench_multi_tenant(),
        }))
    elif "--sweep-fleet" in sys.argv:
        # client-fleet worker subprocess (no jax import on this path)
        i = sys.argv.index("--sweep-fleet")
        _sweep_fleet_main(sys.argv[i + 1 : i + 7])
    elif "--overload-fleet" in sys.argv:
        # overload-drill client worker subprocess (no jax import either)
        i = sys.argv.index("--overload-fleet")
        _overload_fleet_main(sys.argv[i + 1 : i + 10])
    elif "--obs-ab" in sys.argv:
        # Standalone observatory-overhead capture (the r15 twin of the
        # r12/r14 overhead artifacts): both served lanes, TSDB collector
        # + watchdog + canary at production cadence vs all shut down,
        # table embedded.  Committed as BENCH_cpu_r15.json.
        import jax

        ab = bench_obs_ab()
        payload = {
            "platform": jax.devices()[0].platform,
            "capture": "served-only (observatory-overhead check)",
            "served_throughput": ab["instrumented_raw"][-1],
            "served_conc64_throughput": ab["instrumented_conc64"][-1],
            "served_engine": "native",
            "observatory_overhead_ab": ab,
            # the gate reads the MEDIAN pair ratio (see ab["method"]:
            # the closed-loop conc lane's one-off scheduler collapses
            # swing a mean past the whole budget; per-pair arrays are
            # embedded for audit)
            "ok": bool(
                ab["raw_median_ratio"] >= 0.95
                and ab["conc64_median_ratio"] >= 0.95
            ),
        }
        print(json.dumps(payload))
        if not payload["ok"]:
            print(
                f"# observatory A/B FAILED the 0.95 median budget: raw "
                f"{ab['raw_median_ratio']} conc64 "
                f"{ab['conc64_median_ratio']}",
                file=sys.stderr,
            )
            sys.exit(1)
    elif "--durable-ab" in sys.argv:
        # Standalone durable-telemetry overhead capture (the r23 twin of
        # the r15 observatory artifact): both served lanes, the whole
        # MISAKA_TSDB_DIR plane (TSDB spool + usage ledger spool +
        # always-on capture) armed vs disarmed, median ABBA pair ratios
        # >= 0.95.  Committed as BENCH_cpu_r23.json.
        import jax

        ab = bench_durable_ab()
        payload = {
            "platform": jax.devices()[0].platform,
            "capture": "served-only (durable-telemetry overhead check)",
            "served_throughput": ab["durable_raw"][-1],
            "served_conc64_throughput": ab["durable_conc64"][-1],
            "served_engine": "native",
            "durable_overhead_ab": ab,
            "ok": bool(
                ab["raw_median_ratio"] >= 0.95
                and ab["conc64_median_ratio"] >= 0.95
            ),
        }
        print(json.dumps(payload))
        if not payload["ok"]:
            print(
                f"# durable A/B FAILED the 0.95 median budget: raw "
                f"{ab['raw_median_ratio']} conc64 "
                f"{ab['conc64_median_ratio']}",
                file=sys.stderr,
            )
            sys.exit(1)
    elif "--edge-ab" in sys.argv:
        # Standalone edge-overhead capture (the r14 twin of the r10/r12
        # overhead artifacts): both served lanes, the full middleware
        # chain armed vs disarmed, median ABBA pair ratios >= 0.95.
        import jax

        ab = bench_edge_ab()
        payload = {
            "platform": jax.devices()[0].platform,
            "capture": "served-only (edge-overhead check)",
            "served_engine": "native",
            "edge_overhead_ab": ab,
            "ok": bool(
                ab["raw_median_ratio"] >= 0.95
                and ab["conc64_median_ratio"] >= 0.95
            ),
        }
        print(json.dumps(payload))
        if not payload["ok"]:
            print(
                f"# edge overhead FAILED the 0.95 median budget: raw "
                f"{ab['raw_median_ratio']} conc64 "
                f"{ab['conc64_median_ratio']}",
                file=sys.stderr,
            )
            sys.exit(1)
    elif "--simd" in sys.argv:
        # Standalone SIMD + zero-copy-wire capture (the r16 lanes):
        # per-thread scaling of the struct-of-arrays group engine, the
        # same-harness mode table (scalar / generic / avx2 /
        # specialized), the binary-vs-text 64-client wire A/B, and the
        # pool headline gated against the ISSUE 12 acceptance floor
        # (>= 2.5x the committed r13-era ~11.4M scalar baseline).
        # Committed as BENCH_cpu_r16.json.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        payload = {"metric": "simd_wire"}
        # headline FIRST: the later lanes' pools/servers leave allocator +
        # scheduler state behind that measurably dents a same-process rerun
        pool = bench_native_pool(rounds=6)
        payload["pool_throughput"] = round(pool["throughput"], 1)
        payload["pool_simd"] = pool["simd"]
        payload["pool_threads"] = pool["threads"]
        payload["simd_scaling"] = bench_simd_scaling()
        payload["wire_ab"] = bench_wire_ab()
        payload["acceptance_floor"] = 2.5 * 11_400_000.0
        payload["ok"] = bool(
            payload["pool_throughput"] >= payload["acceptance_floor"]
        )
        print(json.dumps(payload))
        if not payload["ok"]:
            print(
                f"# SIMD capture FAILED the 2.5x floor: "
                f"{payload['pool_throughput']:.0f}/s < "
                f"{payload['acceptance_floor']:.0f}/s",
                file=sys.stderr,
            )
            sys.exit(1)
    elif "--resident" in sys.argv:
        # Standalone r17 capture: the per-call overhead lane (serve-call
        # wall at B in {1, 64, 256, 4096}, residency on/off A/B), the
        # pool-level headline re-measured on the resident/futex engine
        # (must hold the committed r16 floor), and the 64-client
        # pipelined-plane sweep.  Committed as BENCH_cpu_r17.json;
        # bench-smoke gates the resident B=256 call rate at 50%.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        payload = {"metric": "resident_serving"}
        payload["cpus"] = os.cpu_count()
        # headline FIRST (same-process lane ordering discipline as --simd):
        # the saturated pool lane, residency ON vs OFF on THIS box — the
        # cross-box comparison against the committed r16 capture is
        # recorded for context but never gated (this container's core
        # count differs from the r16 box's; BENCH_HISTORY r17)
        prev = os.environ.get("MISAKA_NATIVE_RESIDENT")
        os.environ["MISAKA_NATIVE_RESIDENT"] = "0"
        try:
            pool_off = bench_native_pool(rounds=4)
        finally:
            if prev is None:
                os.environ.pop("MISAKA_NATIVE_RESIDENT", None)
            else:
                os.environ["MISAKA_NATIVE_RESIDENT"] = prev
        pool = bench_native_pool(rounds=4)
        payload["pool_throughput"] = round(pool["throughput"], 1)
        payload["pool_throughput_stateless"] = round(
            pool_off["throughput"], 1
        )
        payload["pool_simd"] = pool["simd"]
        payload["pool_threads"] = pool["threads"]
        payload["pool_r16_capture"] = R16_SIMD_POOL
        payload["call_overhead"] = bench_call_overhead()
        try:
            payload["concurrency_sweep"] = bench_concurrency_sweep(
                clients=(64,), seconds=2.0, engine="native",
                http_workers=6, fleet_procs=4,
            )
        except Exception as e:  # pragma: no cover
            payload["concurrency_sweep_error"] = str(e)[:200]
        co256 = payload["call_overhead"]["256"]
        payload["acceptance"] = {
            "speedup_256": co256["speedup"],
            "speedup_floor": 2.0,
            # same-box, same-harness: the resident engine must HOLD the
            # stateless engine's saturated-pool rate (identity-trusted in
            # both modes; 0.8 absorbs this box's run-to-run spread)
            "pool_ab_ratio": round(
                payload["pool_throughput"]
                / max(1.0, payload["pool_throughput_stateless"]), 3
            ),
            "pool_ab_floor": 0.8,
        }
        payload["ok"] = bool(
            co256["speedup"] >= 2.0
            and payload["acceptance"]["pool_ab_ratio"] >= 0.8
        )
        print(json.dumps(payload))
        if not payload["ok"]:
            print(
                f"# resident capture FAILED: B=256 speedup "
                f"{co256['speedup']}x (floor 2.0x), pool A/B "
                f"{payload['acceptance']['pool_ab_ratio']} (floor 0.8)",
                file=sys.stderr,
            )
            sys.exit(1)
    elif "--elision" in sys.argv:
        # Standalone r21 capture: the copy-and-patch JIT rung vs the
        # switch-threaded tick one rung down (full-fill ABBA at B in
        # {256, 4096}), the pack-row elision sweep (1-hot resident sparse
        # fill, B in {64, 1024, 4096, 16384}), and the kill-switch
        # overhead A/B (MISAKA_JIT=0 + MISAKA_PACK_ELIDE=0 vs defaults).
        # Committed as BENCH_cpu_r21.json; bench-smoke gates the JIT
        # B=256 rate and the armed B=4096 call rate at 50%.
        #
        # BOX NOTE (r21, same discipline as r17): this container has ONE
        # core, so every absolute rate here is core-starved; the
        # acceptance reads the same-harness ABBA ratios, which are
        # portable.  The elision speedup is read at the sweep's large
        # end: the elidable pack term is B-proportional while the
        # per-call floor (Python dispatch + feed + masked group ticks,
        # ~flat tens of us — tick-count-independent, measured at ticks
        # 16/32/64) is not, so the ratio grows monotonically with B and
        # the >= 2x criterion lands where the pack pass dominates
        # (B=16384 here), with B=4096 gated at >= 1.5x.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        payload = {"metric": "jit_elision"}
        payload["cpus"] = os.cpu_count()
        # headline FIRST (same-process lane ordering discipline): the
        # JIT A/B runs before the elision pools touch the allocator
        payload["jit_ab"] = bench_jit_ab()
        payload["elision"] = bench_elision_sweep()
        payload["kill_switch_overhead"] = bench_r21_overhead()
        jr = {b: e["median_ratio"] for b, e in payload["jit_ab"].items()}
        er = {b: e["median_speedup"]
              for b, e in payload["elision"].items()}
        payload["acceptance"] = {
            "jit_ratios": jr,
            "jit_floor": 1.15,
            "elision_speedups": er,
            "elision_floor_4096": 1.5,
            "elision_floor_asymptote": 2.0,
            "overhead_ratio": payload["kill_switch_overhead"][
                "median_ratio"],
            "overhead_floor": 0.95,
        }
        payload["ok"] = bool(
            all(r >= 1.15 for r in jr.values())
            and er["4096"] >= 1.5
            and er["16384"] >= 2.0
            and payload["acceptance"]["overhead_ratio"] >= 0.95
        )
        print(json.dumps(payload))
        if not payload["ok"]:
            print(
                f"# r21 capture FAILED: jit {jr} (floor 1.15x), "
                f"elision {er} (floors 1.5x @4096 / 2x @16384), "
                f"kill-switch overhead "
                f"{payload['acceptance']['overhead_ratio']} (floor 0.95)",
                file=sys.stderr,
            )
            sys.exit(1)
    elif "--overload" in sys.argv:
        # Standalone overload-drill capture (the r14 lane): offered load
        # >= 4x capacity across two tenants, shed at the door by the
        # production edge (runtime/edge.py).  Committed as
        # BENCH_cpu_r14.json; bench-smoke gates goodput at 50%.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        payload = {"metric": "overload_drill", **bench_overload()}
        print(json.dumps(payload))
        if not payload["ok"]:
            print("# overload drill FAILED its contract (see fields)",
                  file=sys.stderr)
            sys.exit(1)
    elif "--fleet" in sys.argv:
        # Standalone horizontal scale-out capture (the r13 lane): real
        # MISAKA_FLEET subprocess fleets, 1→4 engine replicas behind
        # the shared frontend tier, 64 keep-alive clients — plus the
        # single-engine IN-HARNESS baseline (one CPython HTTP process,
        # no frontend plane: the r8 wall the fleet exists to break),
        # measured in the same run so the ratio compares one host at
        # one moment.  NOTE the headline ratio deliberately spans both
        # the topology AND the client-harness change (subprocess client
        # fleet vs in-process threads — the criterion's stated
        # baseline); per-replica scaling alone is each lane's
        # speedup_vs_1 (see BENCH_HISTORY r13).  Committed as
        # BENCH_cpu_r13.json.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        payload = {"metric": "fleet_scaling"}
        baseline = bench_concurrency_sweep(
            clients=(64,), seconds=2.0, engine="native",
            http_workers=0, fleet_procs=1,
        )["lanes"][0]
        payload["single_engine_inharness_64"] = baseline
        payload["fleet_scaling"] = bench_fleet_scaling()
        top = payload["fleet_scaling"]["lanes"][-1]
        payload["speedup_vs_single_engine"] = round(
            top["throughput"] / baseline["throughput"], 2
        )
        payload["ok"] = bool(payload["speedup_vs_single_engine"] >= 2.5)
        print(json.dumps(payload))
        if not payload["ok"]:
            print(
                f"# fleet scaling FAILED the 2.5x budget: "
                f"{top['throughput']:.0f}/s at N={top['replicas']} vs "
                f"{baseline['throughput']:.0f}/s single-engine "
                f"({payload['speedup_vs_single_engine']}x)",
                file=sys.stderr,
            )
            sys.exit(1)
    elif "--dist" in sys.argv:
        # Standalone r22 multi-host capture: the plane-transport A/B
        # (unix vs TCP vs TCP+mTLS — what leaving the host costs the
        # MSK1 frame path) and the FleetPlaneRouter failover window
        # (one of two planes closed abruptly mid-load; the max client-
        # observed latency IS the window, the error count must be 0).
        # Committed as BENCH_cpu_r22.json; bench-smoke gates the mTLS
        # transport lane at 50% of the committed capture.
        import jax

        ab = bench_dist_ab()
        payload = {
            "platform": jax.devices()[0].platform,
            "capture": "served-only (plane transport + failover window)",
            "served_engine": "native",
            "cores": os.cpu_count(),
            "dist_ab": ab,
            "ok": bool(
                ab["failover"].get("errors") == 0
                # the TLS record layer on loopback must not halve the
                # plane (measured ~0.9x; a protocol regression —
                # per-frame rehandshake, lost pipelining — trips this)
                and ab.get("mtls_vs_tcp", 1.0) >= 0.5
            ),
        }
        print(json.dumps(payload))
        if not payload["ok"]:
            print(
                f"# dist A/B FAILED: failover errors "
                f"{ab['failover'].get('errors')} (want 0), mtls_vs_tcp "
                f"{ab.get('mtls_vs_tcp')} (want >= 0.5)",
                file=sys.stderr,
            )
            sys.exit(1)
    elif "--trace-ab" in sys.argv:
        # Standalone tracing-overhead capture (the r10 twin of the r07
        # metrics-overhead artifact): both served lanes, tracing on vs
        # the MISAKA_TRACE_REQUESTS=0 kill switch, table embedded.
        import jax

        ab = bench_tracing_ab()
        payload = {
            "platform": jax.devices()[0].platform,
            "capture": "served-only (tracing-overhead check)",
            "served_throughput": ab["instrumented_raw"][-1],
            "served_conc64_throughput": ab["instrumented_conc64"][-1],
            "served_engine": "native",
            "tracing_overhead_ab": ab,
            "ok": bool(
                ab["raw_mean_ratio"] >= 0.95
                and ab["conc64_mean_ratio"] >= 0.95
            ),
        }
        print(json.dumps(payload))
        if not payload["ok"]:
            print(
                f"# tracing A/B FAILED the 0.95 budget: raw "
                f"{ab['raw_mean_ratio']} conc64 {ab['conc64_mean_ratio']}",
                file=sys.stderr,
            )
            sys.exit(1)
    elif "--capture-ab" in sys.argv:
        # Standalone traffic-capture overhead capture (the r20 twin of
        # the r10/r18 A/Bs): both served lanes, recorder armed at
        # sample=1.0 vs idle, plus the MISAKA_CAPTURE=0 kill-switch
        # no-op check.  Committed as BENCH_cpu_r20.json.
        import jax

        ab = bench_capture_ab()
        payload = {
            "platform": jax.devices()[0].platform,
            "capture": "served-only (traffic-capture overhead)",
            "served_throughput": ab["instrumented_raw"][-1],
            "served_conc64_throughput": ab["instrumented_conc64"][-1],
            "served_engine": "native",
            "capture_overhead_ab": ab,
            # MEDIAN pair ratio (see ab["method"]): scheduler-lottery
            # collapses on a saturated box swing a mean past the budget
            "ok": bool(
                ab["raw_median_ratio"] >= 0.95
                and ab["conc64_median_ratio"] >= 0.95
            ),
        }
        print(json.dumps(payload))
        if not payload["ok"]:
            print(
                f"# capture A/B FAILED the 0.95 budget: raw "
                f"{ab['raw_median_ratio']} conc64 "
                f"{ab['conc64_median_ratio']} (medians)",
                file=sys.stderr,
            )
            sys.exit(1)
    elif "--model" in sys.argv:
        # Capture-fitted load-model lane: open-loop Poisson replay of a
        # model JSON emitted by `misaka_tpu replay --emit-model` (or
        # capture.fit_load_model) — yesterday's production traffic as
        # today's regression harness.
        i = sys.argv.index("--model")
        result = bench_model_replay(sys.argv[i + 1])
        print(json.dumps(result))
    elif "--native-trace-ab" in sys.argv:
        # Standalone native-flight-recorder overhead capture (the r18
        # twin of the r10/r12/r15 A/Bs): the served raw lane AND the r17
        # B=256 call-overhead lane, recorder armed vs disarmed on one
        # shared stack, table embedded.  Committed as BENCH_cpu_r18.json.
        import jax

        ab = bench_native_trace_ab()
        payload = {
            "platform": jax.devices()[0].platform,
            "capture": "served-only (native flight-recorder overhead)",
            "served_throughput": ab["instrumented_raw"][-1],
            "call256_calls_per_s": ab["instrumented_call256"][-1],
            "served_engine": "native",
            "native_trace_overhead_ab": ab,
            # MEDIAN pair ratio (see ab["method"]): scheduler-lottery
            # collapses on a saturated box swing a mean past the budget
            "ok": bool(
                ab["raw_median_ratio"] >= 0.95
                and ab["call256_median_ratio"] >= 0.95
            ),
        }
        print(json.dumps(payload))
        if not payload["ok"]:
            print(
                f"# native-trace A/B FAILED the 0.95 budget: raw "
                f"{ab['raw_median_ratio']} call256 "
                f"{ab['call256_median_ratio']} (medians)",
                file=sys.stderr,
            )
            sys.exit(1)
    elif "--edge-native" in sys.argv:
        # Standalone r19 capture: the C++ native edge vs the CPython
        # worker tier on one shared engine + plane (ABBA, per-pair
        # arrays, p50/p99).  Committed as BENCH_cpu_r19.json.  The >=3x
        # acceptance gate arms only on a box comparable to the r08-r16
        # capture box (>= CAPTURE_BOX_CPUS/2 cores): core-starved
        # containers run both tiers through the same scheduler lottery
        # and the ratio stops measuring the edge — the honest numbers
        # are still captured and committed.
        import jax

        ab = bench_edge_native_ab()
        gate_armed = (ab["cores"] or 1) >= CAPTURE_BOX_CPUS // 2
        payload = {
            "platform": jax.devices()[0].platform,
            "capture": "served-only (native C++ edge vs CPython workers)",
            "served_engine": "native",
            "edge_native_ab": ab,
            "speedup_gate_armed": gate_armed,
            "ok": bool(ab["speedup"] >= 3.0) if gate_armed else True,
        }
        if not gate_armed:
            payload["speedup_gate_skipped"] = (
                f"{ab['cores']} core(s) < {CAPTURE_BOX_CPUS // 2}: the "
                f">=3x acceptance gate needs a box where the tiers are "
                f"not core-starved together"
            )
            print(
                f"# edge-native A/B: >=3x gate SKIPPED cross-box "
                f"({ab['cores']} core(s)); measured native "
                f"{ab['native_req_s_median']:.0f} req/s vs worker "
                f"{ab['worker_req_s_median']:.0f} req/s "
                f"(speedup {ab['speedup']})",
                file=sys.stderr,
            )
        print(json.dumps(payload))
        if not payload["ok"]:
            print(
                f"# edge-native A/B FAILED the 3x acceptance: native "
                f"{ab['native_req_s_median']:.0f} req/s vs worker "
                f"{ab['worker_req_s_median']:.0f} req/s "
                f"(speedup {ab['speedup']})",
                file=sys.stderr,
            )
            sys.exit(1)
    elif "--usage-ab" in sys.argv:
        # Standalone observability-plane overhead capture (the r12 twin
        # of the r10 tracing A/B): both served lanes, usage accounting +
        # SLO windows + stack sampler all on vs all killed, table
        # embedded.  Committed as BENCH_cpu_r12.json.
        import jax

        ab = bench_usage_ab()
        payload = {
            "platform": jax.devices()[0].platform,
            "capture": "served-only (usage/slo/sampler overhead check)",
            "served_throughput": ab["instrumented_raw"][-1],
            "served_conc64_throughput": ab["instrumented_conc64"][-1],
            "served_engine": "native",
            "usage_overhead_ab": ab,
            # the gate reads the MEDIAN pair ratio (see ab["method"]:
            # the closed-loop conc lane's one-off scheduler collapses,
            # observed in both directions, swing a mean past the whole
            # budget; the per-pair arrays are embedded for audit)
            "ok": bool(
                ab["raw_median_ratio"] >= 0.95
                and ab["conc64_median_ratio"] >= 0.95
            ),
        }
        print(json.dumps(payload))
        if not payload["ok"]:
            print(
                f"# usage A/B FAILED the 0.95 budget: raw "
                f"{ab['raw_median_ratio']} conc64 "
                f"{ab['conc64_median_ratio']} (medians)",
                file=sys.stderr,
            )
            sys.exit(1)
    elif "--sweep" in sys.argv:
        # Standalone concurrency-sweep capture: the in-process-fleet lane
        # (the committed-baseline harness, A/B-comparable across rounds)
        # plus the multi-process serving-plane lane (subprocess fleets +
        # SO_REUSEPORT frontends — the r8 architecture's number).
        payload = {"concurrency_sweep": bench_concurrency_sweep()}
        try:
            payload["concurrency_sweep_frontends"] = bench_concurrency_sweep(
                http_workers=int(
                    os.environ.get("MISAKA_SWEEP_WORKERS", "") or 6
                ),
                fleet_procs=4,
            )
        except Exception as e:  # pragma: no cover — keep the artifact alive
            print(f"# frontend sweep lane failed: {e}", file=sys.stderr)
        print(json.dumps(payload))
    else:
        main()
