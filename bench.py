"""Benchmark harness: add-2 /compute throughput on the current JAX platform.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "inputs/sec", "vs_baseline": N}

The metric is BASELINE.json's headline: values computed per second through
the docker-compose "add-2" network with output parity against the Go
interpreter.  The reference publishes no numbers (BASELINE.md); vs_baseline
is measured against the driver's north-star target of 1e6 inputs/sec.

Method: B independent network instances run in lockstep (vmap batch axis);
each instance's input ring is preloaded with Q values, and we time jitted
scan chunks until every instance has emitted all Q outputs.  Outputs are
verified (v+2) before the number is reported — a fast-but-wrong kernel
prints nothing.
"""

import json
import sys
import time

import numpy as np

NORTH_STAR = 1_000_000.0  # BASELINE.json north_star target, inputs/sec


def bench_add2(batch=32768, per_instance=128, ticks=1792, block_batch=2048):
    """Fused-kernel benchmark: one launch drains Q values per instance.

    The add-2 pipeline retires one value per ~12 ticks per instance, so
    `ticks` is sized to drain `per_instance` values with slack; completion
    and parity are asserted, so an undersized/incorrect run fails loudly.
    """
    import jax
    import jax.numpy as jnp

    from misaka_tpu import networks

    top = networks.add2(in_cap=per_instance, out_cap=per_instance, stack_cap=16)
    net = top.compile(batch=batch)

    rng = np.random.default_rng(0)
    vals = rng.integers(-1000, 1000, size=(batch, per_instance)).astype(np.int32)

    def fresh_state():
        state = net.init_state()
        return state._replace(
            in_buf=jnp.asarray(vals),
            in_wr=state.in_wr + np.int32(per_instance),
        )

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        runner = net.fused_runner(ticks, block_batch=block_batch)
    else:
        runner = lambda s: net.run(s, ticks)

    # Warm-up compile; sync via a real transfer (block_until_ready does not
    # wait under the axon relay).
    s = runner(fresh_state())
    _ = int(np.asarray(s.tick)[0])

    state = fresh_state()
    _ = int(np.asarray(state.tick)[0])
    total = batch * per_instance
    t0 = time.perf_counter()
    state = runner(state)
    done = int(np.asarray(state.out_wr).min())  # sync point
    elapsed = time.perf_counter() - t0

    out = np.asarray(state.out_buf)
    if done < per_instance or not (np.asarray(state.out_wr) == per_instance).all():
        raise RuntimeError(f"benchmark did not complete: min out_wr {done}/{per_instance}")
    if not (out == vals + 2).all():
        raise RuntimeError("output parity FAILED: results are not input+2")

    return {
        "throughput": total / elapsed,
        "elapsed_s": elapsed,
        "ticks": int(np.asarray(state.tick)[0]),
        "values": total,
        "ticks_per_value": ticks * batch / total,
        "batch": batch,
        "per_instance": per_instance,
    }


def main():
    import jax

    platform = jax.devices()[0].platform
    r = bench_add2()
    print(
        f"# platform={platform} batch={r['batch']} q={r['per_instance']} "
        f"values={r['values']} elapsed={r['elapsed_s']:.3f}s ticks={r['ticks']} "
        f"ticks/value={r['ticks_per_value']:.2f}",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "add2_compute_throughput",
                "value": round(r["throughput"], 1),
                "unit": "inputs/sec",
                "vs_baseline": round(r["throughput"] / NORTH_STAR, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
