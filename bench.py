"""Benchmark harness: /compute throughput on the current JAX platform.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "inputs/sec", "vs_baseline": N}

The metric is BASELINE.json's headline: values computed per second through
the docker-compose "add-2" network with output parity against the Go
interpreter.  The reference publishes no numbers (BASELINE.md); vs_baseline
is measured against the driver's north-star target of 1e6 inputs/sec.

`python bench.py --all` additionally measures every BASELINE config
(add2, acc_loop, ring4, sorter, mesh8) and reports them in a "configs"
field; the headline metric stays add2.  `--latency` appends single-value
end-to-end latency (latency_us_p50 / latency_us_p99 fields) measured
through the minimal-sync serving path.

Method: B independent network instances run in lockstep (vmap batch axis);
each instance's input ring is preloaded with Q values, and we time jitted
scan chunks until every instance has emitted all Q outputs.  Outputs are
verified against the config's expected function before the number is
reported — a fast-but-wrong kernel prints nothing.
"""

import json
import sys
import time

import numpy as np

NORTH_STAR = 1_000_000.0  # BASELINE.json north_star target, inputs/sec


def _expect_sorter(v):
    return np.where(v > 0, 11, np.where(v < 0, -11, 0)).astype(np.int32)


# Per-config oracle + tick budget per retired value (generous; completion is
# asserted, and an undersized budget retries with double the ticks).
CONFIGS = {
    "add2": dict(expect=lambda v: v + 2, ticks_per_value=14, ordered=True),
    "acc_loop": dict(expect=lambda v: v + 3, ticks_per_value=10, ordered=True),
    "ring4": dict(expect=lambda v: v + 4, ticks_per_value=20, ordered=True),
    "sorter": dict(expect=_expect_sorter, ticks_per_value=10, ordered=True),
    # mesh8's two pipelines race for IN, so per-instance output ORDER is
    # arbitration-dependent; parity is a multiset check.
    "mesh8": dict(expect=lambda v: v + 4, ticks_per_value=12, ordered=False),
}


def bench_config(
    name, batch=262144, per_instance=128, block_batch=2048, max_attempts=3
):
    """Measure one BASELINE config: B instances drain Q values each.

    Uses the fused Pallas kernel on TPU (one launch for the whole run), the
    XLA scan engine elsewhere.  Completion and parity are asserted.
    """
    import jax
    import jax.numpy as jnp

    from misaka_tpu import networks

    cfg = CONFIGS[name]
    top = networks.BASELINE_CONFIGS[name](
        in_cap=per_instance, out_cap=per_instance, stack_cap=16
    )
    net = top.compile(batch=batch)

    rng = np.random.default_rng(0)
    vals = rng.integers(-1000, 1000, size=(batch, per_instance)).astype(np.int32)
    if name == "sorter":  # make sure the JEZ branch is exercised too
        vals[:, ::17] = 0
    expected = cfg["expect"](vals)

    def fresh_state():
        state = net.init_state()
        return state._replace(
            in_buf=jnp.asarray(vals),
            in_wr=state.in_wr + np.int32(per_instance),
        )

    on_tpu = jax.devices()[0].platform == "tpu"
    ticks = cfg["ticks_per_value"] * per_instance + 256
    for attempt in range(max_attempts):
        if on_tpu:
            runner = net.fused_runner(ticks, block_batch=block_batch)
        else:
            runner = lambda s: net.run(s, ticks)

        # Warm-up compile; sync via a real transfer (block_until_ready does
        # not wait under the axon relay).
        s = runner(fresh_state())
        _ = int(np.asarray(s.tick)[0])

        state = fresh_state()
        _ = int(np.asarray(state.tick)[0])
        total = batch * per_instance
        t0 = time.perf_counter()
        state = runner(state)
        done = int(np.asarray(state.out_wr).min())  # sync point
        elapsed = time.perf_counter() - t0

        if done >= per_instance and (np.asarray(state.out_wr) == per_instance).all():
            break
        ticks *= 2  # undersized budget: double and retry
    else:
        raise RuntimeError(
            f"{name}: benchmark did not complete: min out_wr {done}/{per_instance}"
        )

    out = np.asarray(state.out_buf)
    if cfg["ordered"]:
        ok = (out == expected).all()
    else:
        ok = (np.sort(out, axis=1) == np.sort(expected, axis=1)).all()
    if not ok:
        raise RuntimeError(f"{name}: output parity FAILED")

    return {
        "name": name,
        "throughput": total / elapsed,
        "elapsed_s": elapsed,
        "ticks": int(np.asarray(state.tick)[0]),
        "values": total,
        "ticks_per_value": ticks * batch / total,
        "batch": batch,
        "per_instance": per_instance,
    }


def bench_add2(batch=262144, per_instance=128, block_batch=2048):
    """The headline metric (kept as an alias for external callers)."""
    return bench_config("add2", batch, per_instance, block_batch)


def bench_latency(samples=200, chunk=16, warmup=20):
    """Single-value end-to-end latency through the engine (unbatched add2).

    Uses the minimal-sync serving shape: enqueue + `chunk` supersteps +
    drain fused into ONE jitted call, so a request costs one dispatch and
    one scalar readback — the per-request latency floor (the HTTP master
    adds queue hops on top).  Returns p50/p99 in microseconds.  Note: on a
    relayed/remote device this mostly measures the host<->device link.
    """
    import jax
    import numpy as np

    from misaka_tpu import networks
    from misaka_tpu.core.step import step

    net = networks.add2(in_cap=16, out_cap=16, stack_cap=16).compile()
    code, prog_len = net._tables

    import functools

    @functools.partial(jax.jit, donate_argnums=(0,))
    def compute_one(state, v):
        in_cap = state.in_buf.shape[0]
        out_cap = state.out_buf.shape[0]
        state = state._replace(
            in_buf=state.in_buf.at[state.in_wr % in_cap].set(v),
            in_wr=state.in_wr + 1,
        )

        def body(s, _):
            return step(code, prog_len, s), None

        state, _ = jax.lax.scan(body, state, None, length=chunk)
        out_val = state.out_buf[(state.out_wr - 1) % out_cap]
        done = state.out_wr - state.out_rd  # 1 iff the value retired in-chunk
        return state._replace(out_rd=state.out_wr), out_val, done

    state = net.init_state()

    def one(state, v):
        t0 = time.perf_counter()
        state, out, done = compute_one(state, v)
        out = int(out)  # the single host sync
        dt = time.perf_counter() - t0
        assert int(done) == 1 and out == v + 2, (out, int(done))
        return state, dt

    for i in range(warmup):
        state, _ = one(state, i)
    times = []
    for i in range(samples):
        state, dt = one(state, i)
        times.append(dt)
    us = np.asarray(times) * 1e6
    return {
        "p50_us": float(np.percentile(us, 50)),
        "p99_us": float(np.percentile(us, 99)),
        "samples": samples,
        "chunk": chunk,
    }


def main():
    import jax

    run_all = "--all" in sys.argv
    platform = jax.devices()[0].platform

    results = {}
    for name in CONFIGS if run_all else ["add2"]:
        r = bench_config(name)
        results[name] = r
        print(
            f"# {name}: platform={platform} batch={r['batch']} "
            f"q={r['per_instance']} values={r['values']} "
            f"elapsed={r['elapsed_s']:.3f}s ticks={r['ticks']} "
            f"ticks/value={r['ticks_per_value']:.2f} "
            f"throughput={r['throughput']:.0f}/s",
            file=sys.stderr,
        )

    headline = results["add2"]
    payload = {
        "metric": "add2_compute_throughput",
        "value": round(headline["throughput"], 1),
        "unit": "inputs/sec",
        "vs_baseline": round(headline["throughput"] / NORTH_STAR, 3),
    }
    if run_all:
        payload["configs"] = {
            name: round(r["throughput"], 1) for name, r in results.items()
        }
    if "--latency" in sys.argv:
        lat = bench_latency()
        print(
            f"# latency: p50={lat['p50_us']:.0f}us p99={lat['p99_us']:.0f}us "
            f"(single value, chunk={lat['chunk']}, n={lat['samples']})",
            file=sys.stderr,
        )
        payload["latency_us_p50"] = round(lat["p50_us"], 1)
        payload["latency_us_p99"] = round(lat["p99_us"], 1)
    print(json.dumps(payload))


if __name__ == "__main__":
    main()
