# Build targets mirroring the reference's Makefile surface (build/grpc/cert,
# /root/reference/Makefile:1-12) plus the native components and local QA.

CXX ?= g++
# Warnings are load-bearing: the default build is -Werror so a warning
# REGRESSION fails `make native` (and `make ci` through it) instead of
# scrolling past.  utils/nativelib.py's on-demand rebuild keeps plain
# flags — a stricter future compiler must not brick runtime rebuilds.
WARNFLAGS ?= -Wall -Wextra -Werror
# -fopenmp-simd: honor the interpreter's `#pragma omp simd` loop
# annotations (pure compiler directive — no OpenMP runtime is linked).
CXXFLAGS ?= -O2 -std=c++17 -shared -fPIC -pthread -fopenmp-simd $(WARNFLAGS)

native: native/libmisaka_assembler.so native/libmisaka_interp.so native/libmisaka_textcodec.so native/libmisaka_frontend.so

# -DMISAKA_SRC_HASH must match utils/nativelib.py's _build (sha256[:16] of
# the source): the loader trusts a .so only when its embedded tag matches
# the source hash, so an untagged build would always be treated as stale.
native/libmisaka_assembler.so: native/assembler.cpp
	$(CXX) $(CXXFLAGS) -DMISAKA_SRC_HASH="\"$$(sha256sum $< | cut -c1-16)\"" $< -o $@

native/libmisaka_interp.so: native/interpreter.cpp
	$(CXX) $(CXXFLAGS) -DMISAKA_SRC_HASH="\"$$(sha256sum $< | cut -c1-16)\"" $< -o $@

native/libmisaka_textcodec.so: native/textcodec.cpp
	$(CXX) $(CXXFLAGS) -DMISAKA_SRC_HASH="\"$$(sha256sum $< | cut -c1-16)\"" $< -o $@

# The native edge builds from THREE units (frontend.cpp + the msk_http/
# msk_frame headers it includes); the identity hash covers their
# CONCATENATION in this exact order — runtime/frontends.py's
# _FrontendNativeLib._src_hash computes the same digest, so prebuilt and
# on-demand artifacts agree on staleness.
FRONTEND_UNITS = native/msk_http.hpp native/msk_frame.hpp native/frontend.cpp
native/libmisaka_frontend.so: $(FRONTEND_UNITS)
	$(CXX) $(CXXFLAGS) -DMISAKA_SRC_HASH="\"$$(cat $(FRONTEND_UNITS) | sha256sum | cut -c1-16)\"" native/frontend.cpp -o $@

# Sanitizer build lanes for the serving interpreter (the one native
# component with worker threads + shared state).  These artifacts are
# local-only (gitignored, never shipped): tools/sanitize_stress.py loads
# them via the MISAKA_INTERP_SO override and runs the concurrent
# serve/close/counter-read scenario — the PR 7 TOCTOU-UAF shape — under
# each instrument.  docs/STATIC_ANALYSIS.md "Sanitizer lanes".
SAN_CXXFLAGS = -O1 -g -fno-omit-frame-pointer -std=c++17 -shared -fPIC \
	-pthread -fopenmp-simd $(WARNFLAGS)

native-asan: native/libmisaka_interp.asan.so native/libmisaka_frontend.asan.so
native/libmisaka_interp.asan.so: native/interpreter.cpp
	$(CXX) $(SAN_CXXFLAGS) -fsanitize=address $< -o $@

native-tsan: native/libmisaka_interp.tsan.so native/libmisaka_frontend.tsan.so
native/libmisaka_interp.tsan.so: native/interpreter.cpp
	$(CXX) $(SAN_CXXFLAGS) -fsanitize=thread $< -o $@

native-ubsan: native/libmisaka_interp.ubsan.so native/libmisaka_frontend.ubsan.so
native/libmisaka_interp.ubsan.so: native/interpreter.cpp
	$(CXX) $(SAN_CXXFLAGS) -fsanitize=undefined -fno-sanitize-recover=all \
		$< -o $@

native/libmisaka_frontend.asan.so: $(FRONTEND_UNITS)
	$(CXX) $(SAN_CXXFLAGS) -fsanitize=address native/frontend.cpp -o $@

native/libmisaka_frontend.tsan.so: $(FRONTEND_UNITS)
	$(CXX) $(SAN_CXXFLAGS) -fsanitize=thread native/frontend.cpp -o $@

native/libmisaka_frontend.ubsan.so: $(FRONTEND_UNITS)
	$(CXX) $(SAN_CXXFLAGS) -fsanitize=undefined -fno-sanitize-recover=all \
		native/frontend.cpp -o $@

# Short ASan lanes (~30s): the CI tripwire for native memory bugs —
# the interpreter pool scenario, the r19 edge lane (instrumented
# frontend.cpp under keep-alive hammering, mid-flight kills and
# supervisor restart cycles), and the r21 jit lane (copy-and-patch
# splice/patch/W^X churn racing arm/disarm/eviction on a hot pool).
sanitize-smoke: native-asan
	JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= timeout -k 10 300 \
		python tools/sanitize_stress.py --sanitizer address --seconds 6
	JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= timeout -k 10 300 \
		python tools/sanitize_stress.py --sanitizer address --lane edge \
		--seconds 6
	JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= timeout -k 10 300 \
		python tools/sanitize_stress.py --sanitizer address --lane jit \
		--seconds 6

# All three instruments, longer scenario (~2min) — the pre-merge lane
# for native/*.cpp changes; each instrument runs both lanes.
sanitize-all: native-asan native-tsan native-ubsan
	JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= timeout -k 10 300 \
		python tools/sanitize_stress.py --sanitizer address --seconds 15
	JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= timeout -k 10 300 \
		python tools/sanitize_stress.py --sanitizer address --lane edge \
		--seconds 15
	JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= timeout -k 10 300 \
		python tools/sanitize_stress.py --sanitizer address --lane jit \
		--seconds 15
	JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= timeout -k 10 300 \
		python tools/sanitize_stress.py --sanitizer thread --seconds 15
	JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= timeout -k 10 300 \
		python tools/sanitize_stress.py --sanitizer thread --lane edge \
		--seconds 15
	JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= timeout -k 10 300 \
		python tools/sanitize_stress.py --sanitizer thread --lane jit \
		--seconds 15
	JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= timeout -k 10 300 \
		python tools/sanitize_stress.py --sanitizer undefined --seconds 15
	JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= timeout -k 10 300 \
		python tools/sanitize_stress.py --sanitizer undefined --lane edge \
		--seconds 15
	JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= timeout -k 10 300 \
		python tools/sanitize_stress.py --sanitizer undefined --lane jit \
		--seconds 15

# Project static analysis (misaka_tpu/lint): the repo's recurring bug
# classes as machine-checked rules MSK001-MSK006.  Exit 1 on any NEW
# finding; pre-existing intentional ones live in
# misaka_tpu/lint/baseline.txt.  docs/STATIC_ANALYSIS.md has the rule
# catalog and the add-a-checker / baseline workflows.
lint:
	python -m misaka_tpu.lint

# Regenerate protobuf message classes for the per-process transport.  The
# image ships protoc but not grpcio-tools; service stubs are hand-declared
# in misaka_tpu/transport/rpc.py.
grpc:
	protoc --python_out=misaka_tpu/transport --proto_path=misaka_tpu/transport \
		misaka_tpu/transport/messenger.proto

# Self-signed TLS for per-process nodes (the reference's `make cert`,
# Makefile:7-12): a CA plus a service cert whose SANs enumerate the node
# hostnames (deploy/certificate.conf).  CERT_FILE=deploy/certs/service.pem
# KEY_FILE=deploy/certs/service.key
cert:
	mkdir -p deploy/certs
	openssl genrsa -out deploy/certs/ca.key 4096
	openssl req -new -x509 -key deploy/certs/ca.key -sha256 \
		-subj "/C=JP/ST=TOK/L=Academy City/O=SYSTEM/OU=Level 6 Shift" \
		-days 365 -out deploy/certs/ca.cert
	openssl genrsa -out deploy/certs/service.key 4096
	openssl req -new -key deploy/certs/service.key \
		-out deploy/certs/service.csr -config deploy/certificate.conf
	openssl x509 -req -in deploy/certs/service.csr -CA deploy/certs/ca.cert \
		-CAkey deploy/certs/ca.key -CAcreateserial \
		-out deploy/certs/service.pem -days 365 -sha256 \
		-extfile deploy/certificate.conf -extensions req_ext

# Real-hardware lane: the Mosaic-compiled fused kernel, one config per
# storage mode (tests/test_tpu.py).  Requires an attached TPU.
test-tpu:
	MISAKA_TPU_TESTS=1 python -m pytest tests/test_tpu.py -m tpu -q

# One-shot TPU evidence capture (probe, hardware test lane, full bench,
# roofline, hi-elision A/B) — run the moment the relayed chip answers.
capture:
	bash tools/tpu_capture.sh

# Fast lane: every component smoke-covered, fuzz/scale/multi-process
# suites excluded (marked slow) — target < 3 min.
test:
	python -m pytest tests/ -x -q -m "not slow"

# Everything, including the slow fuzz/scale/multi-process lanes (~20+ min).
test-all:
	python -m pytest tests/ -x -q

bench:
	python bench.py

# Serving-tier tripwire (~5s): bench_served through the multi-threaded
# native C++ tier must clear the 1M inputs/s north star on this host, or
# the target fails — catches a CPU-fallback serving regression BEFORE a
# driver capture lands on it.  Forced to CPU so it never touches (or
# wedges on) the TPU relay.
bench-smoke:
	JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= python bench.py --smoke

# Metrics-plane tripwire (~10s): boot a batched master + HTTP server, fire
# concurrent traffic, assert GET /metrics parses (Prometheus text
# exposition v0.0.4) and the key series moved (route counters, latency
# histograms, device-loop ticks).  The same assertions run inside tier-1
# (tests/test_metrics.py); docs/OBSERVABILITY.md has the metric catalog.
metrics-smoke:
	JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= python tools/metrics_smoke.py

# Request-tracing tripwire (~10s): boot a server with SO_REUSEPORT
# frontend workers, fire concurrent traffic carrying X-Misaka-Trace IDs,
# fetch GET /debug/perfetto from the engine, and assert spans from >= 3
# tiers (frontend/plane/serve/...) appear under one trace ID — the whole
# propagation chain in one shot.  The same assertions run inside tier-1
# (tests/test_request_trace.py); docs/OBSERVABILITY.md "Request tracing".
trace-smoke:
	JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= python tools/trace_smoke.py

# Program-registry tripwire (~15s): a REAL subprocess server with
# MISAKA_PROGRAMS_DIR armed — upload two programs, serve both concurrently
# from per-program engines (parity-checked), hot-swap one by publishing a
# new version under live traffic with zero client-visible errors, and
# assert /metrics carries program-labeled registry series and
# /debug/requests traces carry the program attr on serve.pass.  The same
# assertions run inside tier-1 (tests/test_registry.py).
registry-smoke:
	JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= timeout -k 10 300 \
		python tools/registry_smoke.py

# Capture/shadow-replay tripwire (~15s): arm the wire recorder over HTTP
# on a registry-armed server, serve mixed two-program traffic, export a
# manifest-verified segment + anchor checkpoints, then assert the whole
# record plane: tools/replay.py replays both programs byte-for-byte
# green (rc 0), an ADD20 mutant candidate renders the loud per-request
# DIVERGENCE lines (rc 1), POST /programs?verify=replay admits the
# unchanged program and 409s the mutant with structured diffs, and
# --emit-model fits a bench.py --model load model from the capture.  The
# same assertions run inside tier-1 (tests/test_capture.py);
# docs/OBSERVABILITY.md "Traffic capture & shadow replay".
replay-smoke:
	JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= timeout -k 10 300 \
		python tools/replay_smoke.py

# Native flight-recorder tripwire (~10s): a REAL subprocess server with
# frontend workers — traced traffic carrying X-Misaka-Trace IDs, then
# assert GET /debug/perfetto renders ONE unified timeline per ID spanning
# >= 5 tiers (http/frontend/plane/serve + native worker-thread spans from
# the in-C++ event rings) and GET /debug/native_trace carries rung-tagged
# unit events with the same IDs attached.  The same assertions run inside
# tier-1 (tests/test_native_trace.py); docs/OBSERVABILITY.md "Native
# flight recorder".
native-trace-smoke:
	JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= timeout -k 10 300 \
		python tools/native_trace_smoke.py

# Usage/SLO/profiler tripwire (~15s): a REAL subprocess server — two
# registry tenants under mixed native+Python load, then assert GET
# /debug/usage attributes nonzero CPU-seconds per program summing to the
# pass wall total (20%), /debug/flamegraph carries both a CPython frame
# aggregate and the native busy/idle split, and /debug/alerts serves the
# SLO states.  The same assertions run inside tier-1 (tests/test_usage.py,
# tests/test_slo.py); docs/OBSERVABILITY.md has the catalogs.
usage-smoke:
	JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= timeout -k 10 300 \
		python tools/usage_smoke.py

# Observatory tripwire (~30s): a REAL subprocess server with the
# embedded TSDB + canary + watchdog at test cadence — >= 3 collected
# intervals with well-formed /debug/series shapes, the self-contained
# dashboard with populated sparklines, a green full-stack canary series,
# and a watchdog page (with exemplar trace IDs, /healthz degraded)
# fired by an injected serve_delay fault over POST /debug/faults and
# cleared on recovery.  The same assertions run inside tier-1
# (tests/test_observatory.py, tests/test_tsdb.py); the fleet-mode live
# drill is tests/test_observatory.py -m slow (test-all / fleet lanes).
# docs/OBSERVABILITY.md "The observatory".
observatory-smoke:
	JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= timeout -k 10 300 \
		python tools/observatory_smoke.py

# Durable-telemetry tripwire (~30s): a REAL subprocess server with
# MISAKA_TSDB_DIR armed at test cadence — the capture spool rotates
# >= 2 on-disk segments (operator cut + size trigger), kill -9 +
# relaunch over the same directory, /debug/series answers with
# pre-restart points (7d window grammar included), the usage-report
# CLI's cumulative totals stay monotone + conserve vs the pass-wall
# anchor, and a pre-kill rotated capture segment replays byte-for-byte
# green.  The same assertions run inside tier-1 (tests/test_durable.py);
# docs/OBSERVABILITY.md "Durable telemetry".
telemetry-smoke:
	JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= timeout -k 10 300 \
		python tools/telemetry_smoke.py

# The CI entry point: tier-1 fast lane + every smoke tripwire +
# bench-smoke, in one target — what a CI runner invokes (there is no
# hosted CI config; this is the single command one would call).  Order:
# the cheap wide net first (pytest), then the subprocess smokes, then the
# throughput gate last (it is the slowest and the most environment-
# sensitive).  Fails on the first broken stage.
ci:
	$(MAKE) lint
	$(MAKE) test
	$(MAKE) sanitize-smoke
	$(MAKE) metrics-smoke
	$(MAKE) trace-smoke
	$(MAKE) native-trace-smoke
	$(MAKE) registry-smoke
	$(MAKE) replay-smoke
	$(MAKE) usage-smoke
	$(MAKE) observatory-smoke
	$(MAKE) telemetry-smoke
	$(MAKE) edge-smoke
	$(MAKE) edge-native-smoke
	$(MAKE) chaos-smoke
	$(MAKE) fleet-smoke
	$(MAKE) dist-smoke
	$(MAKE) bench-smoke

# Production-edge tripwire (~15s): a REAL subprocess server behind TLS
# (throwaway self-signed cert) with API-key auth + per-tenant quotas and
# the SO_REUSEPORT frontend tier — asserts the TLS handshake (CA-pinned
# client ok, untrusted + plaintext refused), bad key -> typed 401,
# non-admin lifecycle -> 403, quota exhaustion -> typed 429 WITH
# Retry-After on the hot compute-plane path, and recovery after the
# advertised backoff.  The same assertions run inside tier-1
# (tests/test_edge.py); docs/ARCHITECTURE.md "The production edge".
edge-smoke:
	JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= timeout -k 10 300 \
		python tools/edge_smoke.py

# Native-edge tripwire (~20s): a REAL subprocess server with the C++
# epoll frontend tier on the public port (native/frontend.cpp) — authed,
# keyless, and over-quota clients through the native tier (typed 401/413
# with the engine chain's exact bodies), a 5-tier Perfetto assertion
# (http/frontend/plane/serve/native) under ONE inbound X-Misaka-Trace
# ID, and the build-failure chaos point proving total fallback to the
# CPython worker tier.  The same assertions run inside tier-1
# (tests/test_native_edge.py); docs/ARCHITECTURE.md "The native edge".
edge-native-smoke: native/libmisaka_frontend.so
	JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= timeout -k 10 300 \
		python tools/edge_native_smoke.py

# Fault-tolerance tripwire (~15s): the fast chaos lane, driven through the
# MISAKA_FAULTS harness (utils/faults.py) — durable-checkpoint rejection of
# torn/corrupt files, crash-mid-save atomicity, auto-checkpoint rotation +
# fallback restore, RPC backoff policy, frontend-supervisor respawn and
# crash-loop circuit breaker — plus the fleet failover shapes from
# tests/test_fleet.py (replica death under concurrent load, drain
# reroute, scoped replica_blackhole and plane_partition hedging
# (the partitioned-remote-peer drill), readmission, typed
# fleet-down 503).  The multi-second kill-9-under-load, dead-peer
# recovery, and subprocess-fleet scenarios are marked slow (test-all and
# fleet-smoke run them).  docs/ARCHITECTURE.md "Fault tolerance" + "The
# engine fleet" describe the contracts.
chaos-smoke:
	JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= timeout -k 10 300 \
		python -m pytest tests/test_chaos.py -q -m "not slow" -p no:cacheprovider
	JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= timeout -k 10 300 \
		python -m pytest tests/test_fleet.py -q -m "not slow" -p no:cacheprovider \
		-k "failover or blackhole or drain or fleet_down or readmits or fault or stale or partition"

# Fleet tripwire (~60s): the REAL thing — a subprocess fleet of 4 engine
# replicas behind supervised SO_REUSEPORT frontends, 64 pooled concurrent
# clients, one kill -9 (zero client-visible errors, supervisor respawn),
# one POST /fleet/roll across all replicas under the same load (zero
# loss, drain→manifest-verified checkpoint→replace→bit-identical
# restore), plus the MISAKA_FAULTS=replica_kill boot scenario.  These
# acceptance tests are slow-marked, so this target is their CI home.
fleet-smoke:
	JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= timeout -k 10 580 \
		python -m pytest tests/test_fleet.py -q -m slow -p no:cacheprovider

# Multi-host tripwire (~90s): TWO real `runtime.app` processes on
# loopback TCP — a standalone remote-peer replica serving its compute
# plane over CA-pinned mTLS (MISAKA_PLANE_TLS_*) and a MISAKA_FLEET=1
# parent that registers it via MISAKA_FLEET_PEERS, probes it on the
# shared replica state machine, and fans frames across both planes.
# Drill: 64 pooled clients through a kill -9 of the REMOTE peer (zero
# client-visible errors), same-port restart readmission, authenticated
# remote /fleet/roll (drain -> checkpoint -> readmit), /edge/token mint
# + locally-verified compute, and the fleet metric surface (peers_up,
# gossip rounds, zero plane-TLS rejects).  Skips cleanly without
# openssl.  docs/ARCHITECTURE.md "Multi-host fleet".
dist-smoke:
	JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= timeout -k 10 580 \
		python tools/dist_smoke.py

# Replay the committed parity corpus (tests/corpus/parity/) against the
# ACTUAL Go reference binary via its own Dockerfile — the SURVEY.md §4
# check.  Skips cleanly (exit 0) where Docker is unavailable (here); the
# corpus's engine side is re-verified by tests/test_parity_corpus.py.
parity-go:
	python tools/parity_go.py

# Same corpus, replayed against OUR wire-compatible per-process gRPC
# cluster over the identical serialized POST /compute protocol — proves
# the replay harness end-to-end where Docker is absent.
parity-local:
	python tools/parity_go.py --local

# Regenerate the parity corpus (rewrites tests/corpus/parity/*.json with
# freshly recorded engine outputs; commit the result).
parity-corpus:
	python tools/gen_parity_corpus.py

# Kill any straggling misaka servers/benches.  The attached TPU relay admits
# one client: a leaked server wedges every later jax.devices() call
# (VERDICT r3 weak #1).  runtime/lifecycle.py makes leaks hard to create;
# this is the manual backstop.
stop:
	-pkill -f 'misaka_tpu.runtime.app'
	-pkill -f 'misaka_tpu/runtime/app'
	-pkill -f 'python -m misaka_tpu'
	-pkill -f 'bench\.py'
	@echo "stopped (any straggling misaka processes killed)"

clean:
	rm -f native/*.so

.PHONY: native native-asan native-tsan native-ubsan sanitize-smoke sanitize-all lint grpc cert test test-all test-tpu capture bench bench-smoke metrics-smoke trace-smoke native-trace-smoke registry-smoke replay-smoke usage-smoke observatory-smoke telemetry-smoke edge-smoke edge-native-smoke chaos-smoke fleet-smoke dist-smoke ci parity-go parity-local parity-corpus stop clean
