# Build targets for misaka_tpu (cf. the reference's Makefile: build/grpc/cert).
# The TPU build has no codegen or TLS certs; native/ holds the C++ runtime
# components.

CXX ?= g++
CXXFLAGS ?= -O2 -std=c++17 -shared -fPIC

native: native/libmisaka_assembler.so

native/libmisaka_assembler.so: native/assembler.cpp
	$(CXX) $(CXXFLAGS) $< -o $@

test:
	python -m pytest tests/ -x -q

bench:
	python bench.py

clean:
	rm -f native/*.so

.PHONY: native test bench clean
