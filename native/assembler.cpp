// Native TIS assembler: tokenizer + dense-table lowering in C++.
//
// Functional twin of misaka_tpu/tis/parser.py + lower.py (which mirror the
// reference's internal/tis/tokenizer.go grammar branch for branch).  Exposed
// as a C ABI for ctypes; used by the runtime for fast /load of large
// programs and as the seed of the native host-runtime layer.  Parity with
// the Python frontend is enforced by tests/test_native.py (corpus + fuzz).
//
// Build: make native   (g++ -O2 -std=c++17 -shared -fPIC)

#include <cstdint>
#include <cstring>
#include <map>
#include <regex>
#include <string>
#include <vector>

namespace {

// --- ISA constants: must match misaka_tpu/tis/isa.py ------------------------
enum Op {
  OP_NOP = 0, OP_SWP = 1, OP_SAV = 2, OP_NEG = 3,
  OP_MOV_LOCAL = 4, OP_MOV_NET = 5, OP_ADD = 6, OP_SUB = 7,
  OP_JMP = 8, OP_JEZ = 9, OP_JNZ = 10, OP_JGZ = 11, OP_JLZ = 12,
  OP_JRO = 13, OP_PUSH = 14, OP_POP = 15, OP_IN = 16, OP_OUT = 17,
};
enum Src { SRC_IMM = 0, SRC_ACC = 1, SRC_NIL = 2, SRC_R0 = 3 };
enum Dst { DST_ACC = 0, DST_NIL = 1 };
enum Field { F_OP = 0, F_SRC, F_IMM, F_DST, F_TGT, F_PORT, F_JMP, NFIELDS };

// --- grammar (tokenizer.go:41-101; \w kept ASCII as in Go) ------------------
const std::regex kLabel("^\\s*([0-9A-Za-z_]+):");
const std::regex kPrefix("^(\\s*[0-9A-Za-z_]+:)?\\s*");
const std::regex kComment("^#.*$");
const std::regex kNullary("^(NOP|SWP|SAV|NEG)\\s*$");
const std::regex kMovValLocal("^MOV\\s+(-?\\d+)\\s*,\\s+(ACC|NIL)\\s*$");
const std::regex kMovValNet("^MOV\\s+(-?\\d+)\\s*,\\s+([0-9A-Za-z_]+:R[0123])\\s*$");
const std::regex kMovSrcLocal("^MOV\\s+(ACC|NIL|R[0123])\\s*,\\s+(ACC|NIL)\\s*$");
const std::regex kMovSrcNet("^MOV\\s+(ACC|NIL|R[0123])\\s*,\\s+([0-9A-Za-z_]+:R[0123])\\s*$");
const std::regex kAddSubVal("^(ADD|SUB)\\s+(-?\\d+)\\s*$");
const std::regex kAddSubSrc("^(ADD|SUB)\\s+(ACC|NIL|R[0123])\\s*$");
const std::regex kJump("^(JMP|JEZ|JNZ|JGZ|JLZ)\\s+([0-9A-Za-z_]+)\\s*$");
const std::regex kJroVal("^JRO\\s+(-?\\d+)\\s*$");
const std::regex kJroSrc("^JRO\\s+(ACC|NIL|R[0123])\\s*$");
const std::regex kPushVal("^PUSH\\s+(-?\\d+)\\s*,\\s+([0-9A-Za-z_]+)\\s*$");
const std::regex kPushSrc("^PUSH\\s+(ACC|NIL|R[0123])\\s*,\\s+([0-9A-Za-z_]+)\\s*$");
const std::regex kPop("^POP\\s+([0-9A-Za-z_]+)\\s*,\\s+(ACC|NIL)\\s*$");
const std::regex kIn("^IN\\s+(ACC|NIL)\\s*$");
const std::regex kOutVal("^OUT\\s+(-?\\d+)\\s*$");
const std::regex kOutSrc("^OUT\\s+(ACC|NIL|R[0123])\\s*$");

std::string upper(const std::string& s) {
  std::string r = s;
  for (auto& c : r) c = toupper((unsigned char)c);
  return r;
}

int32_t parse_i32(const std::string& text) {
  // Python-side wrap semantics: value mod 2^32 into int32 range.
  long long v = strtoll(text.c_str(), nullptr, 10);
  return (int32_t)(uint64_t)v;
}

int src_sel(const std::string& tok) {
  if (tok == "ACC") return SRC_ACC;
  if (tok == "NIL") return SRC_NIL;
  return SRC_R0 + (tok[1] - '0');  // R0..R3
}

std::vector<std::string> split_lines(const std::string& text) {
  std::vector<std::string> out;
  size_t start = 0;
  while (true) {
    size_t nl = text.find('\n', start);
    if (nl == std::string::npos) {
      out.push_back(text.substr(start));
      break;
    }
    out.push_back(text.substr(start, nl - start));
    start = nl + 1;
  }
  return out;
}

std::map<std::string, int> name_map(const char* joined) {
  std::map<std::string, int> m;
  if (!joined || !*joined) return m;
  int i = 0;
  for (auto& name : split_lines(joined)) {
    if (!name.empty()) m[name] = i++;
  }
  return m;
}

struct Error {
  std::string msg;
};

}  // namespace

extern "C" {

// Source-identity tag scanned from the .so bytes by utils/nativelib.py to
// detect a binary built from different source (mtime comparison cannot —
// a fresh checkout gives every file the same timestamp).  The build injects
// -DMISAKA_SRC_HASH=<sha256[:16] of this file>.
#ifndef MISAKA_SRC_HASH
#define MISAKA_SRC_HASH "unbuilt"
#endif
__attribute__((used)) const char misaka_src_hash_tag[] =
    "MISAKA-SRC-HASH:" MISAKA_SRC_HASH;

// Assemble `program` into out_code[max_lines * NFIELDS] (row-major).
// Returns the number of lines, or -1 with `err` filled.
int misaka_assemble(const char* program, const char* lane_names,
                    const char* stack_names, int32_t* out_code, int max_lines,
                    char* err, int err_cap) {
  auto fail = [&](const std::string& m) {
    if (err && err_cap > 0) {
      strncpy(err, m.c_str(), err_cap - 1);
      err[err_cap - 1] = 0;
    }
    return -1;
  };

  auto lanes = name_map(lane_names);
  auto stacks = name_map(stack_names);
  auto lines = split_lines(program ? program : "");
  if ((int)lines.size() > max_lines) return fail("program too long");

  // pass 1: label map (tokenizer.go:11-26)
  std::map<std::string, int> label_map;
  for (size_t i = 0; i < lines.size(); i++) {
    std::smatch m;
    if (std::regex_search(lines[i], m, kLabel)) {
      std::string label = upper(m[1].str());
      if (label_map.count(label)) return fail("Cannot repeat label");
      label_map[label] = (int)i;
    }
  }

  // pass 2: tokenize + lower in one sweep
  for (size_t i = 0; i < lines.size(); i++) {
    int32_t* f = out_code + i * NFIELDS;
    memset(f, 0, NFIELDS * sizeof(int32_t));
    std::string instr = lines[i];
    std::smatch pm;
    if (std::regex_search(instr, pm, kPrefix)) instr = pm.suffix().str();

    std::smatch m;
    auto line_err = [&](const std::string& what) {
      return fail("line " + std::to_string(i) + ", " + what);
    };

    if (instr.empty() || std::regex_match(instr, m, kComment)) {
      f[F_OP] = OP_NOP;
    } else if (std::regex_match(instr, m, kNullary)) {
      const std::string t = m[1].str();
      f[F_OP] = t == "NOP" ? OP_NOP : t == "SWP" ? OP_SWP
                : t == "SAV" ? OP_SAV : OP_NEG;
    } else if (std::regex_match(instr, m, kMovValLocal)) {
      f[F_OP] = OP_MOV_LOCAL;
      f[F_SRC] = SRC_IMM;
      f[F_IMM] = parse_i32(m[1].str());
      f[F_DST] = m[2].str() == "ACC" ? DST_ACC : DST_NIL;
    } else if (std::regex_match(instr, m, kMovValNet) ||
               std::regex_match(instr, m, kMovSrcLocal) ||
               std::regex_match(instr, m, kMovSrcNet)) {
      // disambiguate which matched (regex_match left `m` from the first hit)
      std::smatch mv;
      if (std::regex_match(instr, mv, kMovValNet)) {
        f[F_OP] = OP_MOV_NET;
        f[F_SRC] = SRC_IMM;
        f[F_IMM] = parse_i32(mv[1].str());
        std::string tgt = mv[2].str();
        size_t colon = tgt.find(':');
        std::string name = tgt.substr(0, colon);
        if (!lanes.count(name))
          return line_err("'" + name + "' is not a program node on this network");
        f[F_TGT] = lanes[name];
        f[F_PORT] = tgt[colon + 2] - '0';
      } else if (std::regex_match(instr, mv, kMovSrcLocal)) {
        f[F_OP] = OP_MOV_LOCAL;
        f[F_SRC] = src_sel(mv[1].str());
        f[F_DST] = mv[2].str() == "ACC" ? DST_ACC : DST_NIL;
      } else {
        std::regex_match(instr, mv, kMovSrcNet);
        f[F_OP] = OP_MOV_NET;
        f[F_SRC] = src_sel(mv[1].str());
        std::string tgt = mv[2].str();
        size_t colon = tgt.find(':');
        std::string name = tgt.substr(0, colon);
        if (!lanes.count(name))
          return line_err("'" + name + "' is not a program node on this network");
        f[F_TGT] = lanes[name];
        f[F_PORT] = tgt[colon + 2] - '0';
      }
    } else if (std::regex_match(instr, m, kAddSubVal)) {
      f[F_OP] = m[1].str() == "ADD" ? OP_ADD : OP_SUB;
      f[F_SRC] = SRC_IMM;
      f[F_IMM] = parse_i32(m[2].str());
    } else if (std::regex_match(instr, m, kAddSubSrc)) {
      f[F_OP] = m[1].str() == "ADD" ? OP_ADD : OP_SUB;
      f[F_SRC] = src_sel(m[2].str());
    } else if (std::regex_match(instr, m, kJump)) {
      std::string label = upper(m[2].str());
      if (!label_map.count(label))
        return line_err("label '" + label + "' was not declared");
      const std::string t = m[1].str();
      f[F_OP] = t == "JMP" ? OP_JMP : t == "JEZ" ? OP_JEZ
                : t == "JNZ" ? OP_JNZ : t == "JGZ" ? OP_JGZ : OP_JLZ;
      f[F_JMP] = label_map[label];
    } else if (std::regex_match(instr, m, kJroVal)) {
      f[F_OP] = OP_JRO;
      f[F_SRC] = SRC_IMM;
      f[F_IMM] = parse_i32(m[1].str());
    } else if (std::regex_match(instr, m, kJroSrc)) {
      f[F_OP] = OP_JRO;
      f[F_SRC] = src_sel(m[1].str());
    } else if (std::regex_match(instr, m, kPushVal) ||
               std::regex_match(instr, m, kPushSrc)) {
      std::smatch pv;
      f[F_OP] = OP_PUSH;
      std::string tgt;
      if (std::regex_match(instr, pv, kPushVal)) {
        f[F_SRC] = SRC_IMM;
        f[F_IMM] = parse_i32(pv[1].str());
        tgt = pv[2].str();
      } else {
        std::regex_match(instr, pv, kPushSrc);
        f[F_SRC] = src_sel(pv[1].str());
        tgt = pv[2].str();
      }
      if (!stacks.count(tgt))
        return line_err("'" + tgt + "' is not a stack node on this network");
      f[F_TGT] = stacks[tgt];
    } else if (std::regex_match(instr, m, kPop)) {
      f[F_OP] = OP_POP;
      std::string tgt = m[1].str();
      if (!stacks.count(tgt))
        return line_err("'" + tgt + "' is not a stack node on this network");
      f[F_TGT] = stacks[tgt];
      f[F_DST] = m[2].str() == "ACC" ? DST_ACC : DST_NIL;
    } else if (std::regex_match(instr, m, kIn)) {
      f[F_OP] = OP_IN;
      f[F_DST] = m[1].str() == "ACC" ? DST_ACC : DST_NIL;
    } else if (std::regex_match(instr, m, kOutVal)) {
      f[F_OP] = OP_OUT;
      f[F_SRC] = SRC_IMM;
      f[F_IMM] = parse_i32(m[1].str());
    } else if (std::regex_match(instr, m, kOutSrc)) {
      f[F_OP] = OP_OUT;
      f[F_SRC] = src_sel(m[1].str());
    } else {
      return line_err("'" + instr + "' not a valid instruction");
    }
  }

  return (int)lines.size();
}

}  // extern "C"
