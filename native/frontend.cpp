// Native serving edge (ISSUE 16): an epoll-driven HTTP/1.1 frontend tier
// that terminates the hot compute routes in C++ and speaks the existing
// compute-plane frame protocol straight into the engine — no GIL on the
// data path.  CPython stays the control plane: runtime/frontends.py's
// NativeFrontendSupervisor compiles this unit, starts it over the ctypes
// C API below, and pushes auth-key digests / quota specs / the program
// map as JSON snapshots (msk_edge_push_state), the way specialize.py
// pushes compiled programs.
//
// Division of authority (load-bearing — the parity tests pin it):
//  * The ENGINE-side edge chain stays the authority for every admission
//    decision that ships: each plane frame carries the request's API key
//    and the engine answers typed EdgeReject JSON that this tier renders
//    exactly like the CPython worker's _plane_error (message body,
//    Retry-After ceiling, WWW-Authenticate on 401).
//  * The native tier answers LOCALLY only what the CPython tier also
//    answers locally (shed-cache 429 replays, the plane-depth overload
//    guard) plus the two decisions the pushed state makes safe: fast
//    401s against the pushed digest table (the same 0.5s staleness the
//    engine's own KeyFile re-stat has) and the single-request
//    burst-capacity 413 for keys whose OWN quota spec pins vps.  Every
//    local rejection is billed engine-side through the frame-metadata
//    "shed" rows, so misaka_edge_* counters stay whole.
//  * Anything else — admin routes, debug surfaces, GETs, cold lanes —
//    proxies to the CPython worker tier unchanged (same 5 forwarded /
//    6 copied-back headers as FrontendHandler._proxy).
//
// Concurrency model: N worker threads (MISAKA_NATIVE_EDGE_THREADS), each
// with its own SO_REUSEPORT listener, epoll instance, connection table,
// plane connections, and shed cache — nothing crosses threads except the
// stats atomics, the pushed-state shared_ptr swap, and the span ring.

#include "msk_frame.hpp"
#include "msk_http.hpp"

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

#include <atomic>
#include <cmath>
#include <deque>
#include <iterator>
#include <memory>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <unordered_set>

namespace {

using msk::JsonValue;

inline double mono_now() {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (double)ts.tv_sec + (double)ts.tv_nsec * 1e-9;
}

inline double unix_now() {
    struct timespec ts;
    clock_gettime(CLOCK_REALTIME, &ts);
    return (double)ts.tv_sec + (double)ts.tv_nsec * 1e-9;
}

// ---------------------------------------------------------------------------
// Configuration + pushed state
// ---------------------------------------------------------------------------

struct Config {
    int port = 0;
    int threads = 2;
    int max_conns = 1024;
    int plane_conns = 2;
    int plane_depth_max = 256;
    int proxy_port = 0;
    int64_t max_body = 8 << 20;
    int64_t plane_body_limit = 2 << 20;
    double plane_timeout = 30.0;
    std::string plane_path;
    std::string proxy_host = "127.0.0.1";
    std::string handshake;  // raw bytes (empty = plane secret unarmed)
};

struct BurstQuota {
    double cap = 0.0;        // scaled burst capacity in values
    std::string msg_mid;     // rendered Python-side: " values exceeds ..."
    std::string tenant;
};

// Immutable control-plane snapshot; workers load it via shared_ptr so a
// push never blocks the data path.
struct PushState {
    bool auth_armed = false;
    std::unordered_set<std::string> digests;  // hex HMAC digests
    std::unordered_map<std::string, BurstQuota> bursts;
    std::string missing_msg;  // 401 body for a keyless request
    std::string unknown_msg;  // 401 body for an unknown key
    std::string healthz_body = "{\"ok\": true}\n";
    std::string healthz_ctype = "application/json";
    std::unordered_set<std::string> programs;
    bool trace_enabled = false;
    double trace_sample = 1.0;
    bool slo_armed = false;
    // the capture plane (runtime/capture.py): while the engine-side
    // recorder is armed, locally-terminated rejects (shed 429, 401, 413,
    // overload) are recorded here and drained by the supervisor — the
    // engine never sees those requests, so this tier owns their records
    bool capture_enabled = false;
    double capture_sample = 1.0;
};

struct Stats {
    std::atomic<uint64_t> conns_total{0};
    std::atomic<uint64_t> conns_open{0};
    std::atomic<uint64_t> requests{0};
    std::atomic<uint64_t> plane_shipped{0};
    std::atomic<uint64_t> proxied{0};
    std::atomic<uint64_t> plane_errors{0};
    std::atomic<uint64_t> local_401{0};
    std::atomic<uint64_t> local_413{0};
    std::atomic<uint64_t> shed_hits{0};
    std::atomic<uint64_t> overload{0};
};

struct SpanRec {
    std::string name;
    std::string lane;
    std::string trace;
    double start = 0.0;
    double dur = 0.0;
};

// one locally-terminated request the capture plane records (sampling is
// applied at record time, so drained rows ingest pre-sampled)
struct CaptureRec {
    double t = 0.0;  // unix seconds (the load-model arrival clock)
    std::string program;
    std::string trace;
    int status = 0;
    std::string reason;
};

// ---------------------------------------------------------------------------
// Per-connection state
// ---------------------------------------------------------------------------

enum class CState { Head, Body, Wait };
enum class Dispatch { None, Raw, Compute, Batch, Proxy, Discard };

struct Conn {
    int fd = -1;
    uint64_t gen = 0;
    uint32_t events = 0;  // currently-armed epoll interest
    CState st = CState::Head;
    Dispatch disp = Dispatch::None;
    bool close_after = false;
    std::string rbuf;
    std::string wbuf;
    size_t woff = 0;
    int64_t body_need = 0;

    // request context (reset per request)
    msk::HttpRequest req;
    std::string program;   // "" = default-addressed
    std::string key;       // "" = keyless
    std::string trace_id;  // "" = untraced
    bool trace_inbound = false;  // ID presented by the client (capture
                                 // sampling bypass rides this)
    bool accepts_binary = false;
    double t_start = 0.0, t_parse = 0.0, d_parse = 0.0;

    // deferred reply for drain-then-answer paths (shed-cache hits)
    bool have_deferred = false;
    int deferred_status = 0;
    std::string deferred_body;
    std::vector<std::pair<std::string, std::string>> deferred_extras;

    // proxy upstream
    int upfd = -1;
    bool up_reused = false;
    bool up_connecting = false;
    int up_attempts = 0;
    std::string up_req;   // full serialized upstream request (for retry)
    size_t up_woff = 0;
    std::string up_rbuf;
    size_t up_head_end = 0;
    int64_t up_body_need = -1;  // -1 head pending, -2 read-to-EOF
};

struct PlanePending {
    uint32_t slot = 0;
    uint64_t gen = 0;
    Dispatch kind = Dispatch::Raw;
    bool accepts_binary = false;
    bool zombie = false;
    double deadline = 0.0;
    double t_ship = 0.0;
    double t_req_start = 0.0;
    std::string trace_id;
    std::string shed_program;
    std::string shed_key;
};

struct PlaneConn {
    int fd = -1;
    uint32_t events = 0;
    std::string wbuf;
    size_t woff = 0;
    std::string rbuf;
    std::deque<PlanePending> pending;
    double reconnect_at = 0.0;
};

struct ShedEntry {
    double until = 0.0;
    std::string message;
    std::string tenant;  // "" = no tenant label
    bool has_tenant = false;
    std::string reason;
};

struct Engine;

// epoll tag kinds packed into event.data.u64 as (kind << 48) | index
enum : uint64_t { K_LISTEN = 1, K_WAKE = 2, K_CLIENT = 3, K_PLANE = 4,
                  K_UP = 5 };

struct Worker {
    Engine* eng = nullptr;
    int idx = 0;
    int ep = -1;
    int listen_fd = -1;
    int wake_fd = -1;
    std::string lane;
    uint64_t rng = 0;
    std::vector<std::unique_ptr<Conn>> slots;
    std::vector<uint32_t> free_slots;
    uint64_t next_gen = 1;
    std::vector<PlaneConn> planes;
    std::unordered_map<std::string, ShedEntry> shed;
    std::unordered_map<std::string, uint64_t> shed_rows;  // tenant\0reason
    double next_housekeep = 0.0;

    void run();
    void tick_housekeeping(double now);
    // clients
    void on_accept();
    Conn* conn_at(uint32_t slot, uint64_t gen);
    void close_conn(uint32_t slot);
    void update_events(uint32_t slot);
    void flush_conn(uint32_t slot);
    void on_client_io(uint32_t slot, uint32_t evmask);
    void process(uint32_t slot);
    void handle_head(uint32_t slot);
    void dispatch_body(uint32_t slot, std::string&& body);
    void reply(uint32_t slot, int status, const char* ctype,
               const std::string& body,
               std::vector<std::pair<std::string, std::string>> extras,
               bool add_trace);
    void reply_text(uint32_t slot, int status, const std::string& body,
                    std::vector<std::pair<std::string, std::string>> extras);
    void finish_request(uint32_t slot);
    // plane
    bool ensure_plane(size_t i, double now);
    void ship_frame(uint32_t slot, Dispatch kind, const std::string& payload);
    void flush_plane(size_t i);
    void on_plane_io(size_t i, uint32_t evmask);
    void plane_fail_all(size_t i, const char* why);
    void complete_pending(PlanePending& p, int status,
                          const char* body, size_t body_len);
    void plane_error_reply(uint32_t slot, const PlanePending& p, int status,
                           const std::string& body);
    // proxy
    void start_proxy(uint32_t slot, const std::string& body);
    void start_proxy_post(uint32_t slot);
    bool up_connect(uint32_t slot);
    void up_send(uint32_t slot);
    void on_up_io(uint32_t slot, uint32_t evmask);
    void up_fail(uint32_t slot, const char* why);
    void up_deliver(uint32_t slot);
    void close_up(Conn& c);
    // shed + spans + misc
    void shed_row(const std::string& tenant, bool has_tenant,
                  const char* reason);
    void record_span(const char* name, double start, double dur,
                     const std::string& trace);
    void record_capture(const PushState& st, const Conn& c, int status,
                        const char* reason);
    std::string mint_trace();
    int depth() const;
};

struct Engine {
    Config cfg;
    Stats stats;
    std::atomic<bool> stopping{false};
    std::atomic<int> plane_depth{0};
    std::vector<std::thread> threads;
    std::vector<Worker> workers;
    std::vector<int> listeners;
    int actual_port = 0;

    std::mutex state_mu;
    std::shared_ptr<const PushState> state{std::make_shared<PushState>()};

    std::mutex span_mu;
    std::deque<SpanRec> spans;

    std::mutex cap_mu;
    std::deque<CaptureRec> caps;
    uint64_t caps_dropped = 0;  // ring overflow (guarded by cap_mu)

    std::shared_ptr<const PushState> load_state() {
        std::lock_guard<std::mutex> g(state_mu);
        return state;
    }
};

std::mutex g_api_mu;
Engine* g_engine = nullptr;
std::string g_last_error;

// ---------------------------------------------------------------------------
// Small helpers
// ---------------------------------------------------------------------------

void ep_add(int ep, int fd, uint64_t tag, uint32_t events) {
    struct epoll_event ev;
    ev.events = events;
    ev.data.u64 = tag;
    epoll_ctl(ep, EPOLL_CTL_ADD, fd, &ev);
}

void ep_mod(int ep, int fd, uint64_t tag, uint32_t events) {
    struct epoll_event ev;
    ev.events = events;
    ev.data.u64 = tag;
    epoll_ctl(ep, EPOLL_CTL_MOD, fd, &ev);
}

// str(max(1, ceil(x))) — the CPython tier's Retry-After rendering
std::string retry_after_header(double x) {
    long long v = (long long)std::ceil(x);
    if (v < 1) v = 1;
    char buf[32];
    std::snprintf(buf, sizeof(buf), "%lld", v);
    return buf;
}

const char kTextCType[] = "text/plain; charset=utf-8";
const char kWwwAuth[] = "Bearer realm=\"misaka\", charset=\"UTF-8\"";

}  // namespace

// ---------------------------------------------------------------------------
// Worker: event loop
// ---------------------------------------------------------------------------

namespace {

void Worker::run() {
    ep = epoll_create1(EPOLL_CLOEXEC);
    ep_add(ep, listen_fd, (K_LISTEN << 48), EPOLLIN);
    ep_add(ep, wake_fd, (K_WAKE << 48), EPOLLIN);
    planes.resize((size_t)eng->cfg.plane_conns);
    char lbuf[32];
    std::snprintf(lbuf, sizeof(lbuf), "edge-t%d", idx);
    lane = lbuf;
    rng = 0x9e3779b97f4a7c15ull * (uint64_t)(idx + 1) ^
          (uint64_t)::getpid() << 17 ^ (uint64_t)(mono_now() * 1e9);

    struct epoll_event evs[128];
    while (!eng->stopping.load(std::memory_order_relaxed)) {
        const int n = epoll_wait(ep, evs, 128, 100);
        if (eng->stopping.load(std::memory_order_relaxed)) break;
        for (int i = 0; i < n; i++) {
            const uint64_t tag = evs[i].data.u64;
            const uint64_t kind = tag >> 48;
            const uint32_t id = (uint32_t)(tag & 0xffffffffu);
            const uint32_t em = evs[i].events;
            switch (kind) {
                case K_LISTEN: on_accept(); break;
                case K_WAKE: {
                    uint64_t junk;
                    ssize_t r = read(wake_fd, &junk, 8);
                    (void)r;
                    break;
                }
                case K_CLIENT: on_client_io(id, em); break;
                case K_PLANE: on_plane_io(id, em); break;
                case K_UP: on_up_io(id, em); break;
                default: break;
            }
        }
        const double now = mono_now();
        if (now >= next_housekeep) {
            tick_housekeeping(now);
            next_housekeep = now + 0.05;
        }
    }
    // teardown: close everything this worker owns.  wake_fd is NOT ours
    // to close — the stopper may still be write()ing it (it nudges every
    // worker, including ones that already noticed `stopping` on the poll
    // timeout); msk_edge_stop closes it after the join.
    for (uint32_t s = 0; s < slots.size(); s++) {
        if (slots[s]) close_conn(s);
    }
    for (auto& pc : planes) {
        if (pc.fd >= 0) close(pc.fd);
    }
    close(ep);
}

void Worker::tick_housekeeping(double now) {
    // plane frame deadlines: FIFO, so only the front of each queue can
    // time out first; zombies stay queued to keep response pairing
    for (size_t i = 0; i < planes.size(); i++) {
        PlaneConn& pc = planes[i];
        for (auto& p : pc.pending) {
            if (p.zombie || p.deadline > now) continue;
            p.zombie = true;
            eng->plane_depth.fetch_sub(1, std::memory_order_relaxed);
            eng->stats.plane_errors.fetch_add(1, std::memory_order_relaxed);
            Conn* c = conn_at(p.slot, p.gen);
            if (c != nullptr) {
                reply_text(p.slot, 500, "compute plane timed out", {});
                finish_request(p.slot);
            }
        }
    }
    // shed-cache hygiene: CPython sweeps expired past 1024 entries and
    // hard-caps at 4096
    if (shed.size() > 1024) {
        for (auto it = shed.begin(); it != shed.end();) {
            it = (it->second.until <= now) ? shed.erase(it) : std::next(it);
        }
        if (shed.size() > 4096) shed.clear();
    }
}

int Worker::depth() const {
    return eng->plane_depth.load(std::memory_order_relaxed);
}

std::string Worker::mint_trace() {
    // xorshift64* — cheap per-thread IDs, 16 hex chars like uuid4().hex[:16]
    rng ^= rng >> 12;
    rng ^= rng << 25;
    rng ^= rng >> 27;
    const uint64_t v = rng * 0x2545F4914F6CDD1Dull;
    char buf[24];
    std::snprintf(buf, sizeof(buf), "%016llx", (unsigned long long)v);
    return buf;
}

void Worker::record_span(const char* name, double start, double dur,
                         const std::string& trace) {
    std::lock_guard<std::mutex> g(eng->span_mu);
    if (eng->spans.size() >= 2048) eng->spans.pop_front();
    eng->spans.push_back(SpanRec{name, lane, trace, start, dur});
}

void Worker::record_capture(const PushState& st, const Conn& c, int status,
                            const char* reason) {
    if (!st.capture_enabled) return;
    // MISAKA_CAPTURE_SAMPLE applied HERE (rows ingest pre-sampled); an
    // inbound X-Misaka-Trace bypasses sampling, like the engine recorder
    if (!c.trace_inbound && st.capture_sample < 1.0) {
        rng ^= rng >> 12;
        rng ^= rng << 25;
        rng ^= rng >> 27;
        const double u =
            (double)(rng * 0x2545F4914F6CDD1Dull >> 11) * 0x1.0p-53;
        if (u >= st.capture_sample) return;
    }
    std::lock_guard<std::mutex> g(eng->cap_mu);
    if (eng->caps.size() >= 1024) {
        eng->caps.pop_front();
        eng->caps_dropped++;
    }
    eng->caps.push_back(
        CaptureRec{unix_now(), c.program, c.trace_id, status, reason});
}

void Worker::shed_row(const std::string& tenant, bool has_tenant,
                      const char* reason) {
    std::string k = has_tenant ? tenant : std::string("\x01");
    k.push_back('\0');
    k += reason;
    shed_rows[k] += 1;
}

}  // namespace

// ---------------------------------------------------------------------------
// Worker: client connections
// ---------------------------------------------------------------------------

namespace {

void Worker::on_accept() {
    while (true) {
        const int fd = accept4(listen_fd, nullptr, nullptr,
                               SOCK_NONBLOCK | SOCK_CLOEXEC);
        if (fd < 0) return;
        if ((int)eng->stats.conns_open.load(std::memory_order_relaxed) >=
            eng->cfg.max_conns) {
            close(fd);
            continue;
        }
        int one = 1;
        setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        uint32_t slot;
        if (!free_slots.empty()) {
            slot = free_slots.back();
            free_slots.pop_back();
        } else {
            slot = (uint32_t)slots.size();
            slots.emplace_back();
        }
        slots[slot] = std::make_unique<Conn>();
        Conn& c = *slots[slot];
        c.fd = fd;
        c.gen = next_gen++;
        c.events = EPOLLIN;
        ep_add(ep, fd, (K_CLIENT << 48) | slot, EPOLLIN);
        eng->stats.conns_total.fetch_add(1, std::memory_order_relaxed);
        eng->stats.conns_open.fetch_add(1, std::memory_order_relaxed);
    }
}

Conn* Worker::conn_at(uint32_t slot, uint64_t gen) {
    if (slot >= slots.size() || !slots[slot]) return nullptr;
    return slots[slot]->gen == gen ? slots[slot].get() : nullptr;
}

void Worker::close_conn(uint32_t slot) {
    Conn& c = *slots[slot];
    close_up(c);
    close(c.fd);
    slots[slot].reset();
    free_slots.push_back(slot);
    eng->stats.conns_open.fetch_sub(1, std::memory_order_relaxed);
}

void Worker::update_events(uint32_t slot) {
    Conn& c = *slots[slot];
    uint32_t want = 0;
    // natural backpressure: stop reading while a response is pending or
    // the write buffer is deep
    if (c.st != CState::Wait && c.wbuf.size() - c.woff < (512u << 10)) {
        want |= EPOLLIN;
    }
    if (c.woff < c.wbuf.size()) want |= EPOLLOUT;
    if (want != c.events) {
        ep_mod(ep, c.fd, (K_CLIENT << 48) | slot, want);
        c.events = want;
    }
}

void Worker::flush_conn(uint32_t slot) {
    Conn& c = *slots[slot];
    while (c.woff < c.wbuf.size()) {
        const ssize_t n = send(c.fd, c.wbuf.data() + c.woff,
                               c.wbuf.size() - c.woff, MSG_NOSIGNAL);
        if (n > 0) {
            c.woff += (size_t)n;
            continue;
        }
        if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) break;
        close_conn(slot);
        return;
    }
    if (c.woff >= c.wbuf.size()) {
        c.wbuf.clear();
        c.woff = 0;
        if (c.close_after) {
            close_conn(slot);
            return;
        }
    }
    update_events(slot);
}

void Worker::on_client_io(uint32_t slot, uint32_t evmask) {
    if (slot >= slots.size() || !slots[slot]) return;
    if (evmask & (EPOLLHUP | EPOLLERR)) {
        close_conn(slot);
        return;
    }
    Conn& c = *slots[slot];
    if (evmask & EPOLLIN) {
        char buf[16384];
        while (true) {
            const ssize_t n = recv(c.fd, buf, sizeof(buf), 0);
            if (n > 0) {
                c.rbuf.append(buf, (size_t)n);
                if (c.rbuf.size() > (1u << 20) + (size_t)eng->cfg.max_body) {
                    close_conn(slot);  // pipelined flood guard
                    return;
                }
                if (n < (ssize_t)sizeof(buf)) break;
                continue;
            }
            if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) break;
            close_conn(slot);
            return;
        }
        process(slot);
        if (slot >= slots.size() || !slots[slot]) return;
    }
    if ((evmask & EPOLLOUT) && slots[slot]) flush_conn(slot);
}

// Advance the per-connection state machine as far as the buffered bytes
// allow.  Leaves Wait states alone: a plane / upstream completion will
// re-enter via finish_request.
void Worker::process(uint32_t slot) {
    while (slots[slot]) {
        Conn& c = *slots[slot];
        if (c.close_after || c.st == CState::Wait) break;
        if (c.st == CState::Head) {
            if (c.rbuf.empty()) break;
            c.req = msk::HttpRequest();
            int err_status = 0;
            const int r = msk::http_parse_request(c.rbuf.data(),
                                                  c.rbuf.size(), c.req,
                                                  &err_status);
            if (r == 0) break;
            if (r < 0) {
                c.trace_id.clear();
                reply_text(slot, err_status, "request rejected", {});
                slots[slot]->close_after = true;
                flush_conn(slot);
                return;
            }
            c.t_parse = mono_now();
            c.rbuf.erase(0, c.req.header_bytes);
            handle_head(slot);
            continue;
        }
        // CState::Body
        if ((int64_t)c.rbuf.size() < c.body_need) break;
        std::string body = c.rbuf.substr(0, (size_t)c.body_need);
        c.rbuf.erase(0, (size_t)c.body_need);
        c.body_need = 0;
        dispatch_body(slot, std::move(body));
    }
    if (slots[slot]) {
        flush_conn(slot);
    }
}

void Worker::reply(uint32_t slot, int status, const char* ctype,
                   const std::string& body,
                   std::vector<std::pair<std::string, std::string>> extras,
                   bool add_trace) {
    Conn& c = *slots[slot];
    if (add_trace && !c.trace_id.empty()) {
        bool have = false;
        for (const auto& kv : extras) {
            if (kv.first == "X-Misaka-Trace") have = true;
        }
        if (!have) {
            extras.emplace_back("X-Misaka-Trace", c.trace_id);
            char tbuf[48];
            std::snprintf(tbuf, sizeof(tbuf), "total;dur=%.1f",
                          (mono_now() - c.t_start) * 1000.0);
            extras.emplace_back("Server-Timing", tbuf);
        }
    }
    msk::http_response(c.wbuf, status, ctype, body.data(), body.size(),
                       extras);
    if (!c.req.keep_alive) c.close_after = true;
}

void Worker::reply_text(uint32_t slot, int status, const std::string& body,
                        std::vector<std::pair<std::string, std::string>>
                            extras) {
    reply(slot, status, kTextCType, body, std::move(extras), true);
}

// A terminated request finished (success or typed error): rearm the
// connection for the next pipelined request.
void Worker::finish_request(uint32_t slot) {
    Conn& c = *slots[slot];
    c.st = CState::Head;
    c.disp = Dispatch::None;
    process(slot);
    if (slots[slot]) update_events(slot);
}

}  // namespace

// ---------------------------------------------------------------------------
// Worker: request routing
// ---------------------------------------------------------------------------

namespace {

// "/programs/<name>/(compute|compute_batch|compute_raw)" — the same
// shape _PROGRAM_COMPUTE_RE matches (one non-empty, slash-free segment)
bool match_program_route(const std::string& path, std::string& name,
                         std::string& op) {
    static const char prefix[] = "/programs/";
    if (path.compare(0, sizeof(prefix) - 1, prefix) != 0) return false;
    const size_t nstart = sizeof(prefix) - 1;
    const size_t slash = path.find('/', nstart);
    if (slash == std::string::npos || slash == nstart) return false;
    op = path.substr(slash + 1);
    if (op != "compute" && op != "compute_batch" && op != "compute_raw") {
        return false;
    }
    if (op.find('/') != std::string::npos) return false;
    name = msk::url_unquote(path.substr(nstart, slash - nstart));
    return true;
}

void Worker::handle_head(uint32_t slot) {
    Conn& c = *slots[slot];
    auto st = eng->load_state();
    eng->stats.requests.fetch_add(1, std::memory_order_relaxed);
    c.t_start = c.t_parse;
    c.d_parse = 0.0;
    c.program.clear();
    c.key.clear();
    c.trace_id.clear();
    c.trace_inbound = false;
    c.have_deferred = false;
    c.accepts_binary = false;

    // trace identity: honor a well-formed inbound X-Misaka-Trace
    // unconditionally (inbound IDs skip sampling, like tracespan.begin);
    // mint for a sampled share of the rest.  The capture plane also
    // needs the inbound check (its sampling bypass) even with tracing
    // disabled.
    if (st->trace_enabled || st->capture_enabled) {
        const std::string inbound = c.req.get_str("x-misaka-trace");
        bool ok = inbound.size() >= 4 && inbound.size() <= 64;
        for (const char ch : inbound) {
            if (!((ch >= '0' && ch <= '9') || (ch >= 'a' && ch <= 'z') ||
                  (ch >= 'A' && ch <= 'Z') || ch == '-')) {
                ok = false;
                break;
            }
        }
        if (ok && !inbound.empty()) {
            c.trace_id = inbound;
            c.trace_inbound = true;
        } else if (st->trace_enabled && st->trace_sample > 0.0) {
            rng ^= rng >> 12;
            rng ^= rng << 25;
            rng ^= rng >> 27;
            const double u =
                (double)(rng * 0x2545F4914F6CDD1Dull >> 11) * 0x1.0p-53;
            if (u < st->trace_sample) c.trace_id = mint_trace();
        }
    }

    if (c.req.method != "GET" && c.req.method != "POST") {
        reply_text(slot, 501, "unsupported method", {});
        c.close_after = true;
        return;
    }

    if (c.req.expect_continue && c.req.method == "POST") {
        c.wbuf += "HTTP/1.1 100 Continue\r\n\r\n";
    }

    if (c.req.method == "GET") {
        if (c.req.path == "/healthz") {
            c.d_parse = mono_now() - c.t_parse;
            reply(slot, 200, st->healthz_ctype.c_str(), st->healthz_body,
                  {}, true);
            if (!c.trace_id.empty()) {
                record_span("frontend.request", c.t_start,
                            mono_now() - c.t_start, c.trace_id);
            }
            return;  // stays in Head state; process() continues
        }
        start_proxy(slot, std::string());
        return;
    }

    // ---- POST ----
    std::string op;
    std::string prog_name;
    const bool program_route = match_program_route(c.req.path, prog_name, op);
    std::string route = c.req.path;
    if (program_route) {
        c.program = prog_name;
        route = "/" + op;
    } else {
        c.program = c.req.get_str("x-misaka-program");
    }
    // key: X-Misaka-Key wins, else a Bearer Authorization
    c.key = c.req.get_str("x-misaka-key");
    if (c.key.empty()) {
        const std::string auth = c.req.get_str("authorization");
        if (auth.compare(0, 7, "Bearer ") == 0) {
            std::string k = auth.substr(7);
            while (!k.empty() && (k.front() == ' ' || k.front() == '\t')) {
                k.erase(k.begin());
            }
            while (!k.empty() && (k.back() == ' ' || k.back() == '\t')) {
                k.pop_back();
            }
            c.key = k;
        }
    }

    const bool hot = route == "/compute_raw" || route == "/compute" ||
                     route == "/compute_batch";
    if (!hot) {
        start_proxy_post(slot);
        return;
    }

    // /compute_batch: terminate only the coalesced default lane the plane
    // already implements; the spread lane and cold (unpushed) programs
    // proxy to the CPython tier unchanged
    if (route == "/compute_batch" &&
        !c.program.empty() && st->programs.count(c.program) == 0) {
        start_proxy_post(slot);
        return;
    }
    // the raw spread escape hatch keeps the CPython semantics
    if (route == "/compute_raw" &&
        c.req.target.find("spread=0") != std::string::npos) {
        start_proxy_post(slot);
        return;
    }

    // shed cache: replay a recent engine-side 429 without shipping
    std::string shed_key = c.program;
    shed_key.push_back('\0');
    shed_key += c.key;
    auto sit = shed.find(shed_key);
    if (sit != shed.end()) {
        const double now = mono_now();
        if (sit->second.until > now) {
            eng->stats.shed_hits.fetch_add(1, std::memory_order_relaxed);
            shed_row(sit->second.tenant, sit->second.has_tenant,
                     sit->second.reason.c_str());
            record_capture(*st, c, 429, sit->second.reason.c_str());
            c.have_deferred = true;
            c.deferred_status = 429;
            c.deferred_body = sit->second.message;
            c.deferred_extras = {
                {"Retry-After", retry_after_header(sit->second.until - now)}};
            // drain_or_close: consume a small body, else close on it
            if (c.req.has_content_length && !c.req.bad_content_length &&
                c.req.content_length <= 65536) {
                c.st = CState::Body;
                c.disp = Dispatch::Discard;
                c.body_need = c.req.content_length;
            } else {
                reply_text(slot, 429, c.deferred_body, c.deferred_extras);
                c.have_deferred = false;
                c.close_after = true;
            }
            return;
        }
        shed.erase(sit);
    }

    // plane-depth admission guard (the CPython tier's _edge_guard)
    if (eng->cfg.plane_depth_max > 0 && depth() >= eng->cfg.plane_depth_max) {
        eng->stats.overload.fetch_add(1, std::memory_order_relaxed);
        shed_row(std::string(), false, "overload");
        record_capture(*st, c, 429, "overload");
        char msg[160];
        std::snprintf(msg, sizeof(msg),
                      "frontend overloaded: %d plane frames queued (cap %d); "
                      "retry after backoff",
                      depth(), eng->cfg.plane_depth_max);
        reply_text(slot, 429, msg, {{"Retry-After", "1"}});
        c.close_after = true;
        return;
    }

    if (route == "/compute_raw") {
        c.accepts_binary = msk::wire_accepts_binary(c.req.get_str("accept"));
        // oversized-for-the-plane bodies proxy; the engine's own cap
        // answers the canonical 413
        if (c.req.has_content_length && !c.req.bad_content_length &&
            c.req.content_length > eng->cfg.plane_body_limit) {
            start_proxy_post(slot);
            return;
        }
        if (!c.req.has_content_length) {
            reply_text(slot, 411, "Content-Length required", {});
            c.close_after = true;
            return;
        }
        if (c.req.bad_content_length) {
            reply_text(slot, 400, "cannot parse Content-Length", {});
            c.close_after = true;
            return;
        }
        if (c.req.content_length > eng->cfg.max_body) {
            char msg[160];
            std::snprintf(msg, sizeof(msg),
                          "body of %lld bytes exceeds the %lld-byte cap "
                          "(MISAKA_MAX_BODY)",
                          (long long)c.req.content_length,
                          (long long)eng->cfg.max_body);
            reply_text(slot, 413, msg, {});
            c.close_after = true;
            return;
        }
        c.st = CState::Body;
        c.disp = Dispatch::Raw;
        c.body_need = c.req.content_length;
        return;
    }

    // /compute and /compute_batch: form bodies, Content-Length optional
    // (an absent length is an empty form, the _read_body(required=False)
    // contract)
    if (c.req.has_content_length && c.req.bad_content_length) {
        reply_text(slot, 400, "cannot parse Content-Length", {});
        c.close_after = true;
        return;
    }
    if (c.req.has_content_length &&
        c.req.content_length > eng->cfg.max_body) {
        char msg[160];
        std::snprintf(msg, sizeof(msg),
                      "body of %lld bytes exceeds the %lld-byte cap "
                      "(MISAKA_MAX_BODY)",
                      (long long)c.req.content_length,
                      (long long)eng->cfg.max_body);
        reply_text(slot, 413, msg, {});
        c.close_after = true;
        return;
    }
    c.st = CState::Body;
    c.disp = route == "/compute" ? Dispatch::Compute : Dispatch::Batch;
    c.body_need = c.req.has_content_length ? c.req.content_length : 0;
}

void Worker::dispatch_body(uint32_t slot, std::string&& body) {
    Conn& c = *slots[slot];
    auto st = eng->load_state();
    c.st = CState::Head;
    const Dispatch disp = c.disp;
    c.disp = Dispatch::None;

    if (disp == Dispatch::Discard) {
        // drained a shed-cache hit's body; answer the held reply
        reply_text(slot, c.deferred_status, c.deferred_body,
                   c.deferred_extras);
        c.have_deferred = false;
        return;
    }
    if (disp == Dispatch::Proxy) {
        start_proxy(slot, body);
        return;
    }

    c.d_parse = mono_now() - c.t_parse;

    // local fast-401 against the pushed digest table (same decision, and
    // same staleness window, as the engine's KeyFile); billed through the
    // frame-metadata shed rows of the next shipped frame
    auto local_401 = [&](const std::string& msg) {
        eng->stats.local_401.fetch_add(1, std::memory_order_relaxed);
        shed_row(std::string(), false, "unauthenticated");
        record_capture(*st, c, 401, "unauthenticated");
        reply_text(slot, 401, msg,
                   {{"WWW-Authenticate", kWwwAuth}});
    };
    const bool key_known =
        !st->auth_armed ||
        (!c.key.empty() &&
         st->digests.count(msk::api_key_digest_hex(c.key)) != 0);

    if (disp == Dispatch::Raw) {
        const uint8_t* payload = (const uint8_t*)body.data();
        size_t payload_len = body.size();
        if (msk::wire_is_binary(c.req.get_str("content-type"))) {
            std::string werr;
            if (!msk::wire_unpack((const uint8_t*)body.data(), body.size(),
                                  &payload, &payload_len, werr)) {
                reply_text(slot, 400, "bad binary body: " + werr, {});
                return;
            }
        } else if (body.size() % 4 != 0) {
            reply_text(slot, 400, "body must be raw int32 values", {});
            return;
        }
        if (st->auth_armed && c.key.empty()) {
            local_401(st->missing_msg);
            return;
        }
        if (st->auth_armed && !key_known) {
            local_401(st->unknown_msg);
            return;
        }
        // single-request burst 413 for keys whose own spec pins vps —
        // the engine would reject this frame identically; answering here
        // skips shipping a doomed megabyte
        if (st->auth_armed && !c.key.empty()) {
            auto bit = st->bursts.find(msk::api_key_digest_hex(c.key));
            if (bit != st->bursts.end() &&
                (double)(payload_len / 4) > bit->second.cap) {
                eng->stats.local_413.fetch_add(1, std::memory_order_relaxed);
                shed_row(bit->second.tenant, true, "values");
                record_capture(*st, c, 413, "values");
                char head[48];
                std::snprintf(head, sizeof(head), "request of %zu",
                              payload_len / 4);
                reply_text(slot, 413, head + bit->second.msg_mid, {});
                return;
            }
        }
        ship_frame(slot, Dispatch::Raw,
                   std::string((const char*)payload, payload_len));
        return;
    }

    if (disp == Dispatch::Compute) {
        std::map<std::string, std::string> form;
        msk::form_decode(body.data(), body.size(), form);
        const auto vit = form.find("value");
        bool ok = vit != form.end() && !vit->second.empty();
        int64_t value = 0;
        if (ok) {
            const char* s = vit->second.c_str();
            char* endp = nullptr;
            errno = 0;
            value = std::strtoll(s, &endp, 10);
            while (endp != nullptr && (*endp == ' ' || *endp == '\t')) endp++;
            ok = endp != nullptr && *endp == '\0' && errno == 0 &&
                 value >= INT32_MIN && value <= INT32_MAX;
        }
        if (!ok) {
            reply_text(slot, 400, "cannot parse value", {});
            return;
        }
        if (st->auth_armed && c.key.empty()) {
            local_401(st->missing_msg);
            return;
        }
        if (st->auth_armed && !key_known) {
            local_401(st->unknown_msg);
            return;
        }
        const int32_t v32 = (int32_t)value;
        ship_frame(slot, Dispatch::Compute,
                   std::string((const char*)&v32, 4));
        return;
    }

    // Dispatch::Batch — terminate only the coalesced lane (spread=1);
    // everything else keeps the CPython tier's exact semantics via proxy
    std::map<std::string, std::string> form;
    msk::form_decode(body.data(), body.size(), form);
    const auto spread = form.find("spread");
    if (spread == form.end() || spread->second != "1") {
        start_proxy(slot, body);
        return;
    }
    if (st->auth_armed && c.key.empty()) {
        local_401(st->missing_msg);
        return;
    }
    if (st->auth_armed && !key_known) {
        local_401(st->unknown_msg);
        return;
    }
    const auto vals = form.find("values");
    std::vector<int32_t> values;
    if (vals == form.end() ||
        !msk::parse_i32(vals->second.data(), vals->second.size(), values)) {
        reply_text(slot, 400, "cannot parse values", {});
        return;
    }
    ship_frame(slot, Dispatch::Batch,
               std::string((const char*)values.data(), values.size() * 4));
}

}  // namespace

// ---------------------------------------------------------------------------
// Worker: compute plane client
// ---------------------------------------------------------------------------

namespace {

bool Worker::ensure_plane(size_t i, double now) {
    PlaneConn& pc = planes[i];
    if (pc.fd >= 0) return true;
    if (now < pc.reconnect_at) return false;
    const int fd = socket(AF_UNIX, SOCK_STREAM | SOCK_NONBLOCK | SOCK_CLOEXEC,
                          0);
    if (fd < 0) return false;
    struct sockaddr_un addr;
    std::memset(&addr, 0, sizeof(addr));
    addr.sun_family = AF_UNIX;
    std::snprintf(addr.sun_path, sizeof(addr.sun_path), "%s",
                  eng->cfg.plane_path.c_str());
    if (connect(fd, (struct sockaddr*)&addr, sizeof(addr)) < 0 &&
        errno != EINPROGRESS) {
        close(fd);
        pc.reconnect_at = now + 0.05;
        return false;
    }
    pc.fd = fd;
    pc.events = EPOLLIN;
    pc.wbuf = eng->cfg.handshake;  // 32 secret bytes, or empty
    pc.woff = 0;
    pc.rbuf.clear();
    ep_add(ep, fd, (K_PLANE << 48) | (uint64_t)i, EPOLLIN);
    flush_plane(i);
    return pc.fd >= 0;
}

void Worker::flush_plane(size_t i) {
    PlaneConn& pc = planes[i];
    while (pc.woff < pc.wbuf.size()) {
        const ssize_t n = send(pc.fd, pc.wbuf.data() + pc.woff,
                               pc.wbuf.size() - pc.woff, MSG_NOSIGNAL);
        if (n > 0) {
            pc.woff += (size_t)n;
            continue;
        }
        if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) break;
        if (n < 0 && errno == ENOTCONN) break;  // connect still in flight
        plane_fail_all(i, strerror(errno));
        return;
    }
    if (pc.woff >= pc.wbuf.size()) {
        pc.wbuf.clear();
        pc.woff = 0;
    }
    const uint32_t want =
        EPOLLIN | (pc.wbuf.empty() ? 0u : (uint32_t)EPOLLOUT);
    if (want != pc.events) {
        ep_mod(ep, pc.fd, (K_PLANE << 48) | (uint64_t)i, want);
        pc.events = want;
    }
}

void Worker::ship_frame(uint32_t slot, Dispatch kind,
                        const std::string& payload) {
    Conn& c = *slots[slot];
    auto st = eng->load_state();
    const double now = mono_now();

    // least-loaded live plane connection
    int best = -1;
    for (size_t i = 0; i < planes.size(); i++) {
        if (!ensure_plane(i, now)) continue;
        if (best < 0 || planes[i].pending.size() <
                            planes[(size_t)best].pending.size()) {
            best = (int)i;
        }
    }
    if (best < 0) {
        eng->stats.plane_errors.fetch_add(1, std::memory_order_relaxed);
        reply_text(slot, 502, "compute plane error: unavailable", {});
        return;
    }
    PlaneConn& pc = planes[(size_t)best];

    // frame metadata: the exact object PlaneClient ships — program, the
    // forwarded trace segment, the API key, SLO edge timestamps, and any
    // locally-billed shed rows
    std::string meta = "{\"program\": ";
    if (c.program.empty()) {
        meta += "null";
    } else {
        msk::json_append_str(meta, c.program);
    }
    meta += ", \"traces\": [";
    if (!c.trace_id.empty() && st->trace_enabled) {
        meta += "{\"id\": ";
        msk::json_append_str(meta, c.trace_id);
        if (c.trace_inbound) {
            // the client presented this ID: the engine-side capture
            // recorder bypasses sampling for it
            meta += ", \"in\": 1";
        }
        char sp[192];
        std::snprintf(sp, sizeof(sp),
                      ", \"spans\": [[\"http.parse\", %.9f, %.9f], "
                      "[\"frontend.edge\", %.9f, %.9f]]}",
                      c.t_parse, c.d_parse, c.t_start, now - c.t_start);
        meta += sp;
    }
    meta += "]";
    if (!c.key.empty()) {
        meta += ", \"key\": ";
        msk::json_append_str(meta, c.key);
    }
    if (st->slo_armed) {
        char eb[48];
        std::snprintf(eb, sizeof(eb), ", \"edge\": [%.6f]", c.t_start);
        meta += eb;
    }
    if (!shed_rows.empty()) {
        meta += ", \"shed\": [";
        bool first = true;
        for (const auto& kv : shed_rows) {
            const size_t nul = kv.first.find('\0');
            const std::string tenant = kv.first.substr(0, nul);
            const std::string reason = kv.first.substr(nul + 1);
            if (!first) meta += ", ";
            first = false;
            meta += "[";
            if (tenant == "\x01") {
                meta += "null";
            } else {
                msk::json_append_str(meta, tenant);
            }
            meta += ", ";
            msk::json_append_str(meta, reason);
            char nb[32];
            std::snprintf(nb, sizeof(nb), ", %llu]",
                          (unsigned long long)kv.second);
            meta += nb;
        }
        meta += "]";
        shed_rows.clear();
    }
    meta += "}";

    uint8_t hdr[msk::kPlaneReqHeaderLen];
    msk::plane_req_header((uint32_t)(payload.size() / 4),
                          (uint32_t)meta.size(), hdr);
    pc.wbuf.append((const char*)hdr, sizeof(hdr));
    pc.wbuf += payload;
    pc.wbuf += meta;

    PlanePending p;
    p.slot = slot;
    p.gen = c.gen;
    p.kind = kind;
    p.accepts_binary = c.accepts_binary;
    p.deadline = now + eng->cfg.plane_timeout;
    p.t_ship = now;
    p.t_req_start = c.t_start;
    p.trace_id = c.trace_id;
    p.shed_program = c.program;
    p.shed_key = c.key;
    pc.pending.push_back(std::move(p));
    eng->plane_depth.fetch_add(1, std::memory_order_relaxed);
    eng->stats.plane_shipped.fetch_add(1, std::memory_order_relaxed);

    c.st = CState::Wait;
    // flush may fail the connection and re-enter this conn via
    // plane_fail_all -> finish_request; re-check the slot after
    flush_plane((size_t)best);
    if (slot < slots.size() && slots[slot]) update_events(slot);
}

void Worker::on_plane_io(size_t i, uint32_t evmask) {
    PlaneConn& pc = planes[i];
    if (pc.fd < 0) return;
    if (evmask & (EPOLLHUP | EPOLLERR)) {
        plane_fail_all(i, "connection reset");
        return;
    }
    if (evmask & EPOLLOUT) flush_plane(i);
    if (pc.fd < 0 || !(evmask & EPOLLIN)) return;
    char buf[65536];
    while (true) {
        const ssize_t n = recv(pc.fd, buf, sizeof(buf), 0);
        if (n > 0) {
            pc.rbuf.append(buf, (size_t)n);
            if (n < (ssize_t)sizeof(buf)) break;
            continue;
        }
        if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) break;
        plane_fail_all(i, n == 0 ? "connection closed" : strerror(errno));
        return;
    }
    size_t off = 0;
    while (pc.rbuf.size() - off >= msk::kPlaneRespHeaderLen) {
        int32_t status;
        uint32_t length;
        msk::plane_resp_header((const uint8_t*)pc.rbuf.data() + off, &status,
                               &length);
        const size_t body_len =
            status == 200 ? (size_t)length * 4 : (size_t)length;
        if (pc.rbuf.size() - off < msk::kPlaneRespHeaderLen + body_len) break;
        if (pc.pending.empty()) {
            plane_fail_all(i, "unsolicited plane frame");
            return;
        }
        PlanePending p = std::move(pc.pending.front());
        pc.pending.pop_front();
        complete_pending(p, status,
                         pc.rbuf.data() + off + msk::kPlaneRespHeaderLen,
                         body_len);
        off += msk::kPlaneRespHeaderLen + body_len;
    }
    if (off > 0) pc.rbuf.erase(0, off);
}

void Worker::plane_fail_all(size_t i, const char* why) {
    PlaneConn& pc = planes[i];
    if (pc.fd >= 0) {
        close(pc.fd);
        pc.fd = -1;
    }
    pc.wbuf.clear();
    pc.woff = 0;
    pc.rbuf.clear();
    pc.reconnect_at = mono_now() + 0.05;
    std::deque<PlanePending> pend;
    pend.swap(pc.pending);
    const std::string msg = std::string("compute plane error: ") + why;
    for (auto& p : pend) {
        if (p.zombie) continue;
        eng->plane_depth.fetch_sub(1, std::memory_order_relaxed);
        eng->stats.plane_errors.fetch_add(1, std::memory_order_relaxed);
        Conn* c = conn_at(p.slot, p.gen);
        if (c == nullptr) continue;
        reply_text(p.slot, 502, msg, {});
        finish_request(p.slot);
    }
}

void Worker::complete_pending(PlanePending& p, int status, const char* body,
                              size_t body_len) {
    const double now = mono_now();
    if (!p.zombie) {
        eng->plane_depth.fetch_sub(1, std::memory_order_relaxed);
    }
    if (!p.trace_id.empty()) {
        record_span("frontend.plane.ship", p.t_ship, now - p.t_ship,
                    p.trace_id);
    }
    Conn* c = conn_at(p.slot, p.gen);
    if (p.zombie || c == nullptr) return;  // late frame; FIFO already synced

    if (status == 200) {
        if (p.kind == Dispatch::Raw) {
            if (p.accepts_binary) {
                std::string out((size_t)msk::kWireHeaderLen + body_len, '\0');
                msk::wire_header((uint32_t)(body_len / 4), (uint8_t*)&out[0]);
                std::memcpy(&out[msk::kWireHeaderLen], body, body_len);
                reply(p.slot, 200, msk::kWireContentType, out, {}, true);
            } else {
                reply(p.slot, 200, "application/octet-stream",
                      std::string(body, body_len), {}, true);
            }
        } else if (p.kind == Dispatch::Compute) {
            int32_t v = 0;
            if (body_len >= 4) std::memcpy(&v, body, 4);
            char out[48];
            const int n = std::snprintf(out, sizeof(out),
                                        "{\"value\": %d}\n", v);
            reply(p.slot, 200, "application/json",
                  std::string(out, (size_t)n), {}, true);
        } else {
            std::string out = "{\"values\": [";
            msk::fmt_i32((const int32_t*)body, body_len / 4, ',', out);
            out += "]}\n";
            reply(p.slot, 200, "application/json", out, {}, true);
        }
    } else {
        // single-engine drain mapping: PlaneClient turns 599 into a 503
        // with the body preserved
        if (status == msk::kPlaneDraining) status = 503;
        plane_error_reply(p.slot, p, status, std::string(body, body_len));
    }
    if (!p.trace_id.empty()) {
        record_span("frontend.request", p.t_req_start, now - p.t_req_start,
                    p.trace_id);
    }
    finish_request(p.slot);
}

// The CPython tier's _plane_error: an EdgeReject-shaped JSON body renders
// as its message with the typed headers (and arms the shed cache on a
// 429 with Retry-After); anything else passes through verbatim.
void Worker::plane_error_reply(uint32_t slot, const PlanePending& p,
                               int status, const std::string& body) {
    JsonValue obj;
    std::string message;
    std::string tenant;
    bool has_tenant = false;
    std::string reason;
    double retry_after = -1.0;
    bool edge_shaped = false;
    if (msk::json_parse(body.data(), body.size(), obj) &&
        obj.kind == JsonValue::Object && obj.get("reason") != nullptr &&
        obj.get("reason")->kind == JsonValue::String) {
        edge_shaped = true;
        reason = obj.get_str("reason");
        message = obj.get_str("error");
        const JsonValue* ra = obj.get("retry_after");
        if (ra != nullptr && ra->kind == JsonValue::Number) {
            retry_after = ra->number;
        }
        const JsonValue* tv = obj.get("tenant");
        if (tv != nullptr && tv->kind == JsonValue::String) {
            tenant = tv->str;
            has_tenant = true;
        }
    }
    if (!edge_shaped) {
        reply_text(slot, status, body, {});
        return;
    }
    std::vector<std::pair<std::string, std::string>> extras;
    if (retry_after >= 0.0) {
        extras.emplace_back("Retry-After", retry_after_header(retry_after));
    }
    if (status == 401) {
        extras.emplace_back("WWW-Authenticate", kWwwAuth);
    }
    if (status == 429 && retry_after >= 0.0) {
        const double hold =
            retry_after < 0.25 ? 0.25 : (retry_after > 30.0 ? 30.0
                                                            : retry_after);
        std::string sk = p.shed_program;
        sk.push_back('\0');
        sk += p.shed_key;
        ShedEntry e;
        e.until = mono_now() + hold;
        e.message = message;
        e.tenant = tenant;
        e.has_tenant = has_tenant;
        e.reason = reason.empty() ? "rate" : reason;
        shed[sk] = std::move(e);
    }
    reply_text(slot, status, message, std::move(extras));
}

}  // namespace

// ---------------------------------------------------------------------------
// Worker: proxy lane to the CPython worker tier
// ---------------------------------------------------------------------------

namespace {

// headers the CPython tier forwards upstream / copies back downstream
const char* const kForwardHeaders[] = {"content-type", "x-misaka-program",
                                       "x-misaka-key", "authorization",
                                       "x-misaka-trace"};
const char* const kForwardNames[] = {"Content-Type", "X-Misaka-Program",
                                     "X-Misaka-Key", "Authorization",
                                     "X-Misaka-Trace"};
const char* const kCopyBack[] = {"x-misaka-trace", "server-timing",
                                 "deprecation", "link", "retry-after",
                                 "www-authenticate"};
const char* const kCopyBackNames[] = {"X-Misaka-Trace", "Server-Timing",
                                      "Deprecation", "Link", "Retry-After",
                                      "WWW-Authenticate"};

void Worker::start_proxy(uint32_t slot, const std::string& body) {
    Conn& c = *slots[slot];
    eng->stats.proxied.fetch_add(1, std::memory_order_relaxed);
    std::string req = c.req.method + " " + c.req.target + " HTTP/1.1\r\n";
    req += "Host: " + eng->cfg.proxy_host + "\r\n";
    for (size_t i = 0; i < sizeof(kForwardHeaders) / sizeof(char*); i++) {
        const std::string* v = c.req.get(kForwardHeaders[i]);
        if (v != nullptr && !v->empty()) {
            req += std::string(kForwardNames[i]) + ": " + *v + "\r\n";
        }
    }
    char clbuf[48];
    std::snprintf(clbuf, sizeof(clbuf), "Content-Length: %zu\r\n",
                  body.size());
    if (c.req.method == "POST") req += clbuf;
    req += "\r\n";
    req += body;
    c.up_req = std::move(req);
    c.up_woff = 0;
    c.up_rbuf.clear();
    c.up_body_need = -1;
    c.up_head_end = 0;
    c.up_attempts = 0;
    c.st = CState::Wait;
    update_events(slot);
    up_send(slot);
}

// A POST that proxies must carry its body: read it first with the same
// _read_body(required=False) limits the CPython tier applies, then hand
// the bytes to start_proxy.
void Worker::start_proxy_post(uint32_t slot) {
    Conn& c = *slots[slot];
    if (!c.req.has_content_length) {
        start_proxy(slot, std::string());
        return;
    }
    if (c.req.bad_content_length) {
        reply_text(slot, 400, "cannot parse Content-Length", {});
        c.close_after = true;
        return;
    }
    // beyond the engine cap the canonical 413 closes without reading;
    // answer it here so an unbounded body cannot park in our buffers
    const int64_t hard_cap =
        eng->cfg.max_body > eng->cfg.plane_body_limit
            ? eng->cfg.max_body
            : eng->cfg.plane_body_limit;
    if (c.req.content_length > hard_cap) {
        char msg[160];
        std::snprintf(msg, sizeof(msg),
                      "body of %lld bytes exceeds the %lld-byte cap "
                      "(MISAKA_MAX_BODY)",
                      (long long)c.req.content_length,
                      (long long)eng->cfg.max_body);
        reply_text(slot, 413, msg, {});
        c.close_after = true;
        return;
    }
    c.st = CState::Body;
    c.disp = Dispatch::Proxy;
    c.body_need = c.req.content_length;
}

void Worker::close_up(Conn& c) {
    if (c.upfd >= 0) {
        close(c.upfd);
        c.upfd = -1;
    }
    c.up_reused = false;
    c.up_connecting = false;
}

bool Worker::up_connect(uint32_t slot) {
    Conn& c = *slots[slot];
    const int fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK | SOCK_CLOEXEC,
                          0);
    if (fd < 0) return false;
    struct sockaddr_in addr;
    std::memset(&addr, 0, sizeof(addr));
    addr.sin_family = AF_INET;
    addr.sin_port = htons((uint16_t)eng->cfg.proxy_port);
    if (inet_pton(AF_INET, eng->cfg.proxy_host.c_str(), &addr.sin_addr) != 1) {
        close(fd);
        return false;
    }
    if (connect(fd, (struct sockaddr*)&addr, sizeof(addr)) < 0 &&
        errno != EINPROGRESS) {
        close(fd);
        return false;
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    c.upfd = fd;
    c.up_reused = false;
    c.up_connecting = true;
    ep_add(ep, fd, (K_UP << 48) | slot, EPOLLIN | EPOLLOUT);
    return true;
}

void Worker::up_send(uint32_t slot) {
    Conn& c = *slots[slot];
    c.up_attempts++;
    if (c.upfd < 0 && !up_connect(slot)) {
        up_fail(slot, strerror(errno));
        return;
    }
    if (c.up_connecting) return;  // EPOLLOUT completes the connect
    while (c.up_woff < c.up_req.size()) {
        const ssize_t n = send(c.upfd, c.up_req.data() + c.up_woff,
                               c.up_req.size() - c.up_woff, MSG_NOSIGNAL);
        if (n > 0) {
            c.up_woff += (size_t)n;
            continue;
        }
        if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) return;
        // a stale kept-alive upstream: retry once on a fresh socket
        if (c.up_reused && c.up_rbuf.empty() && c.up_attempts <= 2) {
            close_up(c);
            c.up_woff = 0;
            up_send(slot);
            return;
        }
        up_fail(slot, strerror(errno));
        return;
    }
}

void Worker::up_fail(uint32_t slot, const char* why) {
    Conn& c = *slots[slot];
    close_up(c);
    reply_text(slot, 502, std::string("engine unreachable: ") + why, {});
    finish_request(slot);
}

void Worker::on_up_io(uint32_t slot, uint32_t evmask) {
    if (slot >= slots.size() || !slots[slot]) return;
    Conn& c = *slots[slot];
    if (c.upfd < 0) return;
    if (c.up_connecting && (evmask & (EPOLLOUT | EPOLLHUP | EPOLLERR))) {
        int soerr = 0;
        socklen_t len = sizeof(soerr);
        getsockopt(c.upfd, SOL_SOCKET, SO_ERROR, &soerr, &len);
        if (soerr != 0) {
            close_up(c);
            if (c.up_attempts <= 1) {
                up_send(slot);  // one fresh retry
            } else {
                up_fail(slot, strerror(soerr));
            }
            return;
        }
        c.up_connecting = false;
        ep_mod(ep, c.upfd, (K_UP << 48) | slot, EPOLLIN);
        up_send(slot);
        if (!slots[slot] || c.upfd < 0) return;
    } else if (evmask & EPOLLOUT) {
        up_send(slot);
        if (!slots[slot] || c.upfd < 0) return;
    }
    if (!(evmask & (EPOLLIN | EPOLLHUP | EPOLLERR))) return;
    if (c.st != CState::Wait) return;
    char buf[65536];
    bool eof = false;
    while (true) {
        const ssize_t n = recv(c.upfd, buf, sizeof(buf), 0);
        if (n > 0) {
            c.up_rbuf.append(buf, (size_t)n);
            if (n < (ssize_t)sizeof(buf)) break;
            continue;
        }
        if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) break;
        eof = true;
        break;
    }
    // parse the upstream head once it is complete
    if (c.up_body_need == -1) {
        const size_t pos = c.up_rbuf.find("\r\n\r\n");
        if (pos != std::string::npos) {
            c.up_head_end = pos + 4;
            int64_t clen = -1;
            bool up_close = false;
            size_t ls = c.up_rbuf.find("\r\n") + 2;
            while (ls < pos + 2) {
                size_t le = c.up_rbuf.find("\r\n", ls);
                if (le == std::string::npos || le > pos) le = pos;
                const size_t colon = c.up_rbuf.find(':', ls);
                if (colon != std::string::npos && colon < le) {
                    std::string name = c.up_rbuf.substr(ls, colon - ls);
                    for (char& ch : name) {
                        if (ch >= 'A' && ch <= 'Z') ch = (char)(ch + 32);
                    }
                    size_t vs = colon + 1;
                    while (vs < le && c.up_rbuf[vs] == ' ') vs++;
                    const std::string val = c.up_rbuf.substr(vs, le - vs);
                    if (name == "content-length") {
                        clen = atoll(val.c_str());
                    } else if (name == "connection") {
                        up_close = val.find("close") != std::string::npos;
                    }
                }
                ls = le + 2;
            }
            if (clen >= 0) {
                c.up_body_need = clen;
            } else {
                c.up_body_need = up_close ? -2 : 0;
            }
        }
    }
    if (c.up_body_need >= 0 &&
        c.up_rbuf.size() >= c.up_head_end + (size_t)c.up_body_need) {
        up_deliver(slot);
        return;
    }
    if (eof) {
        if (c.up_body_need == -2) {
            up_deliver(slot);
            return;
        }
        // died before/through the head: stale-retry once, else 502
        if (c.up_reused && c.up_body_need == -1 && c.up_attempts <= 2) {
            close_up(c);
            c.up_woff = 0;
            c.up_rbuf.clear();
            up_send(slot);
            return;
        }
        up_fail(slot, "connection closed before response");
    }
}

void Worker::up_deliver(uint32_t slot) {
    Conn& c = *slots[slot];
    // status
    int status = 502;
    if (c.up_rbuf.size() > 12 && c.up_rbuf.compare(0, 5, "HTTP/") == 0) {
        status = atoi(c.up_rbuf.c_str() + 9);
    }
    // headers we copy back + Content-Type
    std::vector<std::pair<std::string, std::string>> extras;
    std::string ctype;
    bool had_trace_hdr = false;
    bool up_close = false;
    size_t ls = c.up_rbuf.find("\r\n") + 2;
    const size_t pos = c.up_head_end - 4;
    while (ls < pos + 2) {
        size_t le = c.up_rbuf.find("\r\n", ls);
        if (le == std::string::npos || le > pos) le = pos;
        const size_t colon = c.up_rbuf.find(':', ls);
        if (colon != std::string::npos && colon < le) {
            std::string name = c.up_rbuf.substr(ls, colon - ls);
            for (char& ch : name) {
                if (ch >= 'A' && ch <= 'Z') ch = (char)(ch + 32);
            }
            size_t vs = colon + 1;
            while (vs < le && c.up_rbuf[vs] == ' ') vs++;
            const std::string val = c.up_rbuf.substr(vs, le - vs);
            if (name == "content-type") {
                ctype = val;
            } else if (name == "connection") {
                up_close = val.find("close") != std::string::npos;
            } else {
                for (size_t i = 0; i < sizeof(kCopyBack) / sizeof(char*);
                     i++) {
                    if (name == kCopyBack[i]) {
                        extras.emplace_back(kCopyBackNames[i], val);
                        if (i == 0) had_trace_hdr = true;
                    }
                }
            }
        }
        ls = le + 2;
    }
    std::string rbody =
        c.up_body_need >= 0
            ? c.up_rbuf.substr(c.up_head_end, (size_t)c.up_body_need)
            : c.up_rbuf.substr(c.up_head_end);
    if (up_close || c.up_body_need == -2) {
        close_up(c);
    } else {
        c.up_rbuf.clear();
        c.up_reused = true;
    }
    if (!c.trace_id.empty()) {
        record_span("frontend.proxy", c.t_start, mono_now() - c.t_start,
                    c.trace_id);
    }
    reply(slot, status, ctype.empty() ? nullptr : ctype.c_str(), rbody,
          std::move(extras), !had_trace_hdr);
    finish_request(slot);
}

}  // namespace

// ---------------------------------------------------------------------------
// C API (ctypes surface for NativeFrontendSupervisor)
// ---------------------------------------------------------------------------

namespace {

bool parse_config(const char* json, Config& cfg, std::string& err) {
    JsonValue v;
    if (json == nullptr || !msk::json_parse(json, std::strlen(json), v) ||
        v.kind != JsonValue::Object) {
        err = "config must be a JSON object";
        return false;
    }
    cfg.port = (int)v.get_num("port", 0);
    cfg.threads = (int)v.get_num("threads", 2);
    cfg.max_conns = (int)v.get_num("max_conns", 1024);
    cfg.plane_conns = (int)v.get_num("plane_conns", 2);
    cfg.plane_depth_max = (int)v.get_num("plane_depth_max", 256);
    cfg.proxy_port = (int)v.get_num("proxy_port", 0);
    cfg.max_body = (int64_t)v.get_num("max_body", (double)(8 << 20));
    cfg.plane_body_limit =
        (int64_t)v.get_num("plane_body_limit", (double)(2 << 20));
    cfg.plane_timeout = v.get_num("plane_timeout_s", 30.0);
    cfg.plane_path = v.get_str("plane_path");
    cfg.proxy_host = v.get_str("proxy_host", "127.0.0.1");
    if (cfg.threads < 1) cfg.threads = 1;
    if (cfg.threads > 64) cfg.threads = 64;
    if (cfg.plane_conns < 1) cfg.plane_conns = 1;
    const std::string hs = v.get_str("handshake_hex");
    if (!hs.empty()) {
        if (hs.size() % 2 != 0) {
            err = "handshake_hex must be an even-length hex string";
            return false;
        }
        for (size_t i = 0; i < hs.size(); i += 2) {
            auto hexv = [](char ch) -> int {
                if (ch >= '0' && ch <= '9') return ch - '0';
                if (ch >= 'a' && ch <= 'f') return ch - 'a' + 10;
                if (ch >= 'A' && ch <= 'F') return ch - 'A' + 10;
                return -1;
            };
            const int hi = hexv(hs[i]), lo = hexv(hs[i + 1]);
            if (hi < 0 || lo < 0) {
                err = "handshake_hex must be hex";
                return false;
            }
            cfg.handshake.push_back((char)(hi * 16 + lo));
        }
    }
    if (cfg.plane_path.empty()) {
        err = "config requires plane_path";
        return false;
    }
    if (cfg.proxy_port <= 0) {
        err = "config requires proxy_port";
        return false;
    }
    return true;
}

std::shared_ptr<const PushState> parse_push(const char* json,
                                            std::string& err) {
    JsonValue v;
    if (json == nullptr || !msk::json_parse(json, std::strlen(json), v) ||
        v.kind != JsonValue::Object) {
        err = "push state must be a JSON object";
        return nullptr;
    }
    auto st = std::make_shared<PushState>();
    st->auth_armed = v.get_bool("auth_armed", false);
    const JsonValue* digests = v.get("digests");
    if (digests != nullptr && digests->kind == JsonValue::Object) {
        for (const auto& kv : digests->obj) {
            st->digests.insert(kv.first);
            if (kv.second.kind != JsonValue::Object) continue;
            const JsonValue* cap = kv.second.get("burst_cap");
            if (cap != nullptr && cap->kind == JsonValue::Number) {
                BurstQuota q;
                q.cap = cap->number;
                q.msg_mid = kv.second.get_str("burst_msg_mid");
                q.tenant = kv.second.get_str("tenant");
                st->bursts.emplace(kv.first, std::move(q));
            }
        }
    }
    st->missing_msg = v.get_str(
        "reject_missing",
        "API key required (X-Misaka-Key header or Authorization: "
        "Bearer <key>)");
    st->unknown_msg = v.get_str("reject_unknown", "unknown API key");
    const std::string hb = v.get_str("healthz_body");
    if (!hb.empty()) st->healthz_body = hb;
    const std::string hc = v.get_str("healthz_ctype");
    if (!hc.empty()) st->healthz_ctype = hc;
    const JsonValue* progs = v.get("programs");
    if (progs != nullptr && progs->kind == JsonValue::Array) {
        for (const auto& p : progs->arr) {
            if (p.kind == JsonValue::String) st->programs.insert(p.str);
        }
    }
    st->trace_enabled = v.get_bool("trace_enabled", false);
    st->trace_sample = v.get_num("trace_sample", 1.0);
    st->slo_armed = v.get_bool("slo_armed", false);
    st->capture_enabled = v.get_bool("capture_enabled", false);
    st->capture_sample = v.get_num("capture_sample", 1.0);
    return st;
}

}  // namespace

extern "C" {

const char* msk_edge_last_error() { return g_last_error.c_str(); }

int msk_edge_start(const char* config_json) {
    std::lock_guard<std::mutex> g(g_api_mu);
    if (g_engine != nullptr) {
        g_last_error = "native edge already running";
        return -1;
    }
    Config cfg;
    if (!parse_config(config_json, cfg, g_last_error)) return -1;

    std::vector<int> listeners;
    int actual_port = cfg.port;
    for (int i = 0; i < cfg.threads; i++) {
        const int fd = socket(AF_INET,
                              SOCK_STREAM | SOCK_NONBLOCK | SOCK_CLOEXEC, 0);
        if (fd < 0) {
            g_last_error = std::string("socket: ") + strerror(errno);
            for (int lfd : listeners) close(lfd);
            return -1;
        }
        int one = 1;
        setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
        setsockopt(fd, SOL_SOCKET, SO_REUSEPORT, &one, sizeof(one));
        struct sockaddr_in addr;
        std::memset(&addr, 0, sizeof(addr));
        addr.sin_family = AF_INET;
        addr.sin_addr.s_addr = htonl(INADDR_ANY);
        addr.sin_port = htons((uint16_t)actual_port);
        if (bind(fd, (struct sockaddr*)&addr, sizeof(addr)) < 0 ||
            listen(fd, 1024) < 0) {
            g_last_error = std::string("bind/listen: ") + strerror(errno);
            close(fd);
            for (int lfd : listeners) close(lfd);
            return -1;
        }
        if (actual_port == 0) {
            struct sockaddr_in got;
            socklen_t len = sizeof(got);
            getsockname(fd, (struct sockaddr*)&got, &len);
            actual_port = (int)ntohs(got.sin_port);
        }
        listeners.push_back(fd);
    }

    Engine* eng = new Engine();
    eng->cfg = cfg;
    eng->listeners = listeners;
    eng->actual_port = actual_port;
    eng->workers.resize((size_t)cfg.threads);
    for (int i = 0; i < cfg.threads; i++) {
        Worker& w = eng->workers[(size_t)i];
        w.eng = eng;
        w.idx = i;
        w.listen_fd = listeners[(size_t)i];
        w.wake_fd = eventfd(0, EFD_CLOEXEC | EFD_NONBLOCK);
    }
    g_engine = eng;
    for (int i = 0; i < cfg.threads; i++) {
        eng->threads.emplace_back([eng, i] { eng->workers[(size_t)i].run(); });
    }
    return 0;
}

int msk_edge_port() {
    std::lock_guard<std::mutex> g(g_api_mu);
    return g_engine != nullptr ? g_engine->actual_port : -1;
}

int msk_edge_push_state(const char* json) {
    std::lock_guard<std::mutex> g(g_api_mu);
    if (g_engine == nullptr) {
        g_last_error = "native edge not running";
        return -1;
    }
    auto st = parse_push(json, g_last_error);
    if (st == nullptr) return -1;
    std::lock_guard<std::mutex> sg(g_engine->state_mu);
    g_engine->state = st;
    return 0;
}

int64_t msk_edge_stats(char* out, int64_t cap) {
    std::lock_guard<std::mutex> g(g_api_mu);
    if (g_engine == nullptr || out == nullptr) return -1;
    const Stats& s = g_engine->stats;
    char buf[640];
    const int n = std::snprintf(
        buf, sizeof(buf),
        "{\"port\": %d, \"threads\": %d, \"conns_open\": %llu, "
        "\"conns_total\": %llu, \"requests\": %llu, \"plane\": %llu, "
        "\"proxied\": %llu, \"plane_errors\": %llu, \"local_401\": %llu, "
        "\"local_413\": %llu, \"shed_hits\": %llu, \"overload\": %llu, "
        "\"depth\": %d}",
        g_engine->actual_port, g_engine->cfg.threads,
        (unsigned long long)s.conns_open.load(),
        (unsigned long long)s.conns_total.load(),
        (unsigned long long)s.requests.load(),
        (unsigned long long)s.plane_shipped.load(),
        (unsigned long long)s.proxied.load(),
        (unsigned long long)s.plane_errors.load(),
        (unsigned long long)s.local_401.load(),
        (unsigned long long)s.local_413.load(),
        (unsigned long long)s.shed_hits.load(),
        (unsigned long long)s.overload.load(),
        g_engine->plane_depth.load());
    if (n < 0 || n >= (int)cap) return -1;
    std::memcpy(out, buf, (size_t)n + 1);
    return n;
}

int64_t msk_edge_spans(char* out, int64_t cap) {
    std::lock_guard<std::mutex> g(g_api_mu);
    if (g_engine == nullptr || out == nullptr) return -1;
    std::deque<SpanRec> drained;
    {
        std::lock_guard<std::mutex> sg(g_engine->span_mu);
        drained.swap(g_engine->spans);
    }
    std::string js = "[";
    for (const auto& sp : drained) {
        if (js.size() > 1) js += ", ";
        js += "{\"name\": ";
        msk::json_append_str(js, sp.name);
        js += ", \"lane\": ";
        msk::json_append_str(js, sp.lane);
        js += ", \"trace\": ";
        msk::json_append_str(js, sp.trace);
        char nb[80];
        std::snprintf(nb, sizeof(nb), ", \"start\": %.9f, \"dur\": %.9f}",
                      sp.start, sp.dur);
        js += nb;
    }
    js += "]";
    if ((int64_t)js.size() + 1 > cap) return -1;
    std::memcpy(out, js.data(), js.size() + 1);
    return (int64_t)js.size();
}

int64_t msk_edge_captures(char* out, int64_t cap) {
    std::lock_guard<std::mutex> g(g_api_mu);
    if (g_engine == nullptr || out == nullptr) return -1;
    std::deque<CaptureRec> drained;
    uint64_t dropped = 0;
    {
        std::lock_guard<std::mutex> cg(g_engine->cap_mu);
        drained.swap(g_engine->caps);
        dropped = g_engine->caps_dropped;
        g_engine->caps_dropped = 0;
    }
    char db[48];
    std::snprintf(db, sizeof(db), "{\"dropped\": %llu, \"records\": [",
                  (unsigned long long)dropped);
    std::string js = db;
    bool first = true;
    for (const auto& r : drained) {
        if (!first) js += ", ";
        first = false;
        char tb[48];
        std::snprintf(tb, sizeof(tb), "{\"t\": %.6f, \"program\": ", r.t);
        js += tb;
        if (r.program.empty()) {
            js += "null";
        } else {
            msk::json_append_str(js, r.program);
        }
        js += ", \"trace\": ";
        if (r.trace.empty()) {
            js += "null";
        } else {
            msk::json_append_str(js, r.trace);
        }
        char sb[48];
        std::snprintf(sb, sizeof(sb), ", \"in\": %d, \"status\": %d",
                      r.trace.empty() ? 0 : 1, r.status);
        js += sb;
        js += ", \"reason\": ";
        msk::json_append_str(js, r.reason);
        js += "}";
    }
    js += "]}";
    if ((int64_t)js.size() + 1 > cap) return -1;
    std::memcpy(out, js.data(), js.size() + 1);
    return (int64_t)js.size();
}

void msk_edge_stop() {
    std::lock_guard<std::mutex> g(g_api_mu);
    if (g_engine == nullptr) return;
    g_engine->stopping.store(true);
    for (auto& w : g_engine->workers) {
        const uint64_t one = 1;
        ssize_t r = write(w.wake_fd, &one, 8);
        (void)r;
    }
    for (auto& t : g_engine->threads) t.join();
    // fd teardown strictly AFTER the join: a worker may still be
    // registering its listener with epoll (fast stop after start) or
    // draining the wake eventfd — closing under its feet is a race onto
    // a recyclable fd number.  The wake write above pops epoll_wait, so
    // the early listener close bought no shutdown latency anyway.
    for (const int fd : g_engine->listeners) close(fd);
    for (const auto& w : g_engine->workers) close(w.wake_fd);
    delete g_engine;
    g_engine = nullptr;
}

}  // extern "C"

// Identity tag for utils/nativelib.py's content-hash staleness check; the
// build injects -DMISAKA_SRC_HASH=<sha256[:16] of the three source units>.
#ifndef MISAKA_SRC_HASH
#define MISAKA_SRC_HASH "unbuilt"
#endif
extern "C" const char misaka_frontend_src_hash[] =
    "MISAKA-SRC-HASH:" MISAKA_SRC_HASH;
