// msk_frame: shared codec unit for the native serving edge (ISSUE 16).
//
// Everything the C++ frontend tier needs to speak the repo's existing
// binary contracts without CPython in the loop:
//
//  * MSK1 client wire (utils/wire.py twin — same header layout, same
//    rejection SENTENCES: the typed-400 bodies are part of the client
//    contract and the parity tests diff them byte-for-byte),
//  * the decimal int32 text codec (textcodec.cpp's fmt/parse logic,
//    inlined here so frontend.so has no cross-.so dependency) for the
//    /compute and /compute_batch text lanes,
//  * SHA-256 + HMAC-SHA256 (API-key digesting: runtime/edge.py._digest
//    is HMAC(b"misaka-api-key-v1", key) — the control plane pushes hex
//    digests, never raw keys, and the edge digests inbound keys to
//    match),
//  * a minimal recursive-descent JSON reader/writer for the plane frame
//    metadata and the control-plane push payloads.
//
// Header-only; include from frontend.cpp only.  No exceptions, no RTTI
// requirements, C++17.

#pragma once

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <utility>
#include <vector>

namespace msk {

// ---------------------------------------------------------------------------
// MSK1 binary wire (utils/wire.py)
// ---------------------------------------------------------------------------

constexpr uint32_t kWireMagic = 0x314B534D;  // b"MSK1" little-endian
constexpr uint16_t kWireVersion = 1;
constexpr size_t kWireHeaderLen = 12;
constexpr const char* kWireContentType = "application/x-misaka-i32";

inline void wire_header(uint32_t count, uint8_t out[kWireHeaderLen]) {
    uint32_t magic = kWireMagic;
    uint16_t ver = kWireVersion, flags = 0;
    std::memcpy(out, &magic, 4);
    std::memcpy(out + 4, &ver, 2);
    std::memcpy(out + 6, &flags, 2);
    std::memcpy(out + 8, &count, 4);
}

// Validate an MSK1 body; on success set *payload/*payload_len to the raw
// int32 bytes and return true.  On failure fill err with the exact
// wire.WireError sentence the CPython tier would raise.
inline bool wire_unpack(const uint8_t* body, size_t len,
                        const uint8_t** payload, size_t* payload_len,
                        std::string& err) {
    char buf[160];
    if (len < kWireHeaderLen) {
        std::snprintf(buf, sizeof(buf),
                      "body of %zu bytes is shorter than the 12-byte header",
                      len);
        err = buf;
        return false;
    }
    uint32_t magic, count;
    uint16_t version;
    std::memcpy(&magic, body, 4);
    std::memcpy(&version, body + 4, 2);
    std::memcpy(&count, body + 8, 4);
    if (magic != kWireMagic) {
        std::snprintf(buf, sizeof(buf),
                      "bad magic 0x%08x (expected 0x%08x)", magic, kWireMagic);
        err = buf;
        return false;
    }
    if (version != kWireVersion) {
        std::snprintf(buf, sizeof(buf), "unsupported protocol version %u",
                      (unsigned)version);
        err = buf;
        return false;
    }
    const size_t n = len - kWireHeaderLen;
    if (n != (uint64_t)count * 4) {
        std::snprintf(buf, sizeof(buf),
                      "header promises %u values but body carries "
                      "%zu payload bytes", count, n);
        err = buf;
        return false;
    }
    *payload = body + kWireHeaderLen;
    *payload_len = n;
    return true;
}

// Content-Type selects the headered binary request form?  Mirrors
// wire.is_binary: split on ';', strip, exact compare.
inline bool wire_is_binary(const std::string& ctype) {
    size_t end = ctype.find(';');
    if (end == std::string::npos) end = ctype.size();
    size_t a = 0;
    while (a < end && (ctype[a] == ' ' || ctype[a] == '\t')) a++;
    while (end > a && (ctype[end - 1] == ' ' || ctype[end - 1] == '\t')) end--;
    return ctype.compare(a, end - a, kWireContentType) == 0;
}

// Accept negotiates the binary response?  Mirrors wire.accepts_binary:
// plain substring containment.
inline bool wire_accepts_binary(const std::string& accept) {
    return accept.find(kWireContentType) != std::string::npos;
}

// ---------------------------------------------------------------------------
// Decimal int32 text codec (textcodec.cpp logic, same output bytes)
// ---------------------------------------------------------------------------

namespace detail {

const char kPairs[] =
    "00010203040506070809101112131415161718192021222324"
    "25262728293031323334353637383940414243444546474849"
    "50515253545556575859606162636465666768697071727374"
    "75767778798081828384858687888990919293949596979899";

inline void write_digits(char* end, uint32_t m, int nd) {
    char* p = end;
    while (nd >= 2) {
        const uint32_t q = m / 100u, r = m - q * 100u;
        p -= 2;
        std::memcpy(p, kPairs + 2 * r, 2);
        m = q;
        nd -= 2;
    }
    if (nd) *--p = (char)('0' + m % 10u);
}

inline bool is_sep(uint8_t c) {
    return c == ' ' || c == ',' || c == '+' || c == '\t' || c == '\n' ||
           c == '\r';
}

inline int ndigits_u32(uint32_t m) {
    if (m < 10u) return 1;
    if (m < 100u) return 2;
    if (m < 1000u) return 3;
    if (m < 10000u) return 4;
    if (m < 100000u) return 5;
    if (m < 1000000u) return 6;
    if (m < 10000000u) return 7;
    if (m < 100000000u) return 8;
    if (m < 1000000000u) return 9;
    return 10;
}

inline uint32_t mag_u32(int32_t x) {
    return x < 0 ? (uint32_t)(-(int64_t)x) : (uint32_t)x;
}

}  // namespace detail

// Format n int32 values joined by `sep` (textcodec fmt, zero_pad=False):
// fixed-width fields of 1 + digits(max |v|), right-aligned, padded with
// the separator itself when it is ' ' or '+' (else ' '), '-' immediately
// left of the top digit, one separator between tokens, no trailer.
inline void fmt_i32(const int32_t* v, size_t n, char sep, std::string& out) {
    if (n == 0) return;
    uint32_t maxmag = 0;
    for (size_t i = 0; i < n; i++) {
        uint32_t m = detail::mag_u32(v[i]);
        if (m > maxmag) maxmag = m;
    }
    const int width = detail::ndigits_u32(maxmag) + 1;
    const char pad = (sep == ' ' || sep == '+') ? sep : ' ';
    const size_t base = out.size();
    out.resize(base + n * (size_t)(width + 1) - 1);
    char* p = &out[base];
    for (size_t i = 0; i < n; i++) {
        const int32_t x = v[i];
        const uint32_t m = detail::mag_u32(x);
        const int nd = detail::ndigits_u32(m);
        for (int j = 0; j < width - nd; j++) p[j] = pad;
        detail::write_digits(p + width, m, nd);
        if (x < 0) p[width - 1 - nd] = '-';
        p += width;
        if (i + 1 < n) *p++ = sep;
    }
}

// Parse separator-joined decimal tokens (textcodec parse).  Returns
// false on malformed / out-of-int32-range input — the caller answers the
// typed 400 the CPython lane would.
inline bool parse_i32(const char* s, size_t len, std::vector<int32_t>& out) {
    size_t i = 0;
    const uint64_t LIM = 1ull << 31;
    while (i < len) {
        uint8_t c = (uint8_t)s[i];
        if (detail::is_sep(c)) {
            i++;
            continue;
        }
        bool neg = false;
        if (c == '-') {
            neg = true;
            i++;
            if (i >= len || s[i] < '0' || s[i] > '9') return false;
        } else if (c < '0' || c > '9') {
            return false;
        }
        uint64_t mag = 0;
        bool big = false;
        while (i < len) {
            c = (uint8_t)s[i];
            if (c >= '0' && c <= '9') {
                if (!big) {
                    mag = mag * 10u + (uint64_t)(c - '0');
                    if (mag > LIM) big = true;
                }
                i++;
            } else if (detail::is_sep(c)) {
                break;
            } else {
                return false;
            }
        }
        if (big || (neg ? mag > LIM : mag > LIM - 1)) return false;
        out.push_back(neg ? (int32_t)(-(int64_t)mag) : (int32_t)mag);
    }
    return true;
}

// ---------------------------------------------------------------------------
// SHA-256 + HMAC-SHA256 (API-key digesting; no OpenSSL dependency)
// ---------------------------------------------------------------------------

struct Sha256 {
    uint32_t h[8];
    uint8_t block[64];
    uint64_t total = 0;
    size_t fill = 0;

    Sha256() {
        static const uint32_t init[8] = {
            0x6a09e667u, 0xbb67ae85u, 0x3c6ef372u, 0xa54ff53au,
            0x510e527fu, 0x9b05688cu, 0x1f83d9abu, 0x5be0cd19u,
        };
        std::memcpy(h, init, sizeof(h));
    }

    static uint32_t rotr(uint32_t x, int n) {
        return (x >> n) | (x << (32 - n));
    }

    void compress(const uint8_t* p) {
        static const uint32_t K[64] = {
            0x428a2f98u, 0x71374491u, 0xb5c0fbcfu, 0xe9b5dba5u, 0x3956c25bu,
            0x59f111f1u, 0x923f82a4u, 0xab1c5ed5u, 0xd807aa98u, 0x12835b01u,
            0x243185beu, 0x550c7dc3u, 0x72be5d74u, 0x80deb1feu, 0x9bdc06a7u,
            0xc19bf174u, 0xe49b69c1u, 0xefbe4786u, 0x0fc19dc6u, 0x240ca1ccu,
            0x2de92c6fu, 0x4a7484aau, 0x5cb0a9dcu, 0x76f988dau, 0x983e5152u,
            0xa831c66du, 0xb00327c8u, 0xbf597fc7u, 0xc6e00bf3u, 0xd5a79147u,
            0x06ca6351u, 0x14292967u, 0x27b70a85u, 0x2e1b2138u, 0x4d2c6dfcu,
            0x53380d13u, 0x650a7354u, 0x766a0abbu, 0x81c2c92eu, 0x92722c85u,
            0xa2bfe8a1u, 0xa81a664bu, 0xc24b8b70u, 0xc76c51a3u, 0xd192e819u,
            0xd6990624u, 0xf40e3585u, 0x106aa070u, 0x19a4c116u, 0x1e376c08u,
            0x2748774cu, 0x34b0bcb5u, 0x391c0cb3u, 0x4ed8aa4au, 0x5b9cca4fu,
            0x682e6ff3u, 0x748f82eeu, 0x78a5636fu, 0x84c87814u, 0x8cc70208u,
            0x90befffau, 0xa4506cebu, 0xbef9a3f7u, 0xc67178f2u,
        };
        uint32_t w[64];
        for (int i = 0; i < 16; i++) {
            w[i] = ((uint32_t)p[4 * i] << 24) | ((uint32_t)p[4 * i + 1] << 16)
                 | ((uint32_t)p[4 * i + 2] << 8) | (uint32_t)p[4 * i + 3];
        }
        for (int i = 16; i < 64; i++) {
            const uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18)
                              ^ (w[i - 15] >> 3);
            const uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19)
                              ^ (w[i - 2] >> 10);
            w[i] = w[i - 16] + s0 + w[i - 7] + s1;
        }
        uint32_t a = h[0], b = h[1], c = h[2], d = h[3];
        uint32_t e = h[4], f = h[5], g = h[6], hh = h[7];
        for (int i = 0; i < 64; i++) {
            const uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
            const uint32_t ch = (e & f) ^ (~e & g);
            const uint32_t t1 = hh + S1 + ch + K[i] + w[i];
            const uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
            const uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
            const uint32_t t2 = S0 + maj;
            hh = g; g = f; f = e; e = d + t1;
            d = c; c = b; b = a; a = t1 + t2;
        }
        h[0] += a; h[1] += b; h[2] += c; h[3] += d;
        h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
    }

    void update(const uint8_t* p, size_t n) {
        total += n;
        while (n) {
            const size_t take = (64 - fill < n) ? 64 - fill : n;
            std::memcpy(block + fill, p, take);
            fill += take;
            p += take;
            n -= take;
            if (fill == 64) {
                compress(block);
                fill = 0;
            }
        }
    }

    void finish(uint8_t out[32]) {
        const uint64_t bits = total * 8;
        const uint8_t one = 0x80;
        update(&one, 1);
        const uint8_t zero = 0;
        while (fill != 56) update(&zero, 1);
        uint8_t lenb[8];
        for (int i = 0; i < 8; i++) lenb[i] = (uint8_t)(bits >> (56 - 8 * i));
        update(lenb, 8);
        for (int i = 0; i < 8; i++) {
            out[4 * i] = (uint8_t)(h[i] >> 24);
            out[4 * i + 1] = (uint8_t)(h[i] >> 16);
            out[4 * i + 2] = (uint8_t)(h[i] >> 8);
            out[4 * i + 3] = (uint8_t)h[i];
        }
    }
};

inline void hmac_sha256(const uint8_t* key, size_t key_len,
                        const uint8_t* msg, size_t msg_len,
                        uint8_t out[32]) {
    uint8_t k[64];
    std::memset(k, 0, sizeof(k));
    if (key_len > 64) {
        Sha256 kh;
        kh.update(key, key_len);
        kh.finish(k);
    } else {
        std::memcpy(k, key, key_len);
    }
    uint8_t ipad[64], opad[64];
    for (int i = 0; i < 64; i++) {
        ipad[i] = k[i] ^ 0x36;
        opad[i] = k[i] ^ 0x5c;
    }
    uint8_t inner[32];
    Sha256 hi;
    hi.update(ipad, 64);
    hi.update(msg, msg_len);
    hi.finish(inner);
    Sha256 ho;
    ho.update(opad, 64);
    ho.update(inner, 32);
    ho.finish(out);
}

// runtime/edge.py._digest(key): HMAC-SHA256(b"misaka-api-key-v1", key),
// rendered as lowercase hex (the push payload carries hex digests).
inline std::string api_key_digest_hex(const std::string& key) {
    static const char tag[] = "misaka-api-key-v1";
    uint8_t mac[32];
    hmac_sha256((const uint8_t*)tag, sizeof(tag) - 1,
                (const uint8_t*)key.data(), key.size(), mac);
    static const char hexd[] = "0123456789abcdef";
    std::string out(64, '0');
    for (int i = 0; i < 32; i++) {
        out[2 * i] = hexd[mac[i] >> 4];
        out[2 * i + 1] = hexd[mac[i] & 0xf];
    }
    return out;
}

// ---------------------------------------------------------------------------
// Minimal JSON (plane metadata + control-plane push payloads)
// ---------------------------------------------------------------------------

struct JsonValue {
    enum Kind { Null, Bool, Number, String, Array, Object } kind = Null;
    bool boolean = false;
    double number = 0.0;
    std::string str;
    std::vector<JsonValue> arr;
    std::vector<std::pair<std::string, JsonValue>> obj;

    const JsonValue* get(const char* key) const {
        for (const auto& kv : obj) {
            if (kv.first == key) return &kv.second;
        }
        return nullptr;
    }
    std::string get_str(const char* key, const char* dflt = "") const {
        const JsonValue* v = get(key);
        return (v && v->kind == String) ? v->str : std::string(dflt);
    }
    double get_num(const char* key, double dflt = 0.0) const {
        const JsonValue* v = get(key);
        return (v && v->kind == Number) ? v->number : dflt;
    }
    bool get_bool(const char* key, bool dflt = false) const {
        const JsonValue* v = get(key);
        if (v == nullptr) return dflt;
        if (v->kind == Bool) return v->boolean;
        if (v->kind == Number) return v->number != 0.0;
        return dflt;
    }
};

namespace detail {

struct JsonParser {
    const char* p;
    const char* end;

    void skip_ws() {
        while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' ||
                           *p == '\r')) {
            p++;
        }
    }

    bool lit(const char* s, size_t n) {
        if ((size_t)(end - p) < n || std::memcmp(p, s, n) != 0) return false;
        p += n;
        return true;
    }

    static void utf8_append(std::string& s, uint32_t cp) {
        if (cp < 0x80) {
            s.push_back((char)cp);
        } else if (cp < 0x800) {
            s.push_back((char)(0xc0 | (cp >> 6)));
            s.push_back((char)(0x80 | (cp & 0x3f)));
        } else if (cp < 0x10000) {
            s.push_back((char)(0xe0 | (cp >> 12)));
            s.push_back((char)(0x80 | ((cp >> 6) & 0x3f)));
            s.push_back((char)(0x80 | (cp & 0x3f)));
        } else {
            s.push_back((char)(0xf0 | (cp >> 18)));
            s.push_back((char)(0x80 | ((cp >> 12) & 0x3f)));
            s.push_back((char)(0x80 | ((cp >> 6) & 0x3f)));
            s.push_back((char)(0x80 | (cp & 0x3f)));
        }
    }

    bool hex4(uint32_t& out) {
        if (end - p < 4) return false;
        out = 0;
        for (int i = 0; i < 4; i++) {
            const char c = *p++;
            out <<= 4;
            if (c >= '0' && c <= '9') out |= (uint32_t)(c - '0');
            else if (c >= 'a' && c <= 'f') out |= (uint32_t)(c - 'a' + 10);
            else if (c >= 'A' && c <= 'F') out |= (uint32_t)(c - 'A' + 10);
            else return false;
        }
        return true;
    }

    bool parse_string(std::string& out) {
        if (p >= end || *p != '"') return false;
        p++;
        while (p < end) {
            const unsigned char c = (unsigned char)*p;
            if (c == '"') {
                p++;
                return true;
            }
            if (c == '\\') {
                p++;
                if (p >= end) return false;
                const char e = *p++;
                switch (e) {
                    case '"': out.push_back('"'); break;
                    case '\\': out.push_back('\\'); break;
                    case '/': out.push_back('/'); break;
                    case 'b': out.push_back('\b'); break;
                    case 'f': out.push_back('\f'); break;
                    case 'n': out.push_back('\n'); break;
                    case 'r': out.push_back('\r'); break;
                    case 't': out.push_back('\t'); break;
                    case 'u': {
                        uint32_t cp;
                        if (!hex4(cp)) return false;
                        if (cp >= 0xd800 && cp <= 0xdbff && end - p >= 6 &&
                            p[0] == '\\' && p[1] == 'u') {
                            p += 2;
                            uint32_t lo;
                            if (!hex4(lo)) return false;
                            if (lo >= 0xdc00 && lo <= 0xdfff) {
                                cp = 0x10000 + ((cp - 0xd800) << 10)
                                   + (lo - 0xdc00);
                            } else {
                                utf8_append(out, cp);
                                cp = lo;
                            }
                        }
                        utf8_append(out, cp);
                        break;
                    }
                    default: return false;
                }
            } else if (c < 0x20) {
                return false;
            } else {
                out.push_back((char)c);
                p++;
            }
        }
        return false;
    }

    bool parse_value(JsonValue& out, int depth) {
        if (depth > 48) return false;
        skip_ws();
        if (p >= end) return false;
        const char c = *p;
        if (c == '{') {
            p++;
            out.kind = JsonValue::Object;
            skip_ws();
            if (p < end && *p == '}') {
                p++;
                return true;
            }
            while (true) {
                skip_ws();
                std::string key;
                if (!parse_string(key)) return false;
                skip_ws();
                if (p >= end || *p++ != ':') return false;
                JsonValue v;
                if (!parse_value(v, depth + 1)) return false;
                out.obj.emplace_back(std::move(key), std::move(v));
                skip_ws();
                if (p >= end) return false;
                if (*p == ',') {
                    p++;
                    continue;
                }
                if (*p == '}') {
                    p++;
                    return true;
                }
                return false;
            }
        }
        if (c == '[') {
            p++;
            out.kind = JsonValue::Array;
            skip_ws();
            if (p < end && *p == ']') {
                p++;
                return true;
            }
            while (true) {
                JsonValue v;
                if (!parse_value(v, depth + 1)) return false;
                out.arr.push_back(std::move(v));
                skip_ws();
                if (p >= end) return false;
                if (*p == ',') {
                    p++;
                    continue;
                }
                if (*p == ']') {
                    p++;
                    return true;
                }
                return false;
            }
        }
        if (c == '"') {
            out.kind = JsonValue::String;
            return parse_string(out.str);
        }
        if (c == 't') {
            out.kind = JsonValue::Bool;
            out.boolean = true;
            return lit("true", 4);
        }
        if (c == 'f') {
            out.kind = JsonValue::Bool;
            out.boolean = false;
            return lit("false", 5);
        }
        if (c == 'n') {
            out.kind = JsonValue::Null;
            return lit("null", 4);
        }
        // number: delegate to strtod over a bounded copy
        const char* start = p;
        while (p < end && (std::strchr("+-.eE", *p) != nullptr ||
                           (*p >= '0' && *p <= '9'))) {
            p++;
        }
        if (p == start || (size_t)(p - start) > 64) return false;
        char buf[72];
        std::memcpy(buf, start, (size_t)(p - start));
        buf[p - start] = '\0';
        char* done = nullptr;
        out.kind = JsonValue::Number;
        out.number = std::strtod(buf, &done);
        return done == buf + (p - start);
    }
};

}  // namespace detail

inline bool json_parse(const char* s, size_t len, JsonValue& out) {
    detail::JsonParser jp{s, s + len};
    if (!jp.parse_value(out, 0)) return false;
    jp.skip_ws();
    return jp.p == jp.end;
}

// Append a JSON string literal (with quotes) escaping like json.dumps.
inline void json_append_str(std::string& out, const std::string& s) {
    out.push_back('"');
    for (const char ch : s) {
        const unsigned char c = (unsigned char)ch;
        switch (c) {
            case '"': out += "\\\""; break;
            case '\\': out += "\\\\"; break;
            case '\b': out += "\\b"; break;
            case '\f': out += "\\f"; break;
            case '\n': out += "\\n"; break;
            case '\r': out += "\\r"; break;
            case '\t': out += "\\t"; break;
            default:
                if (c < 0x20) {
                    char buf[8];
                    std::snprintf(buf, sizeof(buf), "\\u%04x", c);
                    out += buf;
                } else {
                    out.push_back(ch);
                }
        }
    }
    out.push_back('"');
}

// ---------------------------------------------------------------------------
// Plane frame header (runtime/frontends.py _REQ_HDR / _RESP_HDR)
// ---------------------------------------------------------------------------

constexpr size_t kPlaneReqHeaderLen = 8;   // <II  n_values, n_meta_bytes
constexpr size_t kPlaneRespHeaderLen = 8;  // <iI  status, length
constexpr int kPlaneDraining = 599;        // replica drain sentinel status

inline void plane_req_header(uint32_t n_values, uint32_t n_meta,
                             uint8_t out[kPlaneReqHeaderLen]) {
    std::memcpy(out, &n_values, 4);
    std::memcpy(out + 4, &n_meta, 4);
}

inline void plane_resp_header(const uint8_t* p, int32_t* status,
                              uint32_t* length) {
    std::memcpy(status, p, 4);
    std::memcpy(length, p + 4, 4);
}

}  // namespace msk
