// Copy-and-patch JIT stencil library (raw speed phase 4, r21).
//
// Each function below is ONE parameterized tick fragment: the pass-1
// (fetch + phase A + source resolution) or pass-2 (arbitration + commit)
// body of a single baked (lane, pc) instruction, semantically identical
// to the matching arm of group_tick in native/interpreter.cpp (and to the
// switch case core/specialize.py generates for the switch-threaded tier).
// The per-instruction constants — flat replica-plane bases, port/stack
// indices, immediates, pc successors, jump targets — are "holes": each is
// the ADDRESS of an undefined extern symbol (misaka_hole_K), taken as an
// int64 value.  Compiled with `-c -fno-pic -mcmodel=large`, every hole
// reference becomes a `movabs $imm64` carrying an R_X86_64_64 relocation
// against the hole symbol, so core/jit.py can compile this file ONCE
// (content-keyed in the spec cache), read the relocation table straight
// out of the .o, and then splice + patch fragments per (lane, pc) into an
// executable buffer in microseconds — no per-program C++ compile at all.
//
// Self-containment contract: a stencil may not reference ANYTHING outside
// its own section except the holes — no calls, no rodata, no TLS, no
// jump tables (the build forces -fno-jump-tables -fno-stack-protector
// -fno-exceptions).  core/jit.py verifies this: any relocation that is
// not an R_X86_64_64 against a misaka_hole_* symbol rejects the whole
// library and the ladder falls back one rung to the switch-threaded tier.
//
// ABI: MisakaJitCtx below MUST match native/interpreter.cpp's definition
// field-for-field; both sides carry MISAKA_JIT_ABI and the arm call
// rejects a mismatch (falling back one rung, never corrupting).

#include <cstdint>

#define MISAKA_JIT_ABI 1

// Raw pointers into one Group's SoA planes + the in-flight tick's
// scratch (moved[] and the TickIO arrays live on the driver's stack).
// Keep in lockstep with native/interpreter.cpp (MISAKA_JIT_ABI).
struct MisakaJitCtx {
  int64_t* acc;            // [n_lanes * W]
  int64_t* bak;            // [n_lanes * W]
  int32_t* pc;             // [n_lanes * W]
  int32_t* hold_val;       // [n_lanes * W]
  int32_t* retired;        // [n_lanes * W]
  uint8_t* holding;        // [n_lanes * W]
  int32_t* port_val;       // [n_lanes * kPorts * W]
  uint8_t* port_full;      // [n_lanes * kPorts * W]
  int32_t* stack_mem;      // [W][num_stacks][stack_cap]
  int32_t* in_buf;         // [W][in_cap]
  int32_t* in_rd;          // [W]
  int64_t* s_src_val;      // [n_lanes * W]
  uint8_t* s_src_ok;       // [n_lanes * W]
  uint8_t* s_deliv_full;   // [n_lanes * kPorts * W]
  int32_t* s_deliv_val;    // [n_lanes * kPorts * W]
  int32_t* s_begin_top;    // [num_stacks * W]
  uint8_t* s_stack_taken;  // [num_stacks * W]
  uint8_t* s_pushed;       // [num_stacks * W]
  int32_t* s_push_val;     // [num_stacks * W]
  uint8_t* moved;          // [W]
  uint8_t* io_in_avail;    // [W]
  uint8_t* io_out_free;    // [W]
  uint8_t* io_in_taken;    // [W]
  uint8_t* io_out_taken;   // [W]
  int32_t* io_in_win;      // [W]
  int32_t* io_out_value;   // [W]
};

// Parameter holes: undefined symbols whose ADDRESSES are the patch sites.
// Never defined anywhere — the .o is parsed, never linked.
extern "C" char misaka_hole_0, misaka_hole_1, misaka_hole_2, misaka_hole_3,
    misaka_hole_4, misaka_hole_5, misaka_hole_6, misaka_hole_7;

// A hole's int64 value.  NEVER use a hole in a truthiness/nullness test:
// the compiler may fold `&extern_sym != 0` to true.  Holes are only ever
// indices, immediates and pc targets below.
#define P0 ((int64_t)(intptr_t)&misaka_hole_0)
#define P1 ((int64_t)(intptr_t)&misaka_hole_1)
#define P2 ((int64_t)(intptr_t)&misaka_hole_2)
#define P3 ((int64_t)(intptr_t)&misaka_hole_3)
#define P4 ((int64_t)(intptr_t)&misaka_hole_4)

static inline int32_t i32(int64_t v) {
  return (int32_t)(uint32_t)(uint64_t)v;
}

// The shared commit tail (group_tick: moved, pc successor, latch clear,
// wrap-safe retired advance).  `nxt` is already the baked successor.
static inline void tail(MisakaJitCtx* c, uint64_t r, int64_t i,
                        int64_t nxt) {
  c->moved[r] = 1;
  c->pc[i] = (int32_t)nxt;
  c->holding[i] = 0;
  c->retired[i] = i32((int64_t)c->retired[i] + 1);
}

extern "C" {

// --- pass 1: phase A + source resolution (P0 = lane plane base l*W) --------

// reading op, port source: consume a ready port into the hold latch, then
// resolve from the latch.  P1 = (l*kPorts + (src-R0))*W.
void misaka_st1_port(MisakaJitCtx* c, uint64_t r) {
  const int64_t i = P0 + (int64_t)r;
  const int64_t pi = P1 + (int64_t)r;
  if (!c->holding[i] && c->port_full[pi]) {
    c->hold_val[i] = c->port_val[pi];
    c->holding[i] = 1;
    c->port_full[pi] = 0;
    c->moved[r] = 1;
  }
  c->s_src_val[i] = (int64_t)c->hold_val[i];
  c->s_src_ok[i] = (uint8_t)(c->holding[i] != 0);
}

// reading op, immediate source.  P1 = sign-extended immediate.
void misaka_st1_imm(MisakaJitCtx* c, uint64_t r) {
  const int64_t i = P0 + (int64_t)r;
  c->s_src_val[i] = P1;
  c->s_src_ok[i] = 1;
}

// reading op, ACC source.
void misaka_st1_acc(MisakaJitCtx* c, uint64_t r) {
  const int64_t i = P0 + (int64_t)r;
  c->s_src_val[i] = c->acc[i];
  c->s_src_ok[i] = 1;
}

// NIL source / non-reading op: resolved-and-ready with value 0.
void misaka_st1_zero(MisakaJitCtx* c, uint64_t r) {
  const int64_t i = P0 + (int64_t)r;
  c->s_src_val[i] = 0;
  c->s_src_ok[i] = 1;
}

// --- pass 2: arbitration + commit ------------------------------------------
// Every fragment opens with the source-readiness guard (s_src_ok is 1 for
// non-reading ops by pass-1 construction, so the check is universal).

// MOV <src>, <lane>.<port>: P1 = (tgt*kPorts + port)*W, P2 = nxt.
void misaka_st2_mov_net(MisakaJitCtx* c, uint64_t r) {
  const int64_t i = P0 + (int64_t)r;
  if (!c->s_src_ok[i]) return;
  const int64_t pi = P1 + (int64_t)r;
  if (c->port_full[pi] || c->s_deliv_full[pi]) return;
  c->s_deliv_full[pi] = 1;
  c->s_deliv_val[pi] = i32(c->s_src_val[i]);
  tail(c, r, i, P2);
}

// PUSH <src>, <stack>: P1 = tgt*W, P2 = stack_cap, P3 = nxt.
void misaka_st2_push(MisakaJitCtx* c, uint64_t r) {
  const int64_t i = P0 + (int64_t)r;
  if (!c->s_src_ok[i]) return;
  const int64_t si = P1 + (int64_t)r;
  if (c->s_stack_taken[si] || c->s_begin_top[si] >= (int32_t)P2) return;
  c->s_stack_taken[si] = 1;
  c->s_pushed[si] = 1;
  c->s_push_val[si] = i32(c->s_src_val[i]);
  tail(c, r, i, P3);
}

// POP <stack> -> ACC: P1 = tgt*W, P2 = num_stacks*stack_cap (replica
// stride), P3 = tgt*stack_cap (stack offset), P4 = nxt.
void misaka_st2_pop_acc(MisakaJitCtx* c, uint64_t r) {
  const int64_t i = P0 + (int64_t)r;
  if (!c->s_src_ok[i]) return;
  const int64_t si = P1 + (int64_t)r;
  if (c->s_stack_taken[si] || c->s_begin_top[si] <= 0) return;
  c->s_stack_taken[si] = 1;
  c->acc[i] = (int64_t)c->stack_mem[(int64_t)r * P2 + P3 +
                                    (int64_t)c->s_begin_top[si] - 1];
  tail(c, r, i, P4);
}

// POP <stack> -> NIL (a granted pop with the value discarded): P1 = tgt*W,
// P2 = nxt.
void misaka_st2_pop_nil(MisakaJitCtx* c, uint64_t r) {
  const int64_t i = P0 + (int64_t)r;
  if (!c->s_src_ok[i]) return;
  const int64_t si = P1 + (int64_t)r;
  if (c->s_stack_taken[si] || c->s_begin_top[si] <= 0) return;
  c->s_stack_taken[si] = 1;
  tail(c, r, i, P2);
}

// IN -> ACC: P1 = lane index (the arbitration winner tag), P2 = in_cap,
// P3 = nxt.
void misaka_st2_in_acc(MisakaJitCtx* c, uint64_t r) {
  const int64_t i = P0 + (int64_t)r;
  if (!c->s_src_ok[i]) return;
  if (!c->io_in_avail[r] || c->io_in_taken[r]) return;
  c->io_in_taken[r] = 1;
  c->io_in_win[r] = (int32_t)P1;
  c->acc[i] = (int64_t)c->in_buf[(int64_t)r * P2 +
                                 (int64_t)((uint32_t)c->in_rd[r] %
                                           (uint32_t)P2)];
  tail(c, r, i, P3);
}

// IN -> NIL: P1 = lane index, P2 = nxt.
void misaka_st2_in_nil(MisakaJitCtx* c, uint64_t r) {
  const int64_t i = P0 + (int64_t)r;
  if (!c->s_src_ok[i]) return;
  if (!c->io_in_avail[r] || c->io_in_taken[r]) return;
  c->io_in_taken[r] = 1;
  c->io_in_win[r] = (int32_t)P1;
  tail(c, r, i, P2);
}

// OUT <src>: P1 = nxt.
void misaka_st2_out(MisakaJitCtx* c, uint64_t r) {
  const int64_t i = P0 + (int64_t)r;
  if (!c->s_src_ok[i]) return;
  if (!c->io_out_free[r] || c->io_out_taken[r]) return;
  c->io_out_taken[r] = 1;
  c->io_out_value[r] = i32(c->s_src_val[i]);
  tail(c, r, i, P1);
}

// JRO <src>: P1 = this pc, P2 = prog_len - 1 (the clamp bound).
void misaka_st2_jro(MisakaJitCtx* c, uint64_t r) {
  const int64_t i = P0 + (int64_t)r;
  if (!c->s_src_ok[i]) return;
  const int64_t v = c->s_src_val[i];
  const int64_t mx = P2;
  const int64_t t =
      (v >= INT32_MIN && v <= INT32_MAX) ? P1 + v : (v < 0 ? 0 : mx);
  c->moved[r] = 1;
  c->pc[i] = (int32_t)(t < 0 ? 0 : (t > mx ? mx : t));
  c->holding[i] = 0;
  c->retired[i] = i32((int64_t)c->retired[i] + 1);
}

// JMP: P1 = target.
void misaka_st2_jmp(MisakaJitCtx* c, uint64_t r) {
  const int64_t i = P0 + (int64_t)r;
  if (!c->s_src_ok[i]) return;
  tail(c, r, i, P1);
}

// Conditional jumps: P1 = taken target, P2 = nxt.
void misaka_st2_jez(MisakaJitCtx* c, uint64_t r) {
  const int64_t i = P0 + (int64_t)r;
  if (!c->s_src_ok[i]) return;
  tail(c, r, i, c->acc[i] == 0 ? P1 : P2);
}

void misaka_st2_jnz(MisakaJitCtx* c, uint64_t r) {
  const int64_t i = P0 + (int64_t)r;
  if (!c->s_src_ok[i]) return;
  tail(c, r, i, c->acc[i] != 0 ? P1 : P2);
}

void misaka_st2_jgz(MisakaJitCtx* c, uint64_t r) {
  const int64_t i = P0 + (int64_t)r;
  if (!c->s_src_ok[i]) return;
  tail(c, r, i, c->acc[i] > 0 ? P1 : P2);
}

void misaka_st2_jlz(MisakaJitCtx* c, uint64_t r) {
  const int64_t i = P0 + (int64_t)r;
  if (!c->s_src_ok[i]) return;
  tail(c, r, i, c->acc[i] < 0 ? P1 : P2);
}

// MOV <src> -> ACC: P1 = nxt.
void misaka_st2_mov_acc(MisakaJitCtx* c, uint64_t r) {
  const int64_t i = P0 + (int64_t)r;
  if (!c->s_src_ok[i]) return;
  c->acc[i] = c->s_src_val[i];
  tail(c, r, i, P1);
}

// Commit with no register effect (NOP, MOV -> NIL): P1 = nxt.
void misaka_st2_none(MisakaJitCtx* c, uint64_t r) {
  const int64_t i = P0 + (int64_t)r;
  if (!c->s_src_ok[i]) return;
  tail(c, r, i, P1);
}

// ADD/SUB/NEG/SWP/SAV: 64-bit register arithmetic (wrap-safe through
// uint64, wire truncation happens at MOV_NET/OUT/PUSH sites): P1 = nxt.
void misaka_st2_add(MisakaJitCtx* c, uint64_t r) {
  const int64_t i = P0 + (int64_t)r;
  if (!c->s_src_ok[i]) return;
  c->acc[i] = (int64_t)((uint64_t)c->acc[i] + (uint64_t)c->s_src_val[i]);
  tail(c, r, i, P1);
}

void misaka_st2_sub(MisakaJitCtx* c, uint64_t r) {
  const int64_t i = P0 + (int64_t)r;
  if (!c->s_src_ok[i]) return;
  c->acc[i] = (int64_t)((uint64_t)c->acc[i] - (uint64_t)c->s_src_val[i]);
  tail(c, r, i, P1);
}

void misaka_st2_neg(MisakaJitCtx* c, uint64_t r) {
  const int64_t i = P0 + (int64_t)r;
  if (!c->s_src_ok[i]) return;
  c->acc[i] = (int64_t)(0 - (uint64_t)c->acc[i]);
  tail(c, r, i, P1);
}

void misaka_st2_swp(MisakaJitCtx* c, uint64_t r) {
  const int64_t i = P0 + (int64_t)r;
  if (!c->s_src_ok[i]) return;
  const int64_t oa = c->acc[i];
  c->acc[i] = c->bak[i];
  c->bak[i] = oa;
  tail(c, r, i, P1);
}

void misaka_st2_sav(MisakaJitCtx* c, uint64_t r) {
  const int64_t i = P0 + (int64_t)r;
  if (!c->s_src_ok[i]) return;
  c->bak[i] = c->acc[i];
  tail(c, r, i, P1);
}

}  // extern "C"
